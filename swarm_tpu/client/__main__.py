import sys

from swarm_tpu.client.cli import main

sys.exit(main())
