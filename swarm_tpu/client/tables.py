"""Minimal ASCII table renderer (prettytable is not in this image)."""

from __future__ import annotations


class Table:
    def __init__(self, field_names: list[str]):
        self.field_names = [str(f) for f in field_names]
        self.rows: list[list[str]] = []

    def add_row(self, row) -> None:
        self.rows.append([("" if v is None else str(v)) for v in row])

    def __str__(self) -> str:
        widths = [len(f) for f in self.field_names]
        for row in self.rows:
            for i, cell in enumerate(row):
                widths[i] = max(widths[i], len(cell))
        sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        out = [sep]
        out.append(
            "|"
            + "|".join(f" {f:<{w}} " for f, w in zip(self.field_names, widths))
            + "|"
        )
        out.append(sep)
        for row in self.rows:
            out.append(
                "|" + "|".join(f" {c:<{w}} " for c, w in zip(row, widths)) + "|"
            )
        out.append(sep)
        return "\n".join(out)
