"""``swarm`` CLI — the controller of actions performed within the swarm.

Action set and semantics follow the reference client (``client/swarm``):
``scan, workers, scans, jobs, spinup, terminate, recycle, cat, stream,
reset``, plus ``--tail`` live following, ``--autoscale`` pre-spinup with
auto batch-size = lines/(nodes×1.8) (``client/swarm:140-150``), the ECT
estimator in the scans view (``client/swarm:225-246``), and
``--configure`` persistence.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Optional

import requests

from swarm_tpu.client.tables import Table
from swarm_tpu.config import Config
from swarm_tpu.datamodel import parse_job_id
from swarm_tpu.telemetry import emit_event, new_trace_id
from swarm_tpu.telemetry.events import TRACE_HEADER
from swarm_tpu.telemetry.metrics import parse_exposition


class JobClient:
    def __init__(
        self,
        server_url: str,
        api_key: str,
        timeout: float = 60.0,
        tenant: Optional[str] = None,
        qos: Optional[str] = None,
    ):
        self.base = server_url.rstrip("/")
        self.timeout = timeout
        self.session = requests.Session()
        self.session.headers["Authorization"] = f"Bearer {api_key}"
        if tenant:
            # tenant identity rides every request (docs/GATEWAY.md);
            # absent = the server's default tenant, the reference wire
            # behavior
            self.session.headers["X-Swarm-Tenant"] = tenant
        if qos:
            # latency class next to the tenant header (docs/GATEWAY.md
            # §QoS): "interactive" rides the express lane + gateway
            # cache; absent/"bulk" is the reference wire behavior
            self.session.headers["X-Swarm-QoS"] = qos
        #: trace ID of the most recent submission (scan/stream): the
        #: correlation key every layer's event lines carry for it
        self.last_trace_id: Optional[str] = None

    # ------------------------------------------------------------------
    def start_scan(
        self,
        path: str,
        module: str,
        chunk_index: int,
        batch_size,
        scan_id: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> tuple[int, str]:
        with open(path, "r") as f:
            file_content = f.readlines()
        data = {
            "module": module,
            "file_content": file_content,
            "batch_size": int(float(batch_size)),
            "scan_id": scan_id,
            "chunk_index": chunk_index,
        }
        trace_id = trace_id or new_trace_id()
        self.last_trace_id = trace_id
        emit_event(
            "scan.submit",
            trace_id=trace_id,
            module=module,
            lines=len(file_content),
            batch_size=int(float(batch_size)),
        )
        resp = self.session.post(
            f"{self.base}/queue",
            json=data,
            headers={TRACE_HEADER: trace_id},
            timeout=self.timeout,
        )
        return resp.status_code, resp.text

    def get_metrics_text(self) -> Optional[str]:
        resp = self.session.get(f"{self.base}/metrics", timeout=self.timeout)
        return resp.text if resp.status_code == 200 else None

    def get_statuses(self) -> Optional[dict]:
        resp = self.session.get(f"{self.base}/get-statuses", timeout=self.timeout)
        return resp.json() if resp.status_code == 200 else None

    def fetch_raw(self, scan_id: str) -> str:
        resp = self.session.get(f"{self.base}/raw/{scan_id}", timeout=self.timeout)
        if resp.status_code == 200:
            return resp.text
        return f"Error: {resp.status_code} - {resp.text}"

    def get_latest_chunk_raw(self) -> Optional[str]:
        resp = self.session.get(f"{self.base}/get-latest-chunk", timeout=self.timeout)
        if resp.status_code != 200 or not resp.text:
            return None
        scan_id, chunk_id = parse_job_id(resp.text.strip())
        resp2 = self.session.get(
            f"{self.base}/get-chunk/{scan_id}/{chunk_id}", timeout=self.timeout
        )
        if resp2.status_code == 200:
            return resp2.json()["contents"].strip()
        return None

    def tail(self, timeout_polls: int = 36000) -> None:
        """Live-follow completed chunks (reference client/swarm:72-82)."""
        empty_polls = 0
        while empty_polls <= timeout_polls:
            chunk = self.get_latest_chunk_raw()
            if chunk is not None:
                sys.stdout.write(chunk + "\n")
                sys.stdout.flush()
            else:
                empty_polls += 1
                time.sleep(0.05)

    def spin_up(self, prefix: str, nodes: int) -> tuple[int, str]:
        resp = self.session.post(
            f"{self.base}/spin-up",
            json={"prefix": prefix, "nodes": nodes},
            timeout=self.timeout,
        )
        return resp.status_code, resp.text

    def spin_down(self, prefix: str) -> tuple[int, str]:
        resp = self.session.post(
            f"{self.base}/spin-down", json={"prefix": prefix}, timeout=self.timeout
        )
        return resp.status_code, resp.text

    def reset(self) -> tuple[int, str]:
        resp = self.session.post(f"{self.base}/reset", timeout=self.timeout)
        return resp.status_code, resp.text

    def get_healthz(self) -> Optional[dict]:
        resp = self.session.get(f"{self.base}/healthz", timeout=self.timeout)
        return resp.json() if resp.status_code == 200 else None

    def dead_letter_jobs(self) -> Optional[list]:
        resp = self.session.get(f"{self.base}/dead-letter", timeout=self.timeout)
        return resp.json()["jobs"] if resp.status_code == 200 else None

    def get_tenants(self) -> Optional[dict]:
        resp = self.session.get(f"{self.base}/tenants", timeout=self.timeout)
        return resp.json()["tenants"] if resp.status_code == 200 else None

    # ------------------------------------------------------------------
    def stream_results(
        self,
        scan_id: str,
        from_chunk: int = 0,
        max_reconnects: int = 8,
        reconnect_delay_s: float = 0.5,
    ):
        """Follow a scan's results as the server pushes them: yields
        ``(chunk_index, text)`` in chunk order from ``GET /stream/
        <scan_id>`` (NDJSON, docs/GATEWAY.md).

        Resume discipline: the cursor is "last delivered chunk + 1".
        On ANY disconnect — server restart, idle-timeout record, a
        dropped connection — the client reconnects with ``?from=
        <cursor>`` and continues from exactly the last acked chunk;
        the server serves already-stored chunks from the idempotent
        chunk store, so nothing is lost or duplicated. The reconnect
        budget resets on every delivered chunk (progress heals it)."""
        cursor = int(from_chunk)
        failures = 0
        while True:
            ended = saw_timeout = False
            try:
                resp = self.session.get(
                    f"{self.base}/stream/{scan_id}",
                    params={"from": cursor},
                    stream=True,
                    timeout=self.timeout,
                )
                if resp.status_code != 200:
                    raise requests.HTTPError(f"/stream: {resp.status_code}")
                for line in resp.iter_lines():
                    if not line:
                        continue
                    rec = json.loads(line)
                    event = rec.get("event")
                    if event == "end":
                        ended = True
                        break
                    if event == "timeout":
                        saw_timeout = True
                        break  # reconnect from the cursor
                    if event == "skipped":
                        cursor = int(rec["chunk"]) + 1
                        continue
                    if "chunk" in rec and "data" in rec:
                        cursor = int(rec["chunk"]) + 1
                        failures = 0
                        yield rec["chunk"], rec["data"]
            except requests.exceptions.ReadTimeout:
                # inter-record silence past OUR read timeout, on a
                # connection the server accepted: a healthy-but-slow
                # scan, not a failure (the server's own idle record
                # may be minutes away) — reconnect without burning the
                # budget; a truly dead server fails the reconnect with
                # a ConnectionError and burns it there
                time.sleep(reconnect_delay_s)
                continue
            except (requests.RequestException, ValueError, OSError):
                failures += 1
                if failures > max_reconnects:
                    raise
                time.sleep(reconnect_delay_s)
                continue
            if ended:
                return
            if saw_timeout:
                # a HEALTHY server bounding its handler lifetime while
                # the scan is simply slow — follow indefinitely (tail
                # -f semantics); only real disconnects burn the budget
                time.sleep(reconnect_delay_s)
                continue
            # server closed WITHOUT an end/timeout record (restart,
            # dropped connection): that's a failure — never silently
            # truncate a live stream with a clean exit
            failures += 1
            if failures > max_reconnects:
                raise requests.ConnectionError(
                    f"/stream/{scan_id}: disconnected without an end "
                    f"record after {max_reconnects} reconnects "
                    f"(next chunk {cursor})"
                )
            time.sleep(reconnect_delay_s)

    def requeue_job(self, job_id: str) -> tuple[int, str]:
        resp = self.session.post(
            f"{self.base}/requeue-job/{job_id}", timeout=self.timeout
        )
        return resp.status_code, resp.text

    def get_trace(self, scan_id: str) -> Optional[dict]:
        """Assembled per-scan span waterfall (``swarm trace`` —
        docs/OBSERVABILITY.md §Tracing); None = unknown scan, retention
        expired, or tracing was off when the scan ran."""
        resp = self.session.get(
            f"{self.base}/trace/{scan_id}", timeout=self.timeout
        )
        return resp.json() if resp.status_code == 200 else None

    def post_spans(self, scan_id: str, spans: list) -> bool:
        """Attach an out-of-band span batch to an open scan."""
        resp = self.session.post(
            f"{self.base}/spans",
            json={"scan_id": scan_id, "spans": spans},
            timeout=self.timeout,
        )
        return resp.status_code == 200

    # ------------------------------------------------------------------
    def monitor_add(
        self,
        path: str,
        module: str,
        interval_s: float,
        monitor_id: Optional[str] = None,
        batch_size: int = 0,
        paused: bool = False,
    ) -> tuple[int, str]:
        """Register (or upsert) a standing monitor over the targets in
        ``path`` — tenant/QoS ride the session headers exactly like a
        one-shot scan (docs/MONITORING.md)."""
        with open(path, "r") as f:
            targets = f.readlines()
        data = {
            "module": module,
            "targets": targets,
            "interval_s": interval_s,
            "batch_size": int(batch_size),
            "paused": paused,
        }
        if monitor_id:
            data["monitor_id"] = monitor_id
        resp = self.session.post(
            f"{self.base}/monitor", json=data, timeout=self.timeout
        )
        return resp.status_code, resp.text

    def monitor_list(self) -> Optional[list]:
        resp = self.session.get(f"{self.base}/monitor", timeout=self.timeout)
        return resp.json()["monitors"] if resp.status_code == 200 else None

    def monitor_update(self, monitor_id: str, op: str) -> tuple[int, str]:
        """``op`` is rm | pause | resume."""
        resp = self.session.post(
            f"{self.base}/monitor/{monitor_id}",
            json={"op": op},
            timeout=self.timeout,
        )
        return resp.status_code, resp.text

    def monitor_feed(
        self,
        monitor_id: str,
        from_seq: int = 0,
        max_reconnects: int = 8,
        reconnect_delay_s: float = 0.5,
    ):
        """Follow a monitor's change feed: yields diff-record dicts in
        ``seq`` order from ``GET /monitor-feed/<id>`` (NDJSON).

        Same resume discipline as :meth:`stream_results`, with the
        record ``seq`` as the cursor: on any disconnect — server
        restart, idle-timeout record, dropped connection — reconnect
        with ``?from=<last seq + 1>`` and continue from exactly the
        last acked record (the feed store is idempotent, so nothing
        duplicates or drops). Timeout records reconnect for free; the
        budget burns only on real failures and resets on progress."""
        cursor = int(from_seq)
        failures = 0
        while True:
            ended = saw_timeout = False
            try:
                resp = self.session.get(
                    f"{self.base}/monitor-feed/{monitor_id}",
                    params={"from": cursor},
                    stream=True,
                    timeout=self.timeout,
                )
                if resp.status_code != 200:
                    raise requests.HTTPError(
                        f"/monitor-feed: {resp.status_code}"
                    )
                for line in resp.iter_lines():
                    if not line:
                        continue
                    rec = json.loads(line)
                    event = rec.get("event")
                    if event == "end":
                        ended = True
                        break
                    if event == "timeout":
                        saw_timeout = True
                        break  # reconnect from the cursor
                    if "seq" in rec:
                        cursor = int(rec["seq"]) + 1
                        failures = 0
                        yield rec
            except requests.exceptions.ReadTimeout:
                # healthy-but-quiet monitor (see stream_results): the
                # server's own idle record may be minutes away —
                # reconnect without burning the budget
                time.sleep(reconnect_delay_s)
                continue
            except (requests.RequestException, ValueError, OSError):
                failures += 1
                if failures > max_reconnects:
                    raise
                time.sleep(reconnect_delay_s)
                continue
            if ended:
                return
            if saw_timeout:
                time.sleep(reconnect_delay_s)
                continue
            failures += 1
            if failures > max_reconnects:
                raise requests.ConnectionError(
                    f"/monitor-feed/{monitor_id}: disconnected without "
                    f"an end record after {max_reconnects} reconnects "
                    f"(next seq {cursor})"
                )
            time.sleep(reconnect_delay_s)


# ---------------------------------------------------------------------------
# Views
# ---------------------------------------------------------------------------


def estimate_completion_time(scan_started, total_chunks, chunks_complete, completed_at):
    """ECT = remaining% × elapsed/complete% (reference client/swarm:225-246)."""
    if not chunks_complete or not scan_started:
        return None
    now = time.time()
    elapsed = now - scan_started
    frac = chunks_complete / total_chunks
    if elapsed <= 0:
        return None
    if frac >= 1:
        eta = completed_at or now
    else:
        eta = now + (1 - frac) * (elapsed / frac)
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(eta))


def _fmt_ts(ts) -> str:
    if not ts:
        return ""
    return time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime(ts))


def _fmt_age(ts, now=None) -> str:
    if not ts:
        return ""
    age = max(0.0, (now if now is not None else time.time()) - ts)
    if age < 120:
        return f"{age:.1f}s"
    if age < 7200:
        return f"{age / 60:.1f}m"
    return f"{age / 3600:.1f}h"


def render_workers(statuses: dict, health: Optional[dict] = None) -> str:
    """Per-worker fleet readout: state (active / draining / preempted /
    inactive), last-heartbeat age, poll counters — plus the autoscale
    advisor's target vs actual when /healthz carries a recommendation
    (docs/RESILIENCE.md §Preemption)."""
    draining = statuses.get("draining") or {}
    table = Table(
        ["Worker ID", "State", "Heartbeat Age", "Last Contacted",
         "Polls with No Jobs"]
    )
    for worker_id, w in statuses.get("workers", {}).items():
        state = w.get("status") or ""
        reason = draining.get(worker_id)
        if reason:
            state = f"{state} ({reason})"
        table.add_row(
            [worker_id, state, _fmt_age(w.get("last_contact")),
             _fmt_ts(w.get("last_contact")), w.get("polls_with_no_jobs")]
        )
    lines = [str(table)]
    auto = (health or {}).get("autoscale")
    # before the advisor's first control-law tick the status dict
    # carries None fields — nothing worth a line yet
    if auto and auto.get("action") is not None:
        lines.append(
            f"autoscale[{auto.get('prefix')}]: "
            f"target {auto.get('target_nodes')} vs "
            f"actual {auto.get('current_nodes')} nodes "
            f"({auto.get('action')}"
            + (", dry-run" if auto.get("dry_run") else "")
            + f"); queue depth {auto.get('queue_depth')}, "
            f"forecast {auto.get('forecast_jobs')} jobs"
        )
    return "\n".join(lines)


def render_jobs(statuses: dict) -> str:
    table = Table(
        ["Job ID", "Scan ID", "Chunk", "Status", "Worker ID", "Started", "Completed", "Seconds"]
    )
    jobs = sorted(
        statuses.get("jobs", {}).items(), key=lambda kv: int(kv[1].get("chunk_index", 0))
    )
    for job_id, j in jobs:
        started, completed = j.get("started_at"), j.get("completed_at")
        duration = f"{completed - started:.1f}" if started and completed else ""
        table.add_row(
            [job_id, j.get("scan_id"), j.get("chunk_index"), j.get("status"),
             j.get("worker_id"), _fmt_ts(started), _fmt_ts(completed), duration]
        )
    return str(table)


def render_metrics(text: str) -> str:
    """Pretty-print a /metrics exposition body as tables: one row per
    sample, histograms summarized to count/sum/p-ish buckets."""
    samples = parse_exposition(text)
    table = Table(["Metric", "Labels", "Value"])
    for name, labels, value in samples:
        label_str = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
        if isinstance(value, float) and value.is_integer():
            shown = str(int(value))
        else:
            shown = f"{value:.6g}"
        table.add_row([name, label_str, shown])
    return str(table)


def render_dead_letter(jobs: list) -> str:
    """Quarantined jobs with their failure provenance (one line per
    job; the history is compacted to status×count)."""
    table = Table(
        ["Job ID", "Module", "Attempts", "Failure History"]
    )
    for j in jobs:
        history = j.get("failure_history") or []
        counts: dict[str, int] = {}
        for f in history:
            counts[f.get("status", "?")] = counts.get(f.get("status", "?"), 0) + 1
        summary = ", ".join(f"{k} x{v}" for k, v in sorted(counts.items()))
        table.add_row(
            [j.get("job_id"), j.get("module"), j.get("attempts"), summary]
        )
    return str(table)


def render_resilience_summary(health: dict) -> str:
    """One-glance degradation readout (dead-letter depth + breaker
    states) from unauthenticated /healthz — no Prometheus needed."""
    breakers = health.get("breakers") or {}
    not_closed = {k: v for k, v in breakers.items() if v != "closed"}
    lines = [
        f"dead-letter jobs: {health.get('dead_letter_jobs', 0)}",
        "breakers: "
        + (
            ", ".join(f"{k}={v}" for k, v in sorted(not_closed.items()))
            or f"all closed ({len(breakers)} tracked)"
        ),
    ]
    plan = health.get("fault_plan")
    if plan:
        lines.append(f"fault plan ACTIVE: {plan}")
    return "\n".join(lines)


def render_tenants(tenants: dict) -> str:
    """Per-tenant gateway readout: depth, admission outcomes, states
    (`swarm tenants` — docs/GATEWAY.md)."""
    table = Table(
        ["Tenant", "Queue Depth", "Admitted", "Shed", "Jobs by State"]
    )
    for tenant, t in sorted(tenants.items()):
        states = ", ".join(
            f"{s}: {n}" for s, n in sorted((t.get("jobs_by_state") or {}).items())
        )
        table.add_row(
            [tenant, t.get("queue_depth"), t.get("admitted"), t.get("shed"),
             states]
        )
    return str(table)


def render_trace(doc: dict) -> str:
    """One scan's latency waterfall as a parent-linked tree with
    per-segment percentages, plus the critical-path summary
    ("queue-wait 61%, device 22%, upload 9%") —
    docs/OBSERVABILITY.md §Tracing."""
    from swarm_tpu.telemetry import tracing

    root = doc["root"]
    total = root.get("duration_s") or 0.0
    spans = doc.get("spans") or []
    children: dict = {}
    for s in sorted(spans, key=lambda sp: sp.get("start") or 0.0):
        children.setdefault(s.get("parent_id"), []).append(s)

    lines = [
        f"scan {doc.get('scan_id')}  trace {doc.get('trace_id')}  "
        f"status {doc.get('status')}  chunks {doc.get('chunks')}"
        + (f"  qos {doc['qos']}" if doc.get("qos") else ""),
        f"gateway latency {total * 1000:.1f} ms; "
        f"segments sum {doc.get('segments_sum_s', 0.0) * 1000:.1f} ms"
        + (
            f" ({doc.get('segments_sum_s', 0.0) / total * 100:.1f}%)"
            if total > 0 else ""
        ),
    ]
    shown_attrs = (
        "attempt", "qos", "worker_id", "module", "rows", "error", "tenant"
    )

    def walk(span_id, prefix: str) -> None:
        kids = children.get(span_id, [])
        for i, s in enumerate(kids):
            last = i == len(kids) - 1
            dur = s.get("duration_s") or 0.0
            pct = (dur / total * 100.0) if total > 0 else 0.0
            attrs = s.get("attrs") or {}
            extra = " ".join(
                f"{k}={attrs[k]}" for k in shown_attrs if k in attrs
            )
            lines.append(
                f"{prefix}{'└─ ' if last else '├─ '}"
                f"{s.get('name', '?'):<18} {dur * 1000:9.1f} ms {pct:5.1f}%"
                + (f"  {extra}" if extra else "")
            )
            walk(s.get("span_id"), prefix + ("   " if last else "│  "))

    walk(root.get("span_id"), "")
    orphans = tracing.waterfall_orphans(doc)
    if orphans:
        names = ", ".join(sorted({s.get("name", "?") for s in orphans}))
        lines.append(f"! {len(orphans)} orphan span(s) (lost parents): {names}")
    cp = tracing.critical_path(doc)
    if cp:
        lines.append(
            "critical path: "
            + ", ".join(f"{name} {frac * 100.0:.0f}%" for name, _s, frac in cp)
        )
    return "\n".join(lines)


def render_scans(statuses: dict) -> str:
    table = Table(
        ["Scan ID", "Chunks", "Complete", "%", "Workers", "Module", "Monitor",
         "Started", "Completed", "ECT", "Rows/s"]
    )
    for s in statuses.get("scans", []):
        ect = estimate_completion_time(
            s.get("scan_started"), s.get("total_chunks") or 1,
            s.get("chunks_complete") or 0, s.get("completed_at"),
        )
        # monitor provenance: which standing spec fired this scan, and
        # as which epoch (blank for one-shot scans — docs/MONITORING.md)
        mon = (
            f"{s['monitor_id']}@e{s.get('monitor_epoch')}"
            if s.get("monitor_id") else ""
        )
        table.add_row(
            [s.get("scan_id"), s.get("total_chunks"), s.get("chunks_complete"),
             s.get("percent_complete"), len(s.get("workers") or []), s.get("module"),
             mon, _fmt_ts(s.get("scan_started")), _fmt_ts(s.get("completed_at")),
             ect or "", s.get("rows_per_second") or ""]
        )
    return str(table)


def render_monitors(monitors: list) -> str:
    """Standing-spec registry readout (`swarm monitor ls` —
    docs/MONITORING.md)."""
    table = Table(
        ["Monitor ID", "Module", "Targets", "Interval", "Tenant", "QoS",
         "Epoch", "Paused", "Last Scan"]
    )
    for m in monitors:
        table.add_row(
            [m.get("monitor_id"), m.get("module"),
             len(m.get("targets") or []),
             f"{float(m.get('interval_s') or 0):g}s",
             m.get("tenant"), m.get("qos"), m.get("epoch"),
             "yes" if m.get("paused") else "",
             m.get("last_scan_id") or ""]
        )
    return str(table)


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

ACTIONS = [
    "scan", "workers", "scans", "jobs", "metrics", "dead-letter", "tenants",
    "spinup", "terminate", "cat", "stream", "trace", "monitor", "recycle",
    "reset",
]

#: second-level verbs for ``swarm monitor`` (default: ls)
MONITOR_SUBACTIONS = ["add", "rm", "ls", "pause", "resume", "follow"]


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(description="Swarm Scan Client")
    parser.add_argument("action", nargs="?", choices=ACTIONS)
    parser.add_argument("subaction", nargs="?", default=None,
                        help="monitor subverb: " + "|".join(MONITOR_SUBACTIONS))
    parser.add_argument("--server-url", default=None)
    parser.add_argument("--api-key", default=None)
    parser.add_argument("--config", default=None)
    parser.add_argument("--configure", action="store_true",
                        help="persist server URL and API key to the config file")
    parser.add_argument("--file", help="targets file (scan)")
    parser.add_argument("--module", help="scan module name")
    parser.add_argument("--batch-size", default="auto")
    parser.add_argument("--prefix", help="node name prefix (spinup/terminate)")
    parser.add_argument("--nodes", type=int, help="node count (spinup)")
    parser.add_argument("--scan-id", help="scan id (cat/stream/trace)")
    parser.add_argument("--tenant", default=None,
                        help="tenant id sent as X-Swarm-Tenant (gateway)")
    parser.add_argument("--qos", default=None,
                        choices=["bulk", "interactive"],
                        help="latency class sent as X-Swarm-QoS: "
                             "'interactive' rides the express lane with "
                             "deadline-bounded batching (scan/stream)")
    parser.add_argument("--from-chunk", type=int, default=0,
                        help="resume cursor for stream follow mode")
    parser.add_argument("--interval", type=float, default=None,
                        help="rescan cadence in seconds (monitor add)")
    parser.add_argument("--monitor-id", default=None,
                        help="monitor id (monitor add/rm/pause/resume/follow)")
    parser.add_argument("--from-seq", type=int, default=0,
                        help="resume cursor for monitor follow mode")
    parser.add_argument("--job-id", help="job id (dead-letter --requeue)")
    parser.add_argument("--requeue", action="store_true",
                        help="requeue the quarantined --job-id (dead-letter)")
    parser.add_argument("--autoscale", action="store_true")
    parser.add_argument("--tail", action="store_true", help="follow completed chunks")
    args = parser.parse_args(argv)

    cfg = Config.load(path=args.config, server_url=args.server_url, api_key=args.api_key)
    client = JobClient(
        cfg.resolve_url(), cfg.api_key, tenant=args.tenant, qos=args.qos
    )

    if args.configure:
        cfg.save(args.config)
        print(f"Configuration saved")

    try:
        rc = _run_action(args, cfg, client)
    except requests.ConnectionError:
        print(f"Cannot reach server at {cfg.resolve_url()}")
        return 2

    if args.tail:
        client.tail()
    return rc


def _run_action(args, cfg: Config, client: JobClient) -> int:
    if args.action == "scan":
        if not args.file or not args.module:
            print("Both file and module are required for starting a scan")
            return 1
        total_workers = args.nodes or 1
        if args.autoscale:
            if not args.prefix or not args.nodes:
                print("Both prefix and nodes are required for autoscale")
                return 1
            code, text = client.spin_up(args.prefix, args.nodes)
            print(code, text)
        if args.batch_size != "auto":
            batch_size = int(float(args.batch_size))
        else:
            with open(args.file) as f:
                lines = sum(1 for _ in f)
            batch_size = max(1, int(lines / (total_workers * 1.8)))
        code, text = client.start_scan(args.file, args.module, 0, batch_size)
        print(f"Start Scan Status Code: {code}")
        print(f"Start Scan Response: {text}")
        return 0 if code == 200 else 1

    if args.action == "metrics":
        text = client.get_metrics_text()
        if text is None:
            print("Failed to retrieve metrics")
            return 1
        # degradation at a glance (dead-letter depth, breaker states)
        # before the full exposition table — docs/RESILIENCE.md
        health = client.get_healthz()
        if health is not None:
            print(render_resilience_summary(health))
        try:
            print(render_metrics(text))
        except ValueError as e:
            print(f"Malformed metrics exposition: {e}")
            return 1
        return 0

    if args.action == "dead-letter":
        if args.requeue:
            if not args.job_id:
                print("--job-id is required for dead-letter --requeue")
                return 1
            code, text = client.requeue_job(args.job_id)
            print(code, text)
            return 0 if code == 200 else 1
        jobs = client.dead_letter_jobs()
        if jobs is None:
            print("Failed to retrieve dead-letter jobs")
            return 1
        print(f"Dead-letter jobs: {len(jobs)}")
        print(render_dead_letter(jobs))
        return 0

    if args.action in ("workers", "scans", "jobs"):
        statuses = client.get_statuses()
        if statuses is None:
            print("Failed to retrieve statuses")
            return 1
        if args.action == "workers":
            print("Worker Statuses:")
            # the advisor's target-vs-actual line rides the same view;
            # a dead /healthz just drops it (the table still renders)
            try:
                health = client.get_healthz()
            except requests.RequestException:
                health = None
            print(render_workers(statuses, health))
        elif args.action == "jobs":
            print("Job Statuses:")
            print(render_jobs(statuses))
        else:
            print("Scan Information:")
            print(render_scans(statuses))
        return 0

    if args.action == "spinup":
        if not args.prefix or not args.nodes:
            print("Both prefix and nodes are required for spinning up")
            return 1
        code, _text = client.spin_up(args.prefix, args.nodes)
        if code == 202:
            print(f"Successfully issued spinup for prefix {args.prefix}")
            return 0
        return 1

    if args.action == "terminate":
        if not args.prefix:
            print("Prefix is required for spinning down")
            return 1
        code, text = client.spin_down(args.prefix)
        print(code, text)
        return 0 if code == 202 else 1

    if args.action == "recycle":
        if not args.prefix or not args.nodes:
            print("Both prefix and nodes are required for recycle")
            return 1
        print(client.spin_down(args.prefix))
        print("Waiting 10 seconds to spin fleet back up")
        time.sleep(10)
        print(client.spin_up(args.prefix, args.nodes))
        return 0

    if args.action == "tenants":
        tenants = client.get_tenants()
        if tenants is None:
            print("Failed to retrieve tenants")
            return 1
        print(f"Tenants: {len(tenants)}")
        print(render_tenants(tenants))
        return 0

    if args.action == "stream":
        if not args.scan_id:
            print("scan-id is required for stream")
            return 1
        if not args.module:
            # FOLLOW mode (docs/GATEWAY.md): real server-push result
            # streaming over /stream/<scan_id> — chunks print the
            # moment they land, resumable via --from-chunk (the old
            # behavior polled `cat`; submission mode below is the
            # reference's stdin contract and still requires --module)
            for _chunk, text in client.stream_results(
                args.scan_id, from_chunk=args.from_chunk
            ):
                sys.stdout.write(text if text.endswith("\n") else text + "\n")
                sys.stdout.flush()
            return 0
        chunk: list[str] = []
        chunk_index = 0
        batch = 0 if args.batch_size == "auto" else int(float(args.batch_size))
        # one trace for the whole streamed scan: every flushed chunk's
        # jobs correlate under it
        trace_id = new_trace_id()
        client.last_trace_id = trace_id
        emit_event(
            "scan.stream_start", trace_id=trace_id,
            scan_id=args.scan_id, module=args.module,
        )

        def flush(lines: list[str]) -> None:
            nonlocal chunk_index
            chunk_index += 1
            resp = client.session.post(
                f"{client.base}/queue",
                json={
                    "module": args.module,
                    "file_content": lines,
                    "batch_size": batch,
                    "scan_id": args.scan_id,
                    "chunk_index": chunk_index,
                },
                headers={TRACE_HEADER: trace_id},
                timeout=client.timeout,
            )
            print(f"Uploading chunk {chunk_index}: {resp.status_code}")

        for line in sys.stdin:
            chunk.append(line)
            if len(chunk) >= 10:
                flush(chunk)
                chunk = []
                time.sleep(0.3)
        if chunk:  # the reference dropped the trailing partial chunk
            flush(chunk)
        return 0

    if args.action == "cat":
        if not args.scan_id:
            print("scan-id is required for cat")
            return 1
        print(client.fetch_raw(args.scan_id))
        return 0

    if args.action == "trace":
        if not args.scan_id:
            print("scan-id is required for trace")
            return 1
        doc = client.get_trace(args.scan_id)
        if doc is None:
            print(
                f"No trace for scan {args.scan_id} (tracing disabled, "
                "retention expired, or unknown scan)"
            )
            return 1
        print(render_trace(doc))
        return 0

    if args.action == "monitor":
        sub = args.subaction or "ls"
        if sub not in MONITOR_SUBACTIONS:
            print(f"monitor subaction must be one of: "
                  f"{', '.join(MONITOR_SUBACTIONS)}")
            return 1
        if sub == "ls":
            monitors = client.monitor_list()
            if monitors is None:
                print("Failed to retrieve monitors")
                return 1
            print(f"Monitors: {len(monitors)}")
            print(render_monitors(monitors))
            return 0
        if sub == "add":
            if not args.file or not args.module or args.interval is None:
                print("file, module and --interval are required for "
                      "monitor add")
                return 1
            batch = (
                0 if args.batch_size == "auto"
                else int(float(args.batch_size))
            )
            code, text = client.monitor_add(
                args.file, args.module, args.interval,
                monitor_id=args.monitor_id, batch_size=batch,
            )
            print(f"Monitor Add Status Code: {code}")
            print(f"Monitor Add Response: {text}")
            return 0 if code == 200 else 1
        if not args.monitor_id:
            print(f"--monitor-id is required for monitor {sub}")
            return 1
        if sub == "follow":
            for rec in client.monitor_feed(
                args.monitor_id, from_seq=args.from_seq
            ):
                sys.stdout.write(
                    json.dumps(rec, separators=(",", ":")) + "\n"
                )
                sys.stdout.flush()
            return 0
        code, text = client.monitor_update(args.monitor_id, sub)
        print(code, text)
        return 0 if code == 200 else 1

    if args.action == "reset":
        code, text = client.reset()
        print(code, text)
        return 0 if code == 200 else 1

    if args.action is None:
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
