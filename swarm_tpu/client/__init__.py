"""CLI client for the swarm_tpu control plane."""
