"""Deterministic fault-injection harness.

Named fault points are threaded through the stack (transport, stores,
server, scheduler, device dispatch, executor) and driven by a seeded
plan so every failure mode is reproducible on CPU:

    SWARM_FAULT_PLAN="transport.get_job:2,5;device.dispatch:1;executor.run/poison*:*"

Grammar (``;``-separated clauses)::

    clause       := 'seed=' INT | pattern ':' occurrences [':' action]
    pattern      := point-name [ '/' detail ]     (fnmatch wildcards ok)
    occurrences  := '*' | item (',' item)*
    item         := N | N '-' M | 'p' FLOAT       (1-based call index;
                                                   'p0.3' fires with
                                                   probability 0.3 from
                                                   the seeded RNG)
    action       := 'err' | 'err=' MESSAGE | 'sleep=' SECONDS

A clause counts only the calls it *matches* (pattern match against
``name`` or ``name/detail``), so ``transport.put_chunk:1-3`` means "the
first three uploads fail" regardless of unrelated traffic. ``sleep``
delays instead of raising — the lease-expiry chaos lever.

Zero overhead when unset: :func:`fault_point` is one global load and an
``is None`` test (the env var is resolved once, lazily); ``bench.py
--smoke`` records the measured fault-free cost so the claim stays
honest.
"""

from __future__ import annotations

import fnmatch
import os
import random
import threading
import time
from typing import Optional

from swarm_tpu.telemetry import REGISTRY

ENV_VAR = "SWARM_FAULT_PLAN"

_FAULTS_INJECTED = REGISTRY.counter(
    "swarm_resilience_faults_injected_total",
    "Faults fired by the injection harness, by fault point",
    ("point",),
)
_PLAN_ACTIVE = REGISTRY.gauge(
    "swarm_resilience_fault_plan_active",
    "1 while a fault-injection plan is installed in this process",
)


class FaultInjected(RuntimeError):
    """Default exception raised at a firing fault point."""


class _Clause:
    __slots__ = (
        "pattern", "always", "indices", "ranges", "prob", "action",
        "arg", "calls", "seen", "fired",
    )

    def __init__(self, pattern: str, occ: str, action: str):
        self.pattern = pattern
        self.always = occ == "*"
        self.indices: set[int] = set()
        self.ranges: list[tuple[int, int]] = []
        self.prob: Optional[float] = None
        if not self.always:
            for item in occ.split(","):
                item = item.strip()
                if not item:
                    continue
                if item.startswith("p"):
                    self.prob = float(item[1:])
                elif "-" in item:
                    a, b = item.split("-", 1)
                    self.ranges.append((int(a), int(b)))
                else:
                    self.indices.add(int(item))
        self.action, _, arg = action.partition("=")
        self.arg = arg
        self.calls = 0  # matching calls (diagnostics)
        self.seen = 0   # eligible matching calls (occurrence index base)
        self.fired = 0

    def matches(self, name: str, detail: Optional[str]) -> bool:
        if self.pattern == name:
            return True
        full = f"{name}/{detail}" if detail is not None else name
        return fnmatch.fnmatchcase(full, self.pattern) or fnmatch.fnmatchcase(
            name, self.pattern
        )

    def should_fire(self, rng: random.Random, eligible: bool) -> bool:
        """Count this matching call; decide firing only when
        ``eligible`` (no earlier clause already fired for the same
        call). Occurrence indices are matched against the ELIGIBLE
        call count, so an earlier clause's fire never silently
        consumes a later clause's declared occurrence, and
        probabilistic clauses don't burn RNG draws on calls they could
        never win. At most one clause fires per fault-point call."""
        self.calls += 1
        if not eligible:
            return False
        self.seen += 1
        if self.always:
            return True
        if self.seen in self.indices:
            return True
        if any(a <= self.seen <= b for a, b in self.ranges):
            return True
        if self.prob is not None and rng.random() < self.prob:
            return True
        return False


class FaultPlan:
    """A parsed, seeded fault plan. Thread-safe."""

    def __init__(self, spec: str):
        self.spec = spec
        self.seed = 0
        self._clauses: list[_Clause] = []
        self._lock = threading.Lock()  # guards: _rng (reads)
        for raw in spec.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            if raw.startswith("seed="):
                self.seed = int(raw[5:])
                continue
            parts = raw.split(":")
            if len(parts) == 1:
                pattern, occ, action = parts[0], "*", "err"
            elif len(parts) == 2:
                pattern, occ, action = parts[0], parts[1], "err"
            else:
                pattern, occ, action = parts[0], parts[1], ":".join(parts[2:])
            self._clauses.append(_Clause(pattern.strip(), occ.strip(), action.strip()))
        self._rng = random.Random(self.seed)

    # ------------------------------------------------------------------
    def check(self, name: str, detail: Optional[str], exc: Optional[type]) -> None:
        """Evaluate one fault-point call; raises/sleeps when a clause fires."""
        fire: Optional[_Clause] = None
        with self._lock:
            for clause in self._clauses:
                if not clause.matches(name, detail):
                    continue
                if clause.should_fire(self._rng, eligible=fire is None):
                    clause.fired += 1
                    fire = clause
        if fire is None:
            return
        _FAULTS_INJECTED.labels(point=name).inc()
        # flight recorder (docs/OBSERVABILITY.md): every injected fault
        # dumps the last moments of process context. Memory-only +
        # daemon-thread sinks, so safe here outside self._lock and
        # cheap enough for chaos soaks.
        from swarm_tpu.telemetry import tracing

        tracing.flight_dump("fault", detail=name)
        if fire.action == "sleep":
            time.sleep(float(fire.arg or "0"))
            return
        msg = fire.arg or (
            f"injected fault at {name}"
            + (f"/{detail}" if detail is not None else "")
        )
        raise (exc or FaultInjected)(msg)

    def snapshot(self) -> dict:
        """Per-clause counters (matched calls / fired) for assertions."""
        with self._lock:
            return {
                c.pattern: {"calls": c.calls, "fired": c.fired}
                for c in self._clauses
            }


# ---------------------------------------------------------------------------
# Process-wide plan state. ``_UNSET`` means "env not consulted yet": the
# first fault_point call resolves SWARM_FAULT_PLAN exactly once, after
# which the unset fast path is one global load + ``is None``.
# ---------------------------------------------------------------------------

_UNSET = object()
# writes only: the fault_point()/active_plan() fast path reads _active
# lock-free by design (one global load; a stale read costs one extra
# _resolve_env round, never a wrong verdict)
_active = _UNSET  # guarded-by: _state_lock
_state_lock = threading.Lock()


def install_plan(spec: str) -> FaultPlan:
    """Parse and activate a fault plan for this process."""
    global _active
    plan = FaultPlan(spec)
    with _state_lock:
        _active = plan
    _PLAN_ACTIVE.set(1)
    return plan


def clear_plan() -> None:
    """Deactivate fault injection (fault points become no-ops)."""
    global _active
    with _state_lock:
        _active = None
    _PLAN_ACTIVE.set(0)


def active_plan() -> Optional[FaultPlan]:
    plan = _active
    if plan is _UNSET:
        plan = _resolve_env()
    _PLAN_ACTIVE.set(1 if plan is not None else 0)
    return plan


def _resolve_env() -> Optional[FaultPlan]:
    global _active
    with _state_lock:
        if _active is not _UNSET:  # raced with install/clear
            return _active
        spec = os.environ.get(ENV_VAR, "").strip()
        _active = FaultPlan(spec) if spec else None
    if _active is not None:
        _PLAN_ACTIVE.set(1)
    return _active


def fault_point(
    name: str, detail: Optional[str] = None, exc: Optional[type] = None
) -> None:
    """Declare a named fault point. No-op (one global load + ``is
    None`` test) unless a plan is installed; a firing clause raises
    ``exc`` (default :class:`FaultInjected`) or sleeps."""
    plan = _active
    if plan is None:
        return
    if plan is _UNSET:
        plan = _resolve_env()
        if plan is None:
            return
    plan.check(name, detail, exc)
