"""Retrying transport: typed errors, backoff, per-operation breakers.

:class:`TransportError` is the worker↔server contract fix (SURVEY.md
§5): a dead server must be distinguishable from an idle queue.
``ServerClient`` raises it on connection failures and 5xx responses;
"no job" stays a clean ``None``.

:class:`RetryingServerClient` wraps any object with the ``ServerClient``
surface: every operation retries with jittered exponential backoff and
is guarded by its own circuit breaker (per-operation, so a dead
``renew-lease`` path cannot starve ``get-job`` polls). The jitter RNG
is seeded per client, keeping retry schedules reproducible under the
fault harness.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from swarm_tpu.resilience.breaker import BreakerBoard
from swarm_tpu.telemetry import REGISTRY

_RETRIES = REGISTRY.counter(
    "swarm_resilience_transport_retries_total",
    "Transport operations retried after a TransportError",
    ("op",),
)
_FAILURES = REGISTRY.counter(
    "swarm_resilience_transport_failures_total",
    "Transport operations that exhausted retries (or hit an open breaker)",
    ("op",),
)


class TransportError(RuntimeError):
    """Server unreachable or server-side failure (connection error /
    5xx) — NOT "no job available" or a 4xx contract rejection."""


class CircuitOpenError(TransportError):
    """Fast-fail: the operation's circuit breaker is open."""


class RetryingServerClient:
    """Backoff + breaker facade over a ``ServerClient``-shaped inner
    transport. Only :class:`TransportError` is retried — typed 4xx
    outcomes (``None`` / ``False``) pass straight through."""

    #: operations this facade proxies with retry protection
    OPS = (
        "get_job",
        "update_job",
        "get_input_chunk",
        "put_output_chunk",
        "renew_lease",
        "deregister",
    )

    def __init__(
        self,
        inner,
        retries: int = 3,
        backoff_s: float = 0.2,
        backoff_max_s: float = 5.0,
        breaker_threshold: int = 5,
        breaker_cooldown_s: float = 10.0,
        seed: int = 0,
        sleep=time.sleep,
    ):
        self.inner = inner
        self.retries = max(0, int(retries))
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.breakers = BreakerBoard(
            "transport",
            threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s,
        )
        self._rng = random.Random(seed)  # guarded-by: _rng_lock (reads)
        self._rng_lock = threading.Lock()
        self._sleep = sleep

    # ------------------------------------------------------------------
    def _delay(self, attempt: int) -> float:
        base = min(self.backoff_s * (2 ** attempt), self.backoff_max_s)
        with self._rng_lock:
            return base * (0.5 + self._rng.random())  # 0.5x..1.5x jitter

    def _call(self, op: str, *args, **kw):
        breaker = self.breakers.get(op)
        if not breaker.allow():
            _FAILURES.labels(op=op).inc()
            raise CircuitOpenError(f"transport breaker open for {op}")
        fn = getattr(self.inner, op)
        attempt = 0
        while True:
            try:
                out = fn(*args, **kw)
            except TransportError:
                breaker.record_failure()
                if attempt >= self.retries or not breaker.allow():
                    _FAILURES.labels(op=op).inc()
                    raise
                _RETRIES.labels(op=op).inc()
                self._sleep(self._delay(attempt))
                attempt += 1
                continue
            breaker.record_success()
            return out

    # ------------------------------------------------------------------
    def get_job(self, worker_id: str) -> Optional[dict]:
        return self._call("get_job", worker_id)

    def update_job(self, job_id, changes, worker_id=None) -> bool:
        return self._call("update_job", job_id, changes, worker_id=worker_id)

    def get_input_chunk(self, scan_id, chunk_index) -> Optional[bytes]:
        return self._call("get_input_chunk", scan_id, chunk_index)

    def put_output_chunk(self, scan_id, chunk_index, data) -> bool:
        return self._call("put_output_chunk", scan_id, chunk_index, data)

    def renew_lease(self, job_id, worker_id, **kw) -> bool:
        return self._call("renew_lease", job_id, worker_id, **kw)

    def deregister(self, worker_id) -> bool:
        return self._call("deregister", worker_id)

    def __getattr__(self, name):
        # non-op attributes (base, session, timeout, …) proxy through
        return getattr(self.inner, name)
