"""Disk spool for completed output chunks.

When the server is unreachable at upload time the worker has already
paid for the chunk's compute; dropping the bytes wastes the work and
forces a double execution after lease expiry. The spool persists the
finished chunk (payload + completion metadata) and replays it on the
next successful server contact:

- ``put_output_chunk`` is an idempotent overwrite of the same blob key,
  and the completion update carries the worker's fencing token — if the
  lease expired and the job was re-leased elsewhere, the queue rejects
  the stale completion and the entry is dropped (the work was redone by
  the new assignee). Double-replay of the same entry is therefore a
  strict no-op.
- Entries survive worker restarts (files under ``spool_dir``).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Optional

from swarm_tpu.resilience.transport import TransportError
from swarm_tpu.telemetry import REGISTRY, emit_event

_SPOOLED = REGISTRY.counter(
    "swarm_resilience_spooled_chunks_total",
    "Completed output chunks spooled to disk (server unreachable)",
)
_REPLAYED = REGISTRY.counter(
    "swarm_resilience_spool_replayed_total",
    "Spool replay outcomes",
    ("outcome",),
)


class OutputSpool:
    def __init__(self, root: str | Path):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    # ------------------------------------------------------------------
    def put(
        self,
        job_id: str,
        scan_id: str,
        chunk_index: int,
        worker_id: str,
        data: bytes,
        perf: Optional[dict] = None,
    ) -> None:
        """Persist one finished chunk. Data first, then meta — a
        replay only trusts entries whose meta file exists, so a crash
        mid-put leaves no half entry visible."""
        (self.root / f"{job_id}.bin").write_bytes(data)
        meta = {
            "job_id": job_id,
            "scan_id": scan_id,
            "chunk_index": int(chunk_index),
            "worker_id": worker_id,
            "perf": perf,
            "spooled_at": time.time(),
        }
        (self.root / f"{job_id}.json").write_text(json.dumps(meta))
        _SPOOLED.inc()

    def entries(self) -> list[dict]:
        """Spooled entries in (scan_id, chunk_index) order. Chunk-index
        order is load-bearing for replay determinism: a lexical
        filename sort puts ``scan_10`` before ``scan_2``, so two
        replays of the same spool could touch the server in different
        orders — post-restart reconciliation must see one canonical
        sequence per scan (docs/DURABILITY.md)."""
        out = []
        for meta_path in sorted(self.root.glob("*.json")):
            try:
                out.append(json.loads(meta_path.read_text()))
            except (ValueError, OSError):
                continue
        return sorted(
            out,
            key=lambda m: (
                str(m.get("scan_id") or ""),
                int(m.get("chunk_index") or 0),
            ),
        )

    def __len__(self) -> int:
        return len(list(self.root.glob("*.json")))

    def _drop(self, job_id: str) -> None:
        for suffix in (".json", ".bin"):
            try:
                (self.root / f"{job_id}{suffix}").unlink()
            except FileNotFoundError:
                pass

    # ------------------------------------------------------------------
    def replay(self, client, status_complete: str = "complete") -> int:
        """Push every spooled chunk through ``client`` in per-scan
        chunk-index order; returns the number of entries cleared. Stops
        early on TransportError (the server went away again — keep the
        rest for next time). Logs one summary line per scan so a
        post-restart operator can reconcile exactly what the spool
        replayed (docs/DURABILITY.md)."""
        cleared = 0
        per_scan: dict[str, dict[str, list[int]]] = {}
        for meta in self.entries():
            job_id = meta["job_id"]
            data_path = self.root / f"{job_id}.bin"
            try:
                data = data_path.read_bytes()
            except OSError:
                self._drop(job_id)  # orphan meta: nothing to upload
                continue
            try:
                # ownership probe BEFORE touching the blob: renewing the
                # lease succeeds only while the job is still ours — a
                # re-leased/terminal job must not have its stored chunk
                # overwritten with our stale bytes (the new assignee may
                # have produced legitimately different output for
                # nondeterministic modules). A successful renewal also
                # covers the replay window against expiry.
                ok = client.renew_lease(job_id, meta.get("worker_id"))
                if ok:
                    ok = client.put_output_chunk(
                        meta["scan_id"], meta["chunk_index"], data
                    )
                if ok:
                    # fencing token rides along: a re-leased job's queue
                    # record rejects this stale completion (False) and
                    # the entry is dropped — the new assignee owns it
                    changes = {"status": status_complete}
                    if meta.get("perf"):
                        changes["perf"] = meta["perf"]
                    ok = client.update_job(
                        job_id, changes, worker_id=meta.get("worker_id")
                    )
            except TransportError:
                _REPLAYED.labels(outcome="deferred").inc()
                break
            self._drop(job_id)
            cleared += 1
            outcome = "completed" if ok else "fenced"
            _REPLAYED.labels(outcome=outcome).inc()
            per_scan.setdefault(
                str(meta.get("scan_id")), {"completed": [], "fenced": []}
            )[outcome].append(int(meta.get("chunk_index") or 0))
        for scan_id in sorted(per_scan):
            summary = per_scan[scan_id]
            print(
                f"spool replay [{scan_id}]: "
                f"completed chunks {summary['completed']}, "
                f"fenced chunks {summary['fenced']}"
            )
            emit_event(
                "spool.scan_replayed",
                scan_id=scan_id,
                completed=summary["completed"],
                fenced=summary["fenced"],
            )
        return cleared
