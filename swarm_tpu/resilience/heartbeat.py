"""Lease heartbeats: background renewal while a chunk executes.

The queue requeues in-progress jobs whose lease lapsed
(``server/queue.py _requeue_expired``). A long device chunk could
outlive its lease and get double-executed; the heartbeat ticker renews
the lease from a daemon thread (``POST /renew-lease/<job_id>``) for as
long as the chunk runs, and stops the moment the job reaches a terminal
state — or the moment the server says the lease is no longer ours
(renewal of a requeued/re-leased job is rejected, at which point
continuing to execute is wasted work the fencing token will discard).
"""

from __future__ import annotations

import threading
from typing import Optional

from swarm_tpu.resilience.transport import TransportError
from swarm_tpu.telemetry import REGISTRY

_RENEWALS = REGISTRY.counter(
    "swarm_resilience_lease_renewals_total",
    "Lease-heartbeat renewal attempts",
    ("outcome",),
)


class LeaseHeartbeat:
    """Context manager: renew ``job_id``'s lease every ``interval_s``
    until exit (or until the server rejects a renewal)."""

    def __init__(
        self,
        client,
        job_id: str,
        worker_id: str,
        interval_s: float,
        saturation_fn=None,
    ):
        self.client = client
        self.job_id = job_id
        self.worker_id = worker_id
        self.interval_s = max(0.05, float(interval_s))
        #: optional 0..1 in-flight saturation provider: when set (and
        #: returning a value), each renewal carries it so the gateway's
        #: admission pressure sees accelerator saturation BEFORE the
        #: queue backs up (docs/GATEWAY.md). None keeps the original
        #: wire shape — stub clients without the kwarg stay compatible.
        self.saturation_fn = saturation_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: False once the server refused a renewal: the lease is no
        #: longer ours (expired + re-leased, or the job went terminal)
        self.lease_ok = True
        self.renewals = 0

    # ------------------------------------------------------------------
    def _run(self) -> None:
        m = _RENEWALS
        while not self._stop.wait(self.interval_s):
            kw = {}
            if self.saturation_fn is not None:
                try:
                    saturation = self.saturation_fn()
                except Exception:
                    saturation = None
                if saturation is not None:
                    kw["saturation"] = saturation
            try:
                ok = self.client.renew_lease(self.job_id, self.worker_id, **kw)
            except TransportError:
                # server unreachable: keep ticking — the lease may still
                # be live on the server, and the next tick may land
                m.labels(outcome="error").inc()
                continue
            except Exception:
                m.labels(outcome="error").inc()
                continue
            if ok:
                self.renewals += 1
                m.labels(outcome="renewed").inc()
            else:
                self.lease_ok = False
                m.labels(outcome="rejected").inc()
                return  # not ours anymore; stop renewing

    def start(self) -> "LeaseHeartbeat":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run,
                name=f"lease-hb-{self.job_id}",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def __enter__(self) -> "LeaseHeartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
