"""Fault-injection-driven resilience layer (docs/RESILIENCE.md).

The fleet must keep converging on exact results while processes, links
and devices fail — the partial-failure discipline distributed ML
runtimes treat as table stakes (TensorFlow's dataflow layer,
arXiv:1605.08695; MLPerf-scale TPU-pod runs, arXiv:1909.09756). Five
cooperating pieces, all testable on CPU via the deterministic fault
harness:

- :mod:`swarm_tpu.resilience.faults` — named fault points threaded
  through server, stores, worker runtime, scheduler and ops engine,
  driven by a seeded plan (``SWARM_FAULT_PLAN``); no-ops when unset.
- :mod:`swarm_tpu.resilience.breaker` — circuit breakers with a
  process-wide board so ``/healthz`` can surface open breakers.
- :mod:`swarm_tpu.resilience.transport` — typed
  :class:`TransportError` plus :class:`RetryingServerClient` (jittered
  exponential backoff + per-operation breakers).
- :mod:`swarm_tpu.resilience.spool` — disk spool for completed output
  chunks: an unreachable server never loses finished work; replay is
  idempotent via the queue's fencing token.
- :mod:`swarm_tpu.resilience.heartbeat` — background lease renewal so
  long chunks stop racing the server's ``_requeue_expired``.
"""

from swarm_tpu.resilience.breaker import (  # noqa: F401
    BreakerBoard,
    CircuitBreaker,
    breaker_states,
)
from swarm_tpu.resilience.faults import (  # noqa: F401
    FaultInjected,
    FaultPlan,
    active_plan,
    clear_plan,
    fault_point,
    install_plan,
)
from swarm_tpu.resilience.heartbeat import LeaseHeartbeat  # noqa: F401
from swarm_tpu.resilience.spool import OutputSpool  # noqa: F401
from swarm_tpu.resilience.transport import (  # noqa: F401
    RetryingServerClient,
    TransportError,
)
