"""Circuit breakers with a process-wide board for operator visibility.

Closed → (``threshold`` consecutive failures) → open → (``cooldown_s``
elapses) → half-open: exactly one probe call is allowed through; its
outcome closes or re-opens the breaker. Breakers register on a global
board so ``/healthz`` and ``swarm metrics`` can show degradation
without scraping Prometheus; state transitions also drive the
``swarm_resilience_breaker_open`` gauge.
"""

from __future__ import annotations

import threading
import time
import weakref

from swarm_tpu.telemetry import REGISTRY

_BOARD_LOCK = threading.Lock()  # guards: _BOARD (reads)
# name → live instances: several objects may legitimately share a name
# (two workers' transport boards, two engines with the same batch
# shape) — the board must not let the last registration shadow an open
# earlier one. WeakSet so the board never extends breaker lifetime.
_BOARD: dict[str, "weakref.WeakSet[CircuitBreaker]"] = {}

_BREAKER_OPEN = REGISTRY.gauge(
    "swarm_resilience_breaker_open",
    "1 while the named circuit breaker is open (0 closed/half-open)",
    ("name",),
)
_BREAKER_TRANSITIONS = REGISTRY.counter(
    "swarm_resilience_breaker_transitions_total",
    "Circuit-breaker state transitions",
    ("name", "state"),
)

#: severity order for same-named aggregation (worst state wins)
_SEVERITY = {"closed": 0, "half-open": 1, "open": 2}


def breaker_states(prefix: str = "") -> dict[str, str]:
    """Name → state snapshot of every registered breaker IN THIS
    PROCESS (the /healthz surface — remote workers report theirs via
    completed jobs' ``breakers_open`` perf field and their own
    /metrics). Same-named instances aggregate to the worst state, so
    one open breaker can't hide behind a later-registered closed
    twin."""
    with _BOARD_LOCK:
        items = [(name, list(refs)) for name, refs in _BOARD.items()]
    out: dict[str, str] = {}
    for name, brs in items:
        if not name.startswith(prefix) or not brs:
            continue
        out[name] = max((br.state for br in brs), key=_SEVERITY.__getitem__)
    return out


def reset_board() -> None:
    """Drop all registered breakers (test isolation)."""
    with _BOARD_LOCK:
        _BOARD.clear()


class CircuitBreaker:
    """One named breaker. ``allow()`` gates the protected call;
    ``record_success``/``record_failure`` report its outcome."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(
        self,
        name: str,
        threshold: int = 5,
        cooldown_s: float = 30.0,
        clock=time.monotonic,
    ):
        self.name = name
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()  # guards: _failures (reads), _state (reads), _opened_at (reads), _probe_out (reads)
        self._failures = 0
        self._state = self.CLOSED
        self._opened_at = 0.0
        self._probe_out = False  # half-open: one probe in flight
        with _BOARD_LOCK:
            _BOARD.setdefault(name, weakref.WeakSet()).add(self)

    # ------------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:  # requires-lock: _lock
        if (
            self._state == self.OPEN
            and self._clock() - self._opened_at >= self.cooldown_s
        ):
            self._transition(self.HALF_OPEN)
            self._probe_out = False

    def _transition(self, state: str) -> None:  # requires-lock: _lock
        if state == self._state:
            return
        self._state = state
        _BREAKER_OPEN.labels(name=self.name).set(1 if state == self.OPEN else 0)
        _BREAKER_TRANSITIONS.labels(name=self.name, state=state).inc()
        if state == self.OPEN:
            # flight recorder (docs/OBSERVABILITY.md): a breaker opening
            # is a post-mortem moment — dump the recent span/event ring.
            # FlightRecorder.dump is memory-only (sinks run on a daemon
            # thread), so it is safe under self._lock.
            from swarm_tpu.telemetry import tracing

            tracing.flight_dump("breaker_open", detail=self.name)

    # ------------------------------------------------------------------
    def allow(self) -> bool:
        """Whether the protected call may proceed right now. In
        half-open state exactly one caller gets True (the probe)."""
        with self._lock:
            self._maybe_half_open()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN and not self._probe_out:
                self._probe_out = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_out = False
            self._transition(self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probe_out = False
            if self._state == self.HALF_OPEN or self._failures >= self.threshold:
                self._opened_at = self._clock()
                self._transition(self.OPEN)

    def reset(self) -> None:
        """Force-close and forget the failure streak. Used when the
        FAILING PEER is known to have been replaced — a worker seeing
        the control-plane generation change closes its transport
        breakers because the process that earned the failures is gone
        (docs/DURABILITY.md)."""
        with self._lock:
            self._failures = 0
            self._probe_out = False
            self._transition(self.CLOSED)


class BreakerBoard:
    """Lazily-created breakers sharing one config, keyed by name
    suffix — the per-operation transport breakers and the engine's
    per-shape-class device breakers."""

    def __init__(self, prefix: str, threshold: int = 5, cooldown_s: float = 30.0):
        self.prefix = prefix
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()  # guards: _breakers (reads)
        self._breakers: dict[str, CircuitBreaker] = {}

    def get(self, key: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                br = self._breakers[key] = CircuitBreaker(
                    f"{self.prefix}.{key}",
                    threshold=self.threshold,
                    cooldown_s=self.cooldown_s,
                )
            return br

    def states(self) -> dict[str, str]:
        with self._lock:
            items = list(self._breakers.items())
        return {k: br.state for k, br in items}

    def any_open(self) -> bool:
        return any(s != CircuitBreaker.CLOSED for s in self.states().values())

    def reset_all(self) -> None:
        """Force-close every breaker on this board (see
        :meth:`CircuitBreaker.reset`)."""
        with self._lock:
            breakers = list(self._breakers.values())
        for br in breakers:
            br.reset()
