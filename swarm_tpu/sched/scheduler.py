"""Continuous-batching scheduler: prefetch → buckets → bounded submit.

Sits between the worker runtime and the ops engine (the single place
batching policy lives). The round-5 measurements showed the chip
starving: host chunk decode, memo resolution, and device dispatch ran
serially, one chunk-shaped batch at a time. The scheduler turns that
into a three-stage pipeline over a stream of chunks:

1. **Prefetch**: decode/normalize the NEXT chunk's rows while the
   current batch is on device, classify each row — dead rows resolve
   immediately (they match nothing by contract), memo-known rows
   short-circuit out of device batches BEFORE padding, fresh rows go
   to the padding-bucket planner (sched/buckets.py) — and pre-encode
   planned batches (``encode_packed(reuse_buffers=True)``, drawing
   matrices from ``encoding._RotatingPool`` per bucket shape). Runs on
   a host thread when the host has cores to spare
   (``prefetch="auto"``); on starved hosts the same stage runs inline
   — the device in-flight overlap below does not need the thread.
2. **Submission** (caller's thread): ``engine.begin_packed`` launches
   the device kernel asynchronously; up to ``inflight`` batches ride
   the device at once, so the sparse host walk of batch i overlaps the
   kernel of batch i+1. On the CPU fallback backend the depth
   collapses to 1 — there the "device" is the host, and an in-flight
   kernel would steal exactly the cores the walk needs.
3. **Backpressure**: the encoded-batch queue is bounded
   (``queue_depth``) and the prefetch stage blocks on it — a slow
   extraction pass stalls intake (the chunk iterator simply isn't
   advanced) instead of ballooning host RSS. Peak footprint is
   ``queue_depth + inflight + 1`` encoded batches plus one bucket tail
   per live shape.

Results are exact and bit-identical to the direct path: every batch
goes through the same ``match_packed`` walk, only the batching/overlap
changes (pinned by tests/test_sched.py's parity suite).

Stage 2's walk half can additionally be OFFLOADED (docs/HOST_WALK.md):
with ``walk_offload`` on (auto: spare core + the engine's batched walk
enabled), ``finish_packed`` runs on a dedicated walk worker — batch
N's sparse confirm/extract walk fans out over the engine's walk pool
while THIS thread already encodes and dispatches batch N+1, so the
device submit path never blocks on host confirmation.

Telemetry (swarm_tpu/telemetry REGISTRY):
- ``swarm_sched_batches_total{bucket,kind}`` — bucket occupancy
- ``swarm_sched_rows_total{source}`` — fresh / memo / dead split
- ``swarm_sched_fill_ratio`` — rows ÷ padded rows per device batch
- ``swarm_sched_prefetch_stall_seconds_total`` — submit loop starved
- ``swarm_sched_inflight_depth`` — current in-flight device batches
- ``swarm_sched_bucket_rows{bucket}`` — pending rows per bucket
- ``swarm_sched_walk_offloaded_total`` — walks run on the walk worker
"""

from __future__ import annotations

import bisect
import dataclasses
import os
import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional, Sequence

from swarm_tpu.sched.buckets import (
    QOS_BULK,
    QOS_INTERACTIVE,
    BucketPlanner,
    PlannedBatch,
)
from swarm_tpu.telemetry import REGISTRY
from swarm_tpu.telemetry import tracing
from swarm_tpu.telemetry.sched_export import (
    SCHED_BATCH_AGE,
    SCHED_FLUSH_DEADLINE,
)

_BATCHES = REGISTRY.counter(
    "swarm_sched_batches_total",
    "Scheduler batches submitted, by padding bucket and kind",
    ("bucket", "kind"),
)
_ROWS = REGISTRY.counter(
    "swarm_sched_rows_total",
    "Rows through the scheduler, by resolution source",
    ("source",),  # fresh | memo | dead
)
_FILL = REGISTRY.histogram(
    "swarm_sched_fill_ratio",
    "Real rows / padded rows per submitted device batch",
    buckets=(0.125, 0.25, 0.5, 0.75, 0.9, 1.0),
)
_STALL = REGISTRY.counter(
    "swarm_sched_prefetch_stall_seconds_total",
    "Seconds the submission loop waited on the prefetch stage",
)
_INFLIGHT = REGISTRY.gauge(
    "swarm_sched_inflight_depth",
    "Device batches currently in flight (begun, not yet walked)",
)
_BUCKET_ROWS = REGISTRY.gauge(
    "swarm_sched_bucket_rows",
    "Rows pending in each padding bucket (set at plan time)",
    ("bucket",),
)
_WALK_OFFLOADED = REGISTRY.counter(
    "swarm_sched_walk_offloaded_total",
    "Host walks handed to the scheduler's walk worker instead of "
    "blocking the device-submit thread (docs/HOST_WALK.md)",
)


@dataclasses.dataclass
class SchedulerConfig:
    #: rows per planned batch; 0 = the engine's batch_rows
    rows_target: int = 0
    #: device batches in flight (begun, not yet walked). Bounded so the
    #: recycled encode buffers (_RotatingPool depth 8 / verdict planes
    #: depth 8) can never alias an unconsumed batch. On an accelerator
    #: backend the effective depth stays ≥2 even with the walk offload
    #: armed (the whole point: device batches hide the host walk); on
    #: the CPU fallback it still collapses to 1 — UNLESS the engine
    #: serves a multi-device sharded mesh, where the window must stay
    #: open for the deferred cross-rank reduction to overlap (see
    #: _device_overlap_ok).
    inflight: int = 2
    #: encoded batches buffered between prefetch and submission — the
    #: backpressure bound intake stalls against
    queue_depth: int = 2
    #: probe the cross-batch verdict memo at plan time and route known
    #: rows around the device buckets
    memo_split: bool = True
    #: encode-first speculation once the stream looks steady (two
    #: fresh-free chunks in a row): the lookup that classifies the
    #: chunk IS the batch's pre-encode. Chunk-shaped batches trade the
    #: memo-lane coalescing for a single content pass — right when
    #: chunks are big; for tiny chunks coalescing wins (see plan()).
    speculate: bool = True
    #: "thread" = decode/encode on a prefetch thread; "inline" = same
    #: stage on the caller's thread (no GIL ping-pong — the device
    #: in-flight overlap still applies); "auto" = thread only when the
    #: host has a core to give it
    prefetch: str = "auto"
    #: "on" = hand each batch's host walk (finish_packed) to a
    #: dedicated walk worker so the submit thread keeps dispatching
    #: device batches while batch N's walk runs (docs/HOST_WALK.md);
    #: "off" = walk on the submit thread (the pre-offload behavior);
    #: "auto" = offload when a spare core exists and the engine's
    #: batched walk is enabled
    walk_offload: str = "auto"
    #: interactive-row coalescing deadline (docs/GATEWAY.md §QoS): an
    #: interactive row older than this forces an early partial-bucket
    #: flush — the express batch preempts further coalescing while
    #: bulk batches already on device keep flying. Only consulted for
    #: streams that actually carry interactive rows; 0 disables.
    qos_deadline_ms: float = 50.0
    #: max-age flush for EVERY bucket (the bulk trickle-tail bound);
    #: 0 = off, the pre-QoS hold-until-end-of-stream behavior
    max_age_ms: float = 0.0

    def __post_init__(self):
        # queue_depth (≤2) + inflight (≤4) + the offloaded walk (1) +
        # the encode in progress (1) must stay at or under the
        # recycled-pool depth 8 (see encoding._RotatingPool; the walk
        # slot is charged against inflight in run(), which caps the
        # offloaded total at 2+3+1+1=7). The sharded matcher's parked
        # reduction planes (_PendingShard) are DEVICE-side buffers it
        # staged itself — they ride inside an in-flight batch's slot,
        # not a pool plane, so the budget is unchanged; the staging
        # pool tracks them separately (plane_holds/plane_bytes).
        self.inflight = max(1, min(int(self.inflight), 4))
        self.queue_depth = max(1, min(int(self.queue_depth), 2))


@dataclasses.dataclass
class SchedStats:
    chunks: int = 0
    batches: int = 0
    fresh_rows: int = 0
    memo_rows: int = 0
    dead_rows: int = 0
    fill_sum: float = 0.0  # sum of per-device-batch row-fill ratios
    device_batches: int = 0
    stall_seconds: float = 0.0
    wall_seconds: float = 0.0
    offloaded_walks: int = 0  # walks run on the walk worker

    @property
    def fill_ratio(self) -> float:
        return self.fill_sum / self.device_batches if self.device_batches else 0.0

    def snapshot(self) -> dict:
        return {
            "chunks": self.chunks,
            "batches": self.batches,
            "fresh_rows": self.fresh_rows,
            "memo_rows": self.memo_rows,
            "dead_rows": self.dead_rows,
            "fill_ratio": round(self.fill_ratio, 4),
            "stall_seconds": round(self.stall_seconds, 4),
            "wall_seconds": round(self.wall_seconds, 4),
            "offloaded_walks": self.offloaded_walks,
        }


_DONE = object()


def _rowmatches_of(engine, packed, n: int) -> list:
    """Per-row RowMatches assembly — delegates to the engine's single
    shared assembly (``MatchEngine.rowmatches_from_packed``) so the
    scheduled path can never drift from the direct ``match`` path."""
    return engine.rowmatches_from_packed(packed, n)


class BatchScheduler:
    """Drives one MatchEngine with continuous batching. One scheduler
    per engine; calls are serialized (the worker's job loop and the
    active scanner both call from a single thread)."""

    def __init__(self, engine, config: Optional[SchedulerConfig] = None):
        self.engine = engine
        self.config = config or SchedulerConfig()
        self.stats = SchedStats()
        # swarmlint-exempt: _lock guards run()-LOCAL chunk/result tables
        # shared with the offloaded-walk closure — locals are outside
        # the guards pass's attribute/global model (docs/ANALYSIS.md);
        # the parity suite (tests/test_sched.py) pins the behavior
        self._lock = threading.Lock()  # guards chunk/result tables
        self._overlap_helps: Optional[bool] = None
        # steady-regime streak persists ACROSS run() calls: a worker's
        # job stream is one logical feed, so a new run over known
        # content speculates from its first chunk
        self._steady_streak = 0

    def _device_overlap_ok(self) -> bool:
        """Whether keeping >1 batch in flight can hide device time: on
        a real accelerator the kernel runs off-host, so walking batch i
        while the chip crunches i+1 is free. On the CPU fallback the
        "device" IS the host — an in-flight kernel's XLA threads steal
        exactly the cores the walk needs, so depth collapses to 1.

        EXCEPT on a multi-device sharded mesh: the sharded matcher's
        deferred reduction (parallel/sharded.py, _PendingShard) only
        overlaps when dispatch N+1 happens before collect N, so the
        in-flight window must stay open even when the mesh is
        host-platform virtual devices — the cross-rank psum + verdict
        tail riding behind the next probe is exactly the serialization
        the window exists to hide, XLA threads or not."""
        ok = self._overlap_helps
        if ok is None:
            try:
                import jax

                ok = jax.default_backend() != "cpu"
            except Exception:
                ok = False
            if not ok:
                # resolve the engine's backend first (lazy — the same
                # resolution the first dispatch would do) so a
                # configured-but-unbuilt mesh is visible here
                ranks_fn = getattr(self.engine, "data_ranks", None)
                if ranks_fn is not None:
                    try:
                        ranks_fn()
                    except Exception:
                        pass
                sharded = getattr(self.engine, "sharded", None)
                mesh = getattr(sharded, "mesh", None)
                if mesh is not None and int(mesh.devices.size) > 1:
                    ok = True
            self._overlap_helps = ok
        return ok

    def _walk_offload_ok(self) -> bool:
        """Whether to hand each batch's host walk to a dedicated walk
        worker (docs/HOST_WALK.md): the submit thread then keeps
        dispatching batch N+1's device phase while batch N's walk runs
        on host threads. Explicit on/off wins; auto offloads when a
        spare core exists and the engine's batched walk is enabled
        (``walk_threads`` 0 means the operator pinned the serial
        reference walk — honor it end to end)."""
        mode = getattr(self.config, "walk_offload", "auto")
        if mode == "on":
            return True
        if mode == "off":
            return False
        return (os.cpu_count() or 1) >= 3 and getattr(
            self.engine, "walk_threads", 0
        ) != 0

    def _use_thread(self) -> bool:
        """Prefetch-thread policy: threading buys decode/encode overlap
        only when a spare core can actually run the thread; on 1-2 core
        hosts two Python-bound threads just ping-pong the GIL."""
        mode = self.config.prefetch
        if mode == "thread":
            return True
        if mode == "inline":
            return False
        return (os.cpu_count() or 1) >= 3

    # ------------------------------------------------------------------
    def match_rows(self, rows: Sequence) -> list:
        """All rows' RowMatches, in input order — the drop-in
        replacement for ``engine.match`` (bit-identical results)."""
        rows = list(rows)
        target = self.config.rows_target or self.engine.batch_rows
        chunks = [
            rows[i : i + target] for i in range(0, len(rows), target)
        ] or [[]]
        out: list = []
        for res in self.run(chunks):
            out.extend(res)
        return out

    # ------------------------------------------------------------------
    def run(
        self,
        chunks: Iterable,
        decode: Optional[Callable[[object], Sequence]] = None,
        qos=None,
    ) -> Iterator[list]:
        """Stream chunks through the pipeline; yield each chunk's
        RowMatches list in chunk order as it completes.

        ``chunks`` yields row sequences — or arbitrary payloads when
        ``decode`` is given, in which case decoding runs on the
        prefetch stage (on its thread when one is used). Buckets
        accumulate across chunk boundaries; a chunk's results surface
        once every bucket holding one of its rows has been walked (at
        the latest, at end of stream when partial buckets flush).

        ``qos`` classifies chunks for the express lane
        (docs/GATEWAY.md §QoS): None = all bulk (the pre-QoS
        behavior), a class string applies to every chunk (the worker's
        one-job-one-class feed), a callable maps each raw chunk
        payload to its class (the bench's bimodal feed). Interactive
        rows coalesce in their own buckets and flush early once older
        than ``qos_deadline_ms`` — results stay bit-identical, only
        the batching changes."""
        engine = self.engine
        cfg = self.config
        stats = self.stats
        if callable(qos):
            qos_of = qos
        else:
            fixed_qos = (
                QOS_INTERACTIVE if qos == QOS_INTERACTIVE else QOS_BULK
            )
            qos_of = lambda _payload: fixed_qos  # noqa: E731
        target = cfg.rows_target or engine.batch_rows
        # mesh-aware placement (docs/SHARDING.md): a sharded backend's
        # bucket targets round up to the 'data' axis size so full
        # buckets fill PER RANK; single-device (and stub) engines
        # report 1 and nothing changes
        data_ranks = getattr(engine, "data_ranks", lambda: 1)()
        planner = BucketPlanner(
            rows_target=target,
            max_body=engine.max_body,
            max_header=engine.max_header,
            data_ranks=data_ranks,
            qos_deadline_s=max(0.0, cfg.qos_deadline_ms) / 1000.0,
            max_age_s=max(0.0, cfg.max_age_ms) / 1000.0,
        )
        # chunk bookkeeping (prefetch registers, submission completes;
        # the lock only matters in threaded mode)
        chunk_start: list = []  # gid of each chunk's first row
        chunk_len: list = []
        chunk_left: list = []
        results: dict = {}  # gid -> RowMatches
        chunk_results: dict = {}  # cid -> whole-chunk RowMatches list
        t_run0 = time.perf_counter()

        def plan(register_dead) -> Iterator[tuple]:
            """The prefetch stage as a generator: decode, classify,
            bucket — yields ``(PlannedBatch, pre_encode_or_None)`` in
            submission order. ``register_dead(cid, gids)`` resolves
            dead rows.

            Steady-state regime detection: after two consecutive
            fresh-free chunks the stage speculates ENCODE-FIRST — one
            native lookup both classifies the chunk and, when every
            row is served (or dead), IS the batch's pre-encode. That
            collapses the steady path to exactly the direct path's
            lookup cost (no second hash pass, no per-row planner
            traffic). A chunk with misses re-classifies from the
            lookup's ``state`` array (still no extra probe) and resets
            the regime."""
            gid = 0
            memo_split = cfg.memo_split
            add_known = planner.add_known
            add_fresh = planner.add_fresh
            use_native = engine._use_native_memo()
            # fleet result tier (docs/CACHING.md): when the engine has
            # one attached, the shared lookup rides THIS stage — rows
            # the tier knows are in the L1 before classification, so
            # they take the memo lane (no bucket, no device slot) and
            # the remote round trip overlaps the in-flight batches
            # rather than the dispatch path. Stub engines (tests) may
            # not expose the hook.
            prefetch_shared = getattr(
                engine, "prefetch_shared_memo", None
            )
            for chunk in chunks:
                # classify from the RAW payload (decode may consume it)
                chunk_qos = qos_of(chunk)
                if chunk_qos != QOS_INTERACTIVE:
                    chunk_qos = QOS_BULK
                rows = list(decode(chunk) if decode else chunk)
                now_chunk = time.monotonic()
                with self._lock:
                    cid = len(chunk_start)
                    chunk_start.append(gid)
                    chunk_len.append(len(rows))
                    chunk_left.append(len(rows))
                stats.chunks += 1
                if memo_split and rows and prefetch_shared is not None:
                    prefetch_shared(rows)
                known = None
                state = None
                spec_pre = None
                if memo_split and rows:
                    if (
                        use_native
                        and cfg.speculate
                        and self._steady_streak >= 2
                        # tiny chunks: per-batch fixed costs dominate,
                        # so the memo-lane coalescing below beats a
                        # chunk-shaped speculative batch
                        and len(rows) >= target // 4
                    ):
                        spec_pre = engine.encode_packed(
                            rows, reuse_buffers=True
                        )
                        # native enc tuple: [1]=batch (None = no
                        # misses), [4]=state (-1 known, -2 dead, else
                        # miss slot)
                        state = spec_pre[4]
                        if spec_pre[1] is None:
                            n_dead = int((state == -2).sum())
                            n_memo = len(rows) - n_dead
                            stats.memo_rows += n_memo
                            stats.dead_rows += n_dead
                            if n_memo:
                                _ROWS.labels(source="memo").inc(n_memo)
                            if n_dead:
                                _ROWS.labels(source="dead").inc(n_dead)
                            pb = PlannedBatch(
                                ids=range(gid, gid + len(rows)),
                                rows=rows,
                                bucket=BucketPlanner._memo_label(chunk_qos),
                                kind="memo",
                                data_ranks=data_ranks, qos=chunk_qos,
                            )
                            gid += len(rows)
                            yield pb, spec_pre
                            continue
                        # misses present: fall through, classifying
                        # from state (the speculative encode is
                        # discarded — its buffers recycle via the pool)
                        self._steady_streak = 0
                    else:
                        # ONE native pass classifies the chunk's memo
                        # residency; per-chunk metric tallies below —
                        # a per-ROW ctypes probe or labeled-counter
                        # inc would tax the feed more than the
                        # classification itself
                        known = engine.memo_known_mask(rows)
                n_memo = n_fresh = 0
                dead_ids: list = []
                for j, row in enumerate(rows):
                    i = gid
                    gid += 1
                    if state is not None:
                        st = state[j]
                        if st == -2:
                            dead_ids.append(i)
                            continue
                        is_known = st == -1
                    else:
                        if not getattr(row, "alive", True):
                            # dead rows match nothing by contract — no
                            # bucket, no device, no memo traffic
                            dead_ids.append(i)
                            continue
                        is_known = known is not None and known[j]
                    if is_known:
                        n_memo += 1
                        pb = add_known(i, row, chunk_qos, now_chunk)
                    else:
                        n_fresh += 1
                        pb = add_fresh(i, row, chunk_qos, now_chunk)
                    if pb is not None:
                        yield pb, None
                if dead_ids:
                    register_dead(cid, dead_ids)
                    _ROWS.labels(source="dead").inc(len(dead_ids))
                stats.dead_rows += len(dead_ids)
                stats.memo_rows += n_memo
                stats.fresh_rows += n_fresh
                if n_memo:
                    _ROWS.labels(source="memo").inc(n_memo)
                if n_fresh:
                    _ROWS.labels(source="fresh").inc(n_fresh)
                self._steady_streak = (
                    0 if n_fresh else self._steady_streak + 1
                )
                # deadline-forced flushes (docs/GATEWAY.md §QoS): an
                # interactive row older than qos_deadline_ms preempts
                # further coalescing as a small express batch; with
                # max_age_ms set, bulk tails get the same bound.
                # Checked once per chunk — the feed's natural tick.
                for pb in planner.flush_due(time.monotonic()):
                    SCHED_FLUSH_DEADLINE.labels(qos=pb.qos).inc()
                    # always-on flight-ring record: a deadline preempt
                    # is exactly the context a post-mortem wants
                    tracing.flight_event(
                        "sched.deadline_flush", qos=pb.qos, bucket=pb.bucket
                    )
                    yield pb, None
            for pb in planner.flush_all():
                yield pb, None

        def register_dead(cid: int, dead_ids: list) -> None:
            from swarm_tpu.ops.engine import RowMatches

            with self._lock:
                for i in dead_ids:
                    results[i] = RowMatches(template_ids=[], extractions={})
                chunk_left[cid] -= len(dead_ids)

        def encode_of(pb: PlannedBatch):
            try:
                pre = engine.encode_packed(pb.rows, reuse_buffers=True)
            except Exception:
                pre = None  # finish path re-encodes; never lose the rows
            occ = planner.occupancy()
            occ.setdefault(pb.bucket, 0)  # flushed bucket reads 0
            for bucket, rows_pending in occ.items():
                _BUCKET_ROWS.labels(bucket=bucket).set(rows_pending)
            return pre

        inflight: list = []  # FIFO of (PlannedBatch, handle)
        inflight_cap = cfg.inflight if self._device_overlap_ok() else 1
        walk_exec = None
        walking: list = []  # FIFO of (PlannedBatch, Future) — offloaded
        if self._walk_offload_ok():
            from concurrent.futures import ThreadPoolExecutor

            walk_exec = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="swarm-sched-walk"
            )
            # the offloaded walk keeps one extra encoded batch alive:
            # its slot is charged against the in-flight budget so the
            # recycled encode planes (encoding._RotatingPool depth 8)
            # can never rotate back under an unwalked batch. Cap 3 (not
            # the former 2): on an accelerator the submit thread must
            # keep ≥2 device batches genuinely in flight WHILE a walk
            # runs — with the deeper pool the accounting still closes
            # (queue 2 + inflight 3 + walk 1 + encode 1 = 7 ≤ 8).
            inflight_cap = max(1, min(inflight_cap, 3))

        next_yield = [0]

        def finish_batch(pb: PlannedBatch, handle) -> None:
            packed = engine.finish_packed(handle)
            per = _rowmatches_of(engine, packed, len(pb.ids))
            ids = pb.ids  # ascending (arrival order within the bucket)
            with self._lock:
                if isinstance(ids, range) and ids:
                    # whole-chunk batch (the steady-state speculative
                    # path): adopt the assembled list as the chunk's
                    # result — no per-row dict traffic
                    cid = bisect.bisect_right(chunk_start, ids.start) - 1
                    if (
                        chunk_start[cid] == ids.start
                        and chunk_len[cid] == len(ids)
                    ):
                        chunk_results[cid] = per
                        chunk_left[cid] = 0
                        return
                results.update(zip(ids, per))
                # group the batch's rows by chunk in runs instead of a
                # per-row bisect — batches usually span 1-4 chunks
                k, n = 0, len(ids)
                while k < n:
                    cid = bisect.bisect_right(chunk_start, ids[k]) - 1
                    end_gid = chunk_start[cid] + chunk_len[cid]
                    k2 = k + 1
                    while k2 < n and ids[k2] < end_gid:
                        k2 += 1
                    chunk_left[cid] -= k2 - k
                    k = k2

        def drain_walks(limit: int) -> None:
            # .result() re-raises a walk failure on the submit thread —
            # a failing walk must fail the run, not vanish in a worker
            while len(walking) > limit:
                _pb, fut = walking.pop(0)
                fut.result()

        def finish_oldest() -> None:
            pb, handle = inflight.pop(0)
            _INFLIGHT.set(len(inflight))
            if walk_exec is not None:
                # batch N's walk runs on the walk worker (whose batched
                # confirm/extract passes fan out over the engine's walk
                # pool) while this thread keeps encoding + dispatching
                # batch N+1 — the device never waits for the walk. One
                # walk in flight: the worker serializes walks, and the
                # bound keeps the encode-plane budget exact.
                walking.append((pb, walk_exec.submit(finish_batch, pb,
                                                     handle)))
                stats.offloaded_walks += 1
                _WALK_OFFLOADED.inc()
                drain_walks(1)
            else:
                finish_batch(pb, handle)

        def ready_chunks() -> list:
            out = []
            with self._lock:
                while (
                    next_yield[0] < len(chunk_start)
                    and chunk_left[next_yield[0]] == 0
                ):
                    cid = next_yield[0]
                    res = chunk_results.pop(cid, None)
                    if res is None:
                        s, n = chunk_start[cid], chunk_len[cid]
                        res = [results.pop(g) for g in range(s, s + n)]
                    out.append(res)
                    next_yield[0] += 1
            return out

        def submit(pb: PlannedBatch, pre) -> Iterator[list]:
            # chaos lever (docs/RESILIENCE.md): a failing submission
            # propagates to the caller (worker execute → requeue path);
            # device-path faults inside begin_packed degrade in-engine
            from swarm_tpu.resilience.faults import fault_point

            fault_point("sched.submit", detail=pb.kind)
            handle = engine.begin_packed(pb.rows, pre=pre)
            inflight.append((pb, handle))
            _INFLIGHT.set(len(inflight))
            stats.batches += 1
            _BATCHES.labels(bucket=pb.bucket, kind=pb.kind).inc()
            if pb.oldest_ts is not None:
                # the oldest row's coalescing wait — what the deadline
                # flush bounds per class (docs/GATEWAY.md §QoS)
                SCHED_BATCH_AGE.labels(qos=pb.qos).observe(
                    max(0.0, time.monotonic() - pb.oldest_ts)
                )
            if pb.kind == "fresh":
                stats.device_batches += 1
                stats.fill_sum += pb.fill_rows
                _FILL.labels().observe(pb.fill_rows)
            while len(inflight) >= inflight_cap:
                finish_oldest()
            yield from ready_chunks()

        use_thread = self._use_thread()
        if use_thread and isinstance(chunks, (list, tuple)) and len(chunks) <= 1:
            # single-chunk call (per-wave engine.match): there is no
            # "next chunk" to prefetch — a thread would be pure
            # startup/handoff overhead per wave
            use_thread = False
        try:
            if not use_thread:
                # inline prefetch: same stages, caller's thread. Device
                # in-flight overlap (begin before finish) still applies;
                # only the decode/encode-vs-walk overlap is given up.
                for pb, pre in plan(register_dead):
                    yield from submit(
                        pb, pre if pre is not None else encode_of(pb)
                    )
                while inflight:
                    finish_oldest()
                drain_walks(0)
                for res in ready_chunks():
                    yield res
                return

            q: queue.Queue = queue.Queue(maxsize=cfg.queue_depth)
            stop = threading.Event()
            errors: list = []

            def put(item) -> None:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        return
                    except queue.Full:
                        continue

            def producer() -> None:
                try:
                    for pb, pre in plan(register_dead):
                        put((pb, pre if pre is not None else encode_of(pb)))
                        if stop.is_set():
                            return
                except BaseException as e:
                    errors.append(e)
                finally:
                    put(_DONE)

            thread = threading.Thread(
                target=producer, daemon=True, name="swarm-sched-prefetch"
            )
            thread.start()
            try:
                while True:
                    t0 = time.perf_counter()
                    item = q.get()
                    dt = time.perf_counter() - t0
                    stats.stall_seconds += dt
                    _STALL.inc(dt)
                    if item is _DONE:
                        break
                    pb, pre = item
                    yield from submit(pb, pre)
                while inflight:
                    finish_oldest()
                drain_walks(0)
                # the producer put(_DONE) after flush_all, so joining
                # here is bounded
                thread.join()
                if errors:
                    raise errors[0]
                for res in ready_chunks():
                    yield res
            finally:
                stop.set()
                thread.join()
        finally:
            if walk_exec is not None:
                # bounded: at most one walk is ever queued on the worker
                walk_exec.shutdown(wait=True)
            stats.wall_seconds += time.perf_counter() - t_run0
