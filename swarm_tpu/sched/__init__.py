"""Continuous-batching scheduler (docs/PIPELINE.md).

The batching-policy layer between the worker runtime and the ops
engine: bounded prefetch (decode the next chunk while the current
batch is on device), padding-bucket batch planning (a small fixed set
of device shapes, partial chunks coalesced), memo short-circuiting
(known rows never enter device buckets), and a backpressure-aware
submission loop with bounded in-flight device batches. Enabled per
engine with ``pipeline="on"`` (env ``SWARM_PIPELINE``); results are
bit-identical to the direct path.
"""

from swarm_tpu.sched.buckets import (  # noqa: F401
    BucketPlanner,
    PlannedBatch,
    width_class,
)
from swarm_tpu.sched.scheduler import (  # noqa: F401
    BatchScheduler,
    SchedStats,
    SchedulerConfig,
)
