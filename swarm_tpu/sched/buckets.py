"""Padding-bucket batch planner for the continuous-batching scheduler.

XLA compiles one executable per distinct batch shape, and every row in
a batch pays the batch's padded width. A chunk-shaped feed therefore
leaks time two ways: a single long row drags every short row up to the
cap width, and partial final chunks ship mostly-padding batches. The
planner re-bins incoming rows into a SMALL FIXED set of
(rows × max-stream-length) shapes:

- width classes are multiples of ``width_multiple`` (512 → 1024 →
  1536 → …, capped at the engine's stream caps), keyed by the row's
  body/banner length and header length — the same rounding as
  ``encoding._width_for``, so a bucket's encoded width IS its class
  and each bucket pins exactly one compiled shape;
- a bucket flushes when it reaches ``rows_target`` rows (a full,
  width-homogeneous device batch) or at end of stream (the partial
  final flush, which pays padding only once per bucket per scan
  instead of once per chunk);
- memo-known rows never enter width buckets at all — their content
  won't ride the device, so they queue in arrival order and flush as
  lookup-only batches (``kind="memo"``).

The encode path draws its matrices from ``encoding._RotatingPool``
keyed per (rows, width, role) — each bucket shape rotates its own
recycled buffers, so alternating buckets never re-fault fresh pages.

Shape budget: the two-phase args kernel (docs/DEVICE_MATCH.md) takes
the corpus as device-resident arguments, so every bucket of one width
class shares ONE compiled executable and a shape entry is small
(``DeviceDB.MAX_COMPILED`` still bounds the sharded matcher's pjit
cache). The class ladder admits ``max_body/512`` body classes, but a
real scan mix keeps a handful live — and crucially no MORE shapes than
the direct per-chunk path, whose per-batch max lands on the same
512-multiple ladder unpredictably; the planner makes each live shape
deterministic and reused. Bucket labels are ``w<body>h<header>`` and
surface in the scheduler's telemetry.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional


def width_class(n: int, multiple: int = 512, cap: int = 4096) -> int:
    """Smallest multiple of ``multiple`` that holds ``n`` bytes, capped
    at ``cap`` (rows past the cap truncate on device and host-redo
    exactly — same contract as encoding). EXACTLY mirrors
    ``encoding._width_for`` for a batch whose longest row is ``n``:
    every row in a bucket has length ≤ the class and > class-multiple
    (for the batch max), so the encoded width IS the class — the
    planned bucket pins the compiled shape. (A coarser ladder, e.g.
    powers of two, would NOT pin it: a w2048 bucket whose batch max
    happened to be 1100 would encode at 1536 and leak extra jit
    shapes.)"""
    if n <= multiple:
        return multiple
    w = ((n + multiple - 1) // multiple) * multiple
    return min(w, cap)


@dataclasses.dataclass
class PlannedBatch:
    """One scheduler submission: rows + their global ids, in arrival
    order within the batch."""

    # scheduler-global row ids aligned with rows, ascending; a range
    # marks a whole-chunk batch (the speculative steady-state path —
    # the scheduler then adopts results per chunk with no per-row
    # bookkeeping)
    ids: object  # list[int] | range
    rows: list
    bucket: str  # "w<body>h<header>" | "memo"
    kind: str  # "fresh" | "memo"
    final: bool = False  # end-of-stream partial flush
    #: 'data' mesh-axis size of the engine backend (docs/SHARDING.md):
    #: the engine rounds the padded batch up to a multiple of it, and
    #: fill accounting must charge that mesh padding too
    data_ranks: int = 1

    @property
    def fill_rows(self) -> float:
        """Row occupancy of the padded device batch this will become
        (the engine pads unique rows up to a 256 multiple, then up to
        a multiple of the 'data' axis on a mesh backend)."""
        n = len(self.rows)
        padded = max(256, ((n + 255) // 256) * 256)
        r = max(1, int(self.data_ranks))
        padded = ((padded + r - 1) // r) * r
        return n / padded


class BucketPlanner:
    """Stateful binner: ``add_fresh``/``add_known`` return a full
    :class:`PlannedBatch` when a bucket fills; ``flush_all`` drains the
    partial tails. Buckets accumulate ACROSS chunk boundaries — that is
    the continuous-batching part; the scheduler re-associates results
    with chunks afterwards."""

    def __init__(
        self,
        rows_target: int = 1024,
        width_multiple: int = 512,
        max_body: int = 4096,
        max_header: int = 1024,
        data_ranks: int = 1,
    ):
        self.data_ranks = max(1, int(data_ranks))
        # mesh-aware placement (docs/SHARDING.md): a full bucket must
        # divide evenly over the 'data' axis so every rank's block is
        # the same share of REAL rows — a 2048-row bucket on an 8-way
        # data axis flushes at 2048 (256 real rows per rank), never at
        # a count that leaves one rank mostly padding
        self.rows_target = max(1, int(rows_target))
        r = self.data_ranks
        self.rows_target = ((self.rows_target + r - 1) // r) * r
        self.width_multiple = width_multiple
        self.max_body = max_body
        self.max_header = max_header
        self._fresh: dict = {}  # (wb, wh) -> [ids, rows]
        self._memo_ids: list = []
        self._memo_rows: list = []

    # ------------------------------------------------------------------
    def bucket_of(self, row) -> tuple:
        """(body width class, header width class) — in lockstep with
        ``encoding.encode_batch`` part semantics ("body" is the banner
        when one is set)."""
        blob = row.body if row.banner is None else row.banner
        wb = width_class(len(blob), self.width_multiple, self.max_body)
        wh = width_class(
            len(row.header), self.width_multiple, self.max_header
        )
        return wb, wh

    # ------------------------------------------------------------------
    def add_fresh(self, gid: int, row) -> Optional[PlannedBatch]:
        key = self.bucket_of(row)
        slot = self._fresh.get(key)
        if slot is None:
            slot = self._fresh[key] = ([], [])
        slot[0].append(gid)
        slot[1].append(row)
        if len(slot[0]) >= self.rows_target:
            del self._fresh[key]
            return PlannedBatch(
                ids=slot[0], rows=slot[1],
                bucket=f"w{key[0]}h{key[1]}", kind="fresh",
                data_ranks=self.data_ranks,
            )
        return None

    def add_known(self, gid: int, row) -> Optional[PlannedBatch]:
        self._memo_ids.append(gid)
        self._memo_rows.append(row)
        if len(self._memo_ids) >= self.rows_target:
            out = PlannedBatch(
                ids=self._memo_ids, rows=self._memo_rows,
                bucket="memo", kind="memo", data_ranks=self.data_ranks,
            )
            self._memo_ids, self._memo_rows = [], []
            return out
        return None

    # ------------------------------------------------------------------
    def flush_all(self) -> Iterator[PlannedBatch]:
        """Drain every partial bucket (end of stream). Fresh tails
        flush largest-first so the widest compiled shape warms before
        narrower ones reuse its row-pad class."""
        for key in sorted(self._fresh, reverse=True):
            ids, rows = self._fresh.pop(key)
            yield PlannedBatch(
                ids=ids, rows=rows,
                bucket=f"w{key[0]}h{key[1]}", kind="fresh", final=True,
                data_ranks=self.data_ranks,
            )
        if self._memo_ids:
            yield PlannedBatch(
                ids=self._memo_ids, rows=self._memo_rows,
                bucket="memo", kind="memo", final=True,
                data_ranks=self.data_ranks,
            )
            self._memo_ids, self._memo_rows = [], []

    # ------------------------------------------------------------------
    def occupancy(self) -> dict:
        """bucket label -> rows currently pending (telemetry gauge)."""
        out = {
            f"w{k[0]}h{k[1]}": len(v[0]) for k, v in self._fresh.items()
        }
        if self._memo_ids:
            out["memo"] = len(self._memo_ids)
        return out

    @property
    def pending_rows(self) -> int:
        return sum(len(v[0]) for v in self._fresh.values()) + len(
            self._memo_ids
        )
