"""Padding-bucket batch planner for the continuous-batching scheduler.

XLA compiles one executable per distinct batch shape, and every row in
a batch pays the batch's padded width. A chunk-shaped feed therefore
leaks time two ways: a single long row drags every short row up to the
cap width, and partial final chunks ship mostly-padding batches. The
planner re-bins incoming rows into a SMALL FIXED set of
(rows × max-stream-length) shapes:

- width classes are multiples of ``width_multiple`` (512 → 1024 →
  1536 → …, capped at the engine's stream caps), keyed by the row's
  body/banner length and header length — the same rounding as
  ``encoding._width_for``, so a bucket's encoded width IS its class
  and each bucket pins exactly one compiled shape;
- a bucket flushes when it reaches ``rows_target`` rows (a full,
  width-homogeneous device batch) or at end of stream (the partial
  final flush, which pays padding only once per bucket per scan
  instead of once per chunk);
- memo-known rows never enter width buckets at all — their content
  won't ride the device, so they queue in arrival order and flush as
  lookup-only batches (``kind="memo"``).

The encode path draws its matrices from ``encoding._RotatingPool``
keyed per (rows, width, role) — each bucket shape rotates its own
recycled buffers, so alternating buckets never re-fault fresh pages.

Shape budget: the two-phase args kernel (docs/DEVICE_MATCH.md) takes
the corpus as device-resident arguments, so every bucket of one width
class shares ONE compiled executable and a shape entry is small
(``DeviceDB.MAX_COMPILED`` still bounds the sharded matcher's pjit
cache). The class ladder admits ``max_body/512`` body classes, but a
real scan mix keeps a handful live — and crucially no MORE shapes than
the direct per-chunk path, whose per-batch max lands on the same
512-multiple ladder unpredictably; the planner makes each live shape
deterministic and reused. Bucket labels are ``w<body>h<header>`` and
surface in the scheduler's telemetry.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional


def width_class(n: int, multiple: int = 512, cap: int = 4096) -> int:
    """Smallest multiple of ``multiple`` that holds ``n`` bytes, capped
    at ``cap`` (rows past the cap truncate on device and host-redo
    exactly — same contract as encoding). EXACTLY mirrors
    ``encoding._width_for`` for a batch whose longest row is ``n``:
    every row in a bucket has length ≤ the class and > class-multiple
    (for the batch max), so the encoded width IS the class — the
    planned bucket pins the compiled shape. (A coarser ladder, e.g.
    powers of two, would NOT pin it: a w2048 bucket whose batch max
    happened to be 1100 would encode at 1536 and leak extra jit
    shapes.)"""
    if n <= multiple:
        return multiple
    w = ((n + multiple - 1) // multiple) * multiple
    return min(w, cap)


@dataclasses.dataclass
class PlannedBatch:
    """One scheduler submission: rows + their global ids, in arrival
    order within the batch."""

    # scheduler-global row ids aligned with rows, ascending; a range
    # marks a whole-chunk batch (the speculative steady-state path —
    # the scheduler then adopts results per chunk with no per-row
    # bookkeeping)
    ids: object  # list[int] | range
    rows: list
    bucket: str  # "w<body>h<header>" | "memo" (interactive: "x:" prefix)
    kind: str  # "fresh" | "memo"
    final: bool = False  # end-of-stream partial flush
    #: 'data' mesh-axis size of the engine backend (docs/SHARDING.md):
    #: the engine rounds the padded batch up to a multiple of it, and
    #: fill accounting must charge that mesh padding too
    data_ranks: int = 1
    #: latency class (docs/GATEWAY.md §QoS): interactive batches are
    #: the express lane's small early flushes, bulk is everything else
    qos: str = "bulk"
    #: True when a lapsed deadline (qos_deadline_s / max_age_s) forced
    #: this flush before the bucket filled
    deadline: bool = False
    #: monotonic stamp of the batch's OLDEST row entering the planner
    #: (None on the speculative whole-chunk path, which never waits)
    oldest_ts: Optional[float] = None

    @property
    def fill_rows(self) -> float:
        """Row occupancy of the padded device batch this will become
        (the engine pads unique rows up to a 256 multiple, then up to
        a multiple of the 'data' axis on a mesh backend)."""
        n = len(self.rows)
        padded = max(256, ((n + 255) // 256) * 256)
        r = max(1, int(self.data_ranks))
        padded = ((padded + r - 1) // r) * r
        return n / padded


#: QoS classes a planner bucket can carry (docs/GATEWAY.md §QoS) —
#: interactive buckets coalesce separately from bulk and flush early
#: once their oldest row is ``qos_deadline_s`` old
QOS_BULK = "bulk"
QOS_INTERACTIVE = "interactive"


def _label(key: tuple) -> str:
    """Bucket telemetry label: bulk keeps the pre-QoS ``w<b>h<h>``
    form, interactive buckets prefix ``x:`` (the express lane)."""
    wb, wh, qos = key
    base = f"w{wb}h{wh}"
    return base if qos == QOS_BULK else f"x:{base}"


class BucketPlanner:
    """Stateful binner: ``add_fresh``/``add_known`` return a full
    :class:`PlannedBatch` when a bucket fills; ``flush_due`` drains
    buckets whose deadline lapsed (the express-lane preemption);
    ``flush_all`` drains the partial tails at end of stream. Buckets
    accumulate ACROSS chunk boundaries — that is the continuous-
    batching part; the scheduler re-associates results with chunks
    afterwards. Buckets are keyed per QoS class too, so a small
    interactive flush never carries bulk rows with it."""

    def __init__(
        self,
        rows_target: int = 1024,
        width_multiple: int = 512,
        max_body: int = 4096,
        max_header: int = 1024,
        data_ranks: int = 1,
        qos_deadline_s: float = 0.0,
        max_age_s: float = 0.0,
    ):
        self.data_ranks = max(1, int(data_ranks))
        # mesh-aware placement (docs/SHARDING.md): a full bucket must
        # divide evenly over the 'data' axis so every rank's block is
        # the same share of REAL rows — a 2048-row bucket on an 8-way
        # data axis flushes at 2048 (256 real rows per rank), never at
        # a count that leaves one rank mostly padding
        self.rows_target = max(1, int(rows_target))
        r = self.data_ranks
        self.rows_target = ((self.rows_target + r - 1) // r) * r
        self.width_multiple = width_multiple
        self.max_body = max_body
        self.max_header = max_header
        #: interactive rows older than this force an early partial
        #: flush of their bucket (0 = off; docs/GATEWAY.md §QoS)
        self.qos_deadline_s = float(qos_deadline_s)
        #: max age for ANY bucket — the bulk trickle-tail bound
        #: (0 = off, today's hold-until-flush_all behavior)
        self.max_age_s = float(max_age_s)
        self._fresh: dict = {}  # (wb, wh, qos) -> [ids, rows, first_ts]
        self._memo: dict = {}  # qos -> [ids, rows, first_ts]

    # ------------------------------------------------------------------
    def bucket_of(self, row) -> tuple:
        """(body width class, header width class) — in lockstep with
        ``encoding.encode_batch`` part semantics ("body" is the banner
        when one is set)."""
        blob = row.body if row.banner is None else row.banner
        wb = width_class(len(blob), self.width_multiple, self.max_body)
        wh = width_class(
            len(row.header), self.width_multiple, self.max_header
        )
        return wb, wh

    # ------------------------------------------------------------------
    def add_fresh(
        self, gid: int, row, qos: str = QOS_BULK,
        now: Optional[float] = None,
    ) -> Optional[PlannedBatch]:
        wb, wh = self.bucket_of(row)
        key = (wb, wh, qos)
        slot = self._fresh.get(key)
        if slot is None:
            slot = self._fresh[key] = ([], [], now)
        slot[0].append(gid)
        slot[1].append(row)
        if len(slot[0]) >= self.rows_target:
            del self._fresh[key]
            return PlannedBatch(
                ids=slot[0], rows=slot[1],
                bucket=_label(key), kind="fresh",
                data_ranks=self.data_ranks, qos=qos, oldest_ts=slot[2],
            )
        return None

    def add_known(
        self, gid: int, row, qos: str = QOS_BULK,
        now: Optional[float] = None,
    ) -> Optional[PlannedBatch]:
        slot = self._memo.get(qos)
        if slot is None:
            slot = self._memo[qos] = ([], [], now)
        slot[0].append(gid)
        slot[1].append(row)
        if len(slot[0]) >= self.rows_target:
            del self._memo[qos]
            return PlannedBatch(
                ids=slot[0], rows=slot[1],
                bucket=self._memo_label(qos), kind="memo",
                data_ranks=self.data_ranks, qos=qos, oldest_ts=slot[2],
            )
        return None

    @staticmethod
    def _memo_label(qos: str) -> str:
        return "memo" if qos == QOS_BULK else "x:memo"

    # ------------------------------------------------------------------
    def _due(self, slot, qos: str, now: float) -> bool:
        first_ts = slot[2]
        if first_ts is None:
            return False
        age = now - first_ts
        if (
            qos == QOS_INTERACTIVE
            and self.qos_deadline_s > 0
            and age >= self.qos_deadline_s
        ):
            return True
        return self.max_age_s > 0 and age >= self.max_age_s

    def flush_due(self, now: float) -> Iterator[PlannedBatch]:
        """Deadline-forced partial flushes (docs/GATEWAY.md §QoS,
        docs/PIPELINE.md): an interactive bucket whose oldest row is
        ``qos_deadline_s`` old flushes NOW as a small express batch —
        the scheduler's in-flight window lets it ride the device ahead
        of further coalescing without draining bulk batches already in
        flight. With ``max_age_s`` set, bulk buckets get the same
        treatment (the trickling-scan tail bound); by default they
        keep waiting for ``flush_all``."""
        for key in [
            k for k, s in self._fresh.items() if self._due(s, k[2], now)
        ]:
            ids, rows, first_ts = self._fresh.pop(key)
            yield PlannedBatch(
                ids=ids, rows=rows, bucket=_label(key), kind="fresh",
                data_ranks=self.data_ranks, qos=key[2], deadline=True,
                oldest_ts=first_ts,
            )
        for qos in [
            q for q, s in self._memo.items() if self._due(s, q, now)
        ]:
            ids, rows, first_ts = self._memo.pop(qos)
            yield PlannedBatch(
                ids=ids, rows=rows, bucket=self._memo_label(qos),
                kind="memo", data_ranks=self.data_ranks, qos=qos,
                deadline=True, oldest_ts=first_ts,
            )

    # ------------------------------------------------------------------
    def flush_all(self) -> Iterator[PlannedBatch]:
        """Drain every partial bucket (end of stream). Interactive
        tails first (they are latency-bound even here), then bulk
        fresh tails largest-first so the widest compiled shape warms
        before narrower ones reuse its row-pad class."""
        for key in sorted(
            self._fresh,
            key=lambda k: (k[2] != QOS_INTERACTIVE, -k[0], -k[1]),
        ):
            ids, rows, first_ts = self._fresh.pop(key)
            yield PlannedBatch(
                ids=ids, rows=rows,
                bucket=_label(key), kind="fresh", final=True,
                data_ranks=self.data_ranks, qos=key[2],
                oldest_ts=first_ts,
            )
        for qos in list(self._memo):
            ids, rows, first_ts = self._memo.pop(qos)
            yield PlannedBatch(
                ids=ids, rows=rows,
                bucket=self._memo_label(qos), kind="memo", final=True,
                data_ranks=self.data_ranks, qos=qos, oldest_ts=first_ts,
            )

    # ------------------------------------------------------------------
    def occupancy(self) -> dict:
        """bucket label -> rows currently pending (telemetry gauge)."""
        out = {_label(k): len(v[0]) for k, v in self._fresh.items()}
        for qos, slot in self._memo.items():
            if slot[0]:
                out[self._memo_label(qos)] = len(slot[0])
        return out

    @property
    def pending_rows(self) -> int:
        return sum(len(v[0]) for v in self._fresh.values()) + sum(
            len(v[0]) for v in self._memo.values()
        )
