"""Core data model: scans, jobs, chunks, workers.

Identifier formats and the status taxonomy follow the reference wire
protocol so the reference client works against this server unchanged:

- scan ids are ``<module>_<unix-ts>`` (reference ``server/server.py:181-183``)
- job ids are ``<scan_id>_<chunk_index>`` (reference ``server/server.py:441``)
- job statuses walk ``queued → in progress → starting → downloading →
  executing → uploading → complete`` with terminal failure statuses
  ``cmd failed`` / ``upload failed - *`` (reference ``server/server.py:454,485``,
  ``worker/worker.py:61-108``).

On top of the reference's model this adds *leases*: a dispatched job
carries a lease deadline and is requeued when the lease expires without
a state transition (the reference loses jobs whose worker dies —
``SURVEY.md §5``).
"""

from __future__ import annotations

import dataclasses
import json
import re
import time
from typing import Any, Iterator, Optional

# Scan ids flow into filesystem paths and {input}/{output} command
# substitution on both server and worker — one shared rule so the two
# validation sites cannot drift.
SCAN_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,128}$")


class JobStatus:
    """Status taxonomy, wire-identical to the reference."""

    QUEUED = "queued"
    IN_PROGRESS = "in progress"
    STARTING = "starting"
    DOWNLOADING = "downloading"
    EXECUTING = "executing"
    UPLOADING = "uploading"
    COMPLETE = "complete"
    CMD_FAILED = "cmd failed"
    UPLOAD_FAILED_NOT_FOUND = "upload failed - file not found"
    UPLOAD_FAILED_CREDENTIALS = "upload failed - credentials"
    UPLOAD_FAILED_UNKNOWN = "upload failed - unknown"
    # Quarantine (new vs reference): a job that exhausted max_attempts
    # parks here WITH its failure history instead of silently going
    # terminal-failed. Operators inspect/requeue via `swarm dead-letter`.
    DEAD_LETTER = "dead letter"

    TERMINAL = frozenset(
        {
            COMPLETE,
            CMD_FAILED,
            UPLOAD_FAILED_NOT_FOUND,
            UPLOAD_FAILED_CREDENTIALS,
            UPLOAD_FAILED_UNKNOWN,
            DEAD_LETTER,
        }
    )
    FAILED = frozenset(TERMINAL - {COMPLETE})
    # leased statuses: dispatched and not yet terminal — lease
    # enforcement must cover ALL of these (a worker dying mid-execute
    # leaves the job in "executing", not "in progress")
    ACTIVE = frozenset(
        {IN_PROGRESS, STARTING, DOWNLOADING, EXECUTING, UPLOADING}
    )
    ALL = frozenset(
        {
            QUEUED,
            IN_PROGRESS,
            STARTING,
            DOWNLOADING,
            EXECUTING,
            UPLOADING,
        }
        | TERMINAL
    )


class WorkerStatus:
    """Worker liveness states (reference ``server/server.py:489-507``).

    ``draining``/``preempted`` are additions for the elastic fleet
    (docs/RESILIENCE.md §Preemption): a draining worker finishes its
    current lease but is offered no new jobs; a preempted worker is a
    draining worker whose drain was initiated by a provider preemption
    notice. Both deregister (or lapse) into ``inactive``.
    """

    ACTIVE = "active"
    PENDING = "pending"
    INACTIVE = "inactive"
    DRAINING = "draining"
    PREEMPTED = "preempted"

    #: states the queue must not offer new jobs to
    NO_DISPATCH = frozenset({DRAINING, PREEMPTED})


def generate_scan_id(module: str, timestamp: Optional[int] = None) -> str:
    """``<module>_<unix-ts>`` — reference ``server/server.py:181-183``."""
    ts = int(time.time()) if timestamp is None else int(timestamp)
    return f"{module}_{ts}"


def job_id_for(scan_id: str, chunk_index: int) -> str:
    """``<scan_id>_<chunk_index>`` — reference ``server/server.py:441``."""
    return f"{scan_id}_{chunk_index}"


def parse_job_id(job_id: str) -> tuple[str, int]:
    """Split a job id back into ``(scan_id, chunk_index)``.

    The reference client splits on ``_`` and assumes exactly three parts
    (``client/swarm:58-63``); this version is robust to modules whose
    names themselves contain underscores by splitting from the right.
    """
    scan_id, _, idx = job_id.rpartition("_")
    return scan_id, int(idx)


def parse_scan_id(scan_id: str) -> tuple[str, int]:
    """Split ``<module>_<ts>`` into ``(module, started_ts)``."""
    module, _, ts = scan_id.rpartition("_")
    return module, int(ts)


def chunk_input_key(scan_id: str, chunk_index: int) -> str:
    """Blob key for an input chunk (reference ``server/server.py:446``)."""
    return f"{scan_id}/input/chunk_{chunk_index}.txt"


def chunk_output_key(scan_id: str, chunk_index: int) -> str:
    """Blob key for an output chunk (reference ``worker/worker.py:96``)."""
    return f"{scan_id}/output/chunk_{chunk_index}.txt"


def chunk_generator(sequence: list, batch_size: int) -> Iterator[list]:
    """Split a target list into fixed-size chunks.

    Mirrors reference ``server/server.py:185-187``; a chunk is the unit
    of dispatch, checkpointing and (on the TPU path) device sharding.
    ``batch_size <= 0`` means one whole-sequence chunk (the reference
    normalizes 0 the same way in ``server/server.py:434-435``).
    """
    batch_size = int(batch_size)
    if batch_size <= 0:
        batch_size = max(1, len(sequence))
    for i in range(0, len(sequence), batch_size):
        yield sequence[i : i + batch_size]


@dataclasses.dataclass
class Job:
    """One chunk of a scan, dispatched to exactly one worker at a time.

    Field names match the reference's Redis job hash payload
    (``server/server.py:198-205``) so serialized jobs are wire-identical;
    ``lease_expires_at`` is an addition (absent fields are simply extra
    keys to the reference client, which ignores unknown keys).
    """

    job_id: str
    scan_id: str
    chunk_index: int
    module: str
    status: str = JobStatus.QUEUED
    worker_id: Optional[str] = None
    started_at: Optional[float] = None
    completed_at: Optional[float] = None
    lease_expires_at: Optional[float] = None
    attempts: int = 0
    # per-job performance sample reported by the worker on completion
    # (download/execute/upload seconds, device rows + seconds — SURVEY.md
    # §5 "tracing": timing exported through the same status API fields).
    # Extra key to the reference client, which ignores unknown fields.
    perf: Optional[dict] = None
    # scan-scoped correlation ID (telemetry.events): minted by the
    # client, carried via the X-Swarm-Trace header into /queue, stored
    # here, and handed back out through /get-job so every layer's event
    # lines for one scan share it. Extra wire key to the reference.
    trace_id: Optional[str] = None
    # failure provenance: one entry per failed attempt / lease expiry
    # ({ts, worker_id, status}), carried into the dead-letter state so
    # quarantined jobs explain themselves. Extra wire key.
    failure_history: Optional[list] = None
    # submitting tenant (gateway PR, docs/GATEWAY.md): None = the
    # default tenant — reference submissions carry no tenant header and
    # land there, so legacy job records round-trip unchanged. Extra
    # wire key the reference client ignores.
    tenant: Optional[str] = None
    # latency class (docs/GATEWAY.md §QoS): None = bulk, the reference
    # wire behavior — submissions without X-Swarm-QoS land here and the
    # record round-trips unchanged. "interactive" rides the express
    # dispatch lane and the scheduler's deadline-flush path. Extra wire
    # key the reference client ignores.
    qos: Optional[str] = None
    # gateway admission stamp (time.time() at queue_scan): the
    # admission-to-verdict latency histograms subtract this from
    # completed_at per QoS class. Extra wire key.
    admitted_at: Optional[float] = None
    # target-line count of this job's input chunk (stamped at
    # submission): the gateway cache's writeback hook reads it to skip
    # over-bound bulk chunks without fetching the blob. Extra wire key.
    chunk_rows: Optional[int] = None
    # standing-monitor provenance (docs/MONITORING.md): jobs fired by
    # a monitor epoch carry the spec id and epoch number so `swarm
    # scans` / /get-statuses can attribute a scan to its monitor. None
    # on every one-shot submission — the reference wire contract is
    # byte-preserved when absent (extra always-present-None keys, the
    # same pattern as tenant/qos). Extra wire keys the reference
    # client ignores.
    monitor_id: Optional[str] = None
    monitor_epoch: Optional[int] = None

    @classmethod
    def create(
        cls,
        scan_id: str,
        chunk_index: int,
        module: str,
        trace_id: Optional[str] = None,
        tenant: Optional[str] = None,
        qos: Optional[str] = None,
        admitted_at: Optional[float] = None,
        chunk_rows: Optional[int] = None,
        monitor_id: Optional[str] = None,
        monitor_epoch: Optional[int] = None,
    ) -> "Job":
        return cls(
            job_id=job_id_for(scan_id, chunk_index),
            scan_id=scan_id,
            chunk_index=chunk_index,
            module=module,
            trace_id=trace_id,
            tenant=tenant,
            qos=qos,
            admitted_at=admitted_at,
            chunk_rows=chunk_rows,
            monitor_id=monitor_id,
            monitor_epoch=monitor_epoch,
        )

    def to_wire(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, payload: dict[str, Any]) -> "Job":
        fields = {f.name for f in dataclasses.fields(cls)}
        known = {k: v for k, v in payload.items() if k in fields}
        known.setdefault("job_id", job_id_for(payload["scan_id"], payload["chunk_index"]))
        return cls(**known)

    def to_json(self) -> str:
        return json.dumps(self.to_wire())

    @classmethod
    def from_json(cls, blob: str | bytes) -> "Job":
        return cls.from_wire(json.loads(blob))


@dataclasses.dataclass
class WorkerInfo:
    """Per-worker liveness record (reference ``server/server.py:471-508``)."""

    worker_id: str
    last_contact: Optional[float] = None
    polls_with_no_jobs: int = 0
    status: str = WorkerStatus.ACTIVE

    def to_wire(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d.pop("worker_id")
        return d

    @classmethod
    def from_wire(cls, worker_id: str, payload: dict[str, Any]) -> "WorkerInfo":
        fields = {f.name for f in dataclasses.fields(cls)} - {"worker_id"}
        return cls(worker_id=worker_id, **{k: v for k, v in payload.items() if k in fields})


@dataclasses.dataclass
class ScanSummary:
    """Per-scan rollup (reference ``server/server.py:239-294``)."""

    scan_id: str
    total_chunks: int = 0
    chunks_complete: int = 0
    percent_complete: float = 0.0
    workers: list = dataclasses.field(default_factory=list)
    module: Optional[str] = None
    scan_started: Optional[int] = None
    scan_completed: Optional[float] = None
    completed_at: Optional[float] = None
    scan_time: Optional[float] = None
    scan_status: Optional[str] = None
    average_scan_time: Optional[float] = None
    # aggregated worker perf samples (None until a job reports perf)
    rows_processed: Optional[int] = None
    device_seconds: Optional[float] = None
    execute_seconds: Optional[float] = None
    rows_per_second: Optional[float] = None
    # standing-monitor provenance (docs/MONITORING.md): set when the
    # scan's jobs were fired by a monitor epoch, None for one-shot
    # scans — the reference rollup shape gains only extra keys
    monitor_id: Optional[str] = None
    monitor_epoch: Optional[int] = None

    def to_wire(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def rollup_scans(jobs: dict[str, dict]) -> list[dict]:
    """Collate per-job records into per-scan summaries.

    Behavior-parity with reference ``server/server.py:239-302``: chunk
    totals, completion percentage, distinct workers, scan_started parsed
    from the scan id timestamp, completed_at = max job completed_at.
    """
    scans: dict[str, ScanSummary] = {}
    for job in jobs.values():
        scan_id = job.get("scan_id")
        summary = scans.get(scan_id)
        if summary is None:
            summary = scans[scan_id] = ScanSummary(scan_id=scan_id, module=job.get("module"))
            try:
                summary.scan_started = parse_scan_id(scan_id)[1]
            except (ValueError, TypeError, AttributeError):
                summary.scan_started = None
        summary.total_chunks += 1
        if job.get("status") == JobStatus.COMPLETE:
            summary.chunks_complete += 1
        if job.get("worker_id") not in summary.workers:
            summary.workers.append(job.get("worker_id"))
        completed = job.get("completed_at")
        if completed is not None and (
            summary.completed_at is None or completed > summary.completed_at
        ):
            summary.completed_at = completed
        if summary.monitor_id is None and job.get("monitor_id"):
            summary.monitor_id = job.get("monitor_id")
            summary.monitor_epoch = job.get("monitor_epoch")
        perf = job.get("perf")
        if isinstance(perf, dict):
            summary.rows_processed = (summary.rows_processed or 0) + int(
                perf.get("rows", 0)
            )
            summary.device_seconds = (summary.device_seconds or 0.0) + float(
                perf.get("device_s", 0.0)
            )
            summary.execute_seconds = (summary.execute_seconds or 0.0) + float(
                perf.get("execute_s", 0.0)
            )
    for summary in scans.values():
        summary.percent_complete = round(
            summary.chunks_complete / summary.total_chunks * 100, 2
        )
        if summary.percent_complete == 100:
            summary.scan_status = "complete"
        if summary.rows_processed and summary.execute_seconds:
            summary.rows_per_second = round(
                summary.rows_processed / summary.execute_seconds, 2
            )
    return [s.to_wire() for s in scans.values()]
