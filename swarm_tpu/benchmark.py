"""Benchmark sampler: sample-size extrapolation for fleet A/B runs.

Parity with the reference's only measurement tool
(``experimental/benchmark.py:15-58``): given a target file and an
instance count, compute the per-instance batch size
(``total/instances/1.7``), a sample size, and the magnification factor,
then write a shuffled sample file — so a small scan's wall-clock can be
extrapolated to the full run (``sample_seconds × magnification``).

Extended for the TPU A/B story (BASELINE.md config #1): the sampler is
importable (pure functions, deterministic with ``seed``) and the CLI
additionally reports device-throughput extrapolation when given
``--rows-per-second`` (e.g. from a scan's ``/get-statuses`` rollup).
"""

from __future__ import annotations

import argparse
import dataclasses
import random
from typing import Optional, Sequence


@dataclasses.dataclass
class SamplePlan:
    total_lines: int
    instances: int
    batch_size: float
    sample_size: float
    magnification: float

    @property
    def lines_to_get(self) -> int:
        # the reference samples 13× the sample size so per-chunk variance
        # averages out (benchmark.py:50)
        return int(self.sample_size * 13)

    def extrapolate(self, sample_seconds: float) -> float:
        """Full-run wall-clock estimate from a timed sample run."""
        return sample_seconds * self.magnification


def plan(total_lines: int, instances: int) -> SamplePlan:
    """Reference math (benchmark.py:30-42), including its edge cases."""
    batch_size = int(total_lines / instances) / 1.7 if instances else 0.0
    if total_lines < instances:
        instances = total_lines
        batch_size = 1.0
        sample_size = 1.0
    elif batch_size > 1000:
        sample_size = batch_size / 150
    else:
        sample_size = batch_size / 7
    magnification = batch_size / sample_size if sample_size else 0.0
    return SamplePlan(
        total_lines=total_lines,
        instances=instances,
        batch_size=batch_size,
        sample_size=sample_size,
        magnification=magnification,
    )


def sample_lines(
    lines: Sequence[str], p: SamplePlan, seed: Optional[int] = None
) -> list[str]:
    shuffled = list(lines)
    random.Random(seed).shuffle(shuffled)
    return shuffled[: p.lines_to_get]


def main(argv: Optional[list[str]] = None) -> None:
    parser = argparse.ArgumentParser(description="swarm-tpu benchmark sampler")
    parser.add_argument("input_file", help="input file containing targets")
    parser.add_argument("instances", type=int, help="number of instances")
    parser.add_argument("--out", default="sample.txt", help="sample output file")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument(
        "--rows-per-second",
        type=float,
        default=None,
        help="measured pipeline throughput (rows_per_second from the scan "
        "rollup: rows / execute-phase wall-clock) for a full-run estimate",
    )
    args = parser.parse_args(argv)

    with open(args.input_file) as f:
        lines = f.readlines()
    p = plan(len(lines), args.instances)
    print(f"Total lines: {p.total_lines}")
    print(f"Batch size: {p.batch_size}")
    print(f"Sample size: {p.sample_size}")
    print(f"Magnification factor: {p.magnification}")
    with open(args.out, "w") as f:
        f.writelines(sample_lines(lines, p, seed=args.seed))
    print(f"Sample written to {args.out}")
    if args.rows_per_second:
        secs = p.total_lines / args.rows_per_second
        print(f"Estimated full-run execute time: {secs:.2f}s "
              f"at {args.rows_per_second:.0f} rows/s")


if __name__ == "__main__":
    main()
