"""Template corpus → dense tensor database for the device match kernels.

Lowering strategy (designed for TPU/XLA, not a port — the reference
shells out to nuclei/nmap for this entire layer):

- Every *word-like* payload (word matchers, binary matchers, dsl
  ``contains`` conjuncts, regex required-literals) becomes a **word
  slot**: a (bytes, stream, case) triple. Slots of length ≥ 4 register a
  q-gram (8-gram, or 4-gram for short words) in per-(stream, case, q)
  hash tables — sorted unique h1 groups + entry arrays + a Bloom bitmap
  probed by the kernel. Tiny slots (1–3 bytes) take a dense shifted
  compare (exact). The kernel screens q-gram hits via 128 hash bits
  (entry h1/h2 + suffix-gram h1/h2), then **byte-verifies** each hit on
  device by gathering the window under ``slot_bytes``/``slot_len`` and
  comparing — a verified hit is *certain* (no host confirm), a failed
  compare is a proven non-match, and only slots longer than
  ``VERIFY_WIDTH`` (prefix-verified) stay uncertain.
- Matchers lower to records over those bits plus scalar features
  (status, part lengths): word/binary → slot-bucket reductions,
  status/size → scalar compares, simple dsl → conjunctive scalar
  programs (len/status/content_length) with optional residues (md5 → a
  digest check the host or the device md5 kernel confirms), regex → a
  prefilter slot whose hits are uncertain-by-construction.
- Matchers that cannot be soundly approximated (kval/json/xpath,
  literal-less regex, exotic dsl) force their template onto the
  **host-always** list — evaluated by the exact CPU oracle so overall
  parity stays 100%; the compiler reports how much of the corpus that
  tail is.
- Out-of-band parts (``interactsh_protocol``/``interactsh_request``)
  lower onto their own tiny device streams (oobp/oobr), filled from
  ``Response.oob_*`` by the worker's interaction listener
  (worker/oob.py); rows without interactions carry empty streams, so
  the no-listener behavior is the old constant-False — exactly.

Uncertainty contract (the parity invariant): a matcher's device bit is
exact unless its ``uncertain`` bit is set, and uncertain bits can only
be set when the underlying superset signal *fired* — absence of a hit is
always exact. Host confirmation therefore only runs on (row, template)
pairs whose verdict actually fired an uncertain matcher.
"""

from __future__ import annotations

import binascii
import dataclasses
import re
import sys
from typing import Optional

import numpy as np

from swarm_tpu.fingerprints import dslc, regexlin
from swarm_tpu.fingerprints.model import Matcher, Template
from swarm_tpu.ops import hashing
from swarm_tpu.ops.encoding import (
    HOST_ONLY_PARTS,
    STREAMS,
    lower_bytes_np,
    stream_for_part,
)

# ---------------------------------------------------------------------------
# Constants / enums (shared with ops.match / ops.verdict)
# ---------------------------------------------------------------------------

VERIFY_WIDTH = 64  # byte-exact verify cap; longer slots are prefix+host

# Matcher kinds
MK_CONST_FALSE = 0
MK_WORDS = 1  # word/binary/contains — slots under this matcher's condition
MK_STATUS = 2
MK_SIZE = 3
MK_SCALAR_DSL = 4  # conjunctive scalar program (+ optional residue)
MK_REGEX_PREFILTER = 5  # slot bit is a superset; hit ⇒ uncertain

# Scalar-program variable ids
SV_STATUS = 0
SV_LEN_BODY = 1
SV_LEN_HEADER = 2
SV_LEN_ALL = 3
SV_CONTENT_LENGTH = 4
SCALAR_VARS = 5

# Scalar-program comparison ops
SOP_EQ, SOP_NE, SOP_LT, SOP_GT, SOP_LE, SOP_GE, SOP_TRUE = range(7)

MAX_SCALAR_CONJUNCTS = 6
MAX_GROUP = 8  # max word slots sharing one (table, h1) group
HARD_GROUP = 64  # degrade ceiling when gram shedding fails (see below)

# Rough byte-commonness weights for picking the rarest q-gram of a word.
# Calibrated for the actual haystacks (HTML bodies, HTTP headers):
# markup/structural bytes are the MOST common there — "</title>",
# "\r\nServer:", "=\"" style grams recur in nearly every response, so a
# gram of markup bytes must never beat a gram of letters. (A weight
# inversion here once made every "…</title>" word share the
# "</title>" gram — one shared table group, mass candidate collisions.)
_COMMON = np.zeros(256, dtype=np.float32)
for _c in b"<>/\"'=.-_:;()\r\n\t ":
    _COMMON[_c] = 1.3
for _c in b"etaoinshrdlucmfwygpb":
    _COMMON[_c] = 1.0
for _c in b"ETAOINSHRDLU0123456789":
    _COMMON[_c] = 0.8
for _c in b"&?%+,![]{}":
    _COMMON[_c] = 0.9


def _gram_offsets_by_rarity(data: bytes, q: int) -> list[int]:
    """Candidate gram offsets, rarest window first."""
    if len(data) <= q:
        return [0]
    weights = _COMMON[np.frombuffer(data, dtype=np.uint8)]
    window_scores = np.convolve(weights, np.ones(q), mode="valid")
    return list(np.argsort(window_scores, kind="stable").astype(int))


# ---------------------------------------------------------------------------
# Regex required-literal extraction (prefilter factory)
# ---------------------------------------------------------------------------


MAX_LITERAL_ALTS = 16  # cap on any-of literal sets from alternations


def _lower_ascii(data: bytes) -> bytes:
    return bytes(lower_bytes_np(np.frombuffer(data, np.uint8)).tobytes())


# Strings present in ~every HTTP(HTML) response: a required literal that
# is (or sits inside) one of these fires on all traffic, so candidates
# containing only such members rank below any discriminating set.
_UBIQUITOUS = (
    b"<title>", b"</title>", b"<html", b"</html>", b"<head", b"</head>",
    b"<body", b"</body>", b"<div", b"</div>", b"<span", b"</span>",
    b"<link", b"<meta", b"<script", b"</script>", b"href=", b"src=",
    b"http://", b"https://", b"content-type", b"text/html", b"charset=",
    b"</a>", b"utf-8", b"class=", b"style=", b"width=", b"id=",
)


def _lit_rarity(lit: bytes) -> int:
    """Effective discriminating length of one literal: a literal that is
    itself (a piece of) boilerplate prunes nothing; one that merely
    *contains* boilerplate plus more is judged by its full length."""
    if any(lit in u for u in _UBIQUITOUS):
        return 1
    return len(lit)


# Beyond this many bytes a literal's extra length adds no pruning power
# (a 16-byte exact substring is already as discriminating as any), so
# member COUNT becomes the deciding cost: a digit-crossing expansion
# that multiplies one signature into 10 near-identical word slots
# ("…reposerver pro 0".."…pro 9") must lose to the single two-bytes-
# shorter run — the 10 slots share every rare gram, overflow one
# word-table hash group, and buy nothing.
_RARITY_CAP = 16


def _litset_score(cand: list[bytes]) -> tuple[int, int, int]:
    """(capped min member rarity, -member count, true min rarity):
    every member must be rare for the set to prune, since any member
    firing routes to confirm; past _RARITY_CAP, fewer members wins."""
    r = min(_lit_rarity(c) for c in cand)
    return (min(r, _RARITY_CAP), -len(cand), r)


def required_literal_set(
    pattern: str, min_len: int = 4, max_alts: int = MAX_LITERAL_ALTS,
    collect: Optional[list] = None,
) -> Optional[list[bytes]]:
    """A set S of lowered byte literals such that **every** match of
    ``pattern`` contains at least one s ∈ S as a substring.

    Walks the sre parse tree keeping a *set* of literal runs: an
    alternation multiplies the run set by each branch's full literal
    expansions (so ``(?:InvalidURI|NoSuchBucket)`` and case-permutation
    chains like ``(f|F)(i|I)…`` both resolve — the latter collapses to
    one literal after ASCII lowering, since the probe always runs on
    the lowered stream). Optional nodes (``X?``) multiply the run set
    by {""} ∪ expansions(X) so adjacency survives (``db[_-]?pw`` →
    {dbpw, db_pw, db-pw}, not {db}); where a group/alternation has no
    full expansion, its literal *prefix* expansions extend the runs
    before the flush (``[.](com|co.uk)`` → {.com, .co}) — a prefix is
    forced contiguous with the consumed left context, so the combined
    runs stay necessary. Other non-literal nodes flush the run set as
    a candidate. Returns the best candidate (longest minimum member,
    then fewest members) with every member ≥ min_len, or None.

    Soundness: a run set is only considered when every member reflects
    a byte sequence forced by one complete alternation path; ASCII
    lowering is sound because the device probes the lowered stream
    (non-A-Z bytes are untouched on both sides). Runs collected under
    case-insensitivity with non-ASCII bytes are rejected — Python folds
    Unicode there, device lowering is ASCII-only.

    ``collect``: optional list; every candidate that clears ``min_len``
    from a *mandatory* position (top-level concatenation, mandatory
    group bodies, branch-union sets — never branch-local sets) is
    appended. Each collected set is independently necessary, so the
    list is a CNF (AND of OR-sets) usable as a host-side gate
    (``required_literal_cnf``)."""
    try:
        tree = regexlin.parse_quiet(pattern)
    except re.error:
        return None

    global_ci = bool(tree.state.flags & re.IGNORECASE)
    best: list[Optional[list[bytes]]] = [None]
    # >0 ⇒ inside a branch-local walk: candidates there are necessary
    # only for that branch, not the whole pattern — never collected
    branch_local = [0]

    def consider(cand: list[bytes]) -> None:
        if not cand or any(len(c) < min_len for c in cand):
            return
        if collect is not None and not branch_local[0]:
            collect.append(sorted(cand))
        cur = best[0]
        if cur is None or _litset_score(cand) > _litset_score(cur):
            best[0] = cand

    def class_alts(arg, ci: bool) -> Optional[list[bytes]]:
        """Small literal character class [Gg] → its (lowered) bytes.
        ``\\d`` expands to 0-9: over the latin-1 decode the oracle
        matches on (cpu_ref._decode), every code point is ≤ 0xFF and
        the only Nd-category ones are ASCII digits, so the expansion
        is exact."""
        alts = set()
        for kind, val in arg:
            skind = str(kind)
            if skind == "CATEGORY" and str(val) == "CATEGORY_DIGIT":
                alts.update(b"0123456789"[i : i + 1] for i in range(10))
            elif skind != "LITERAL" or not (0 <= val < 256):
                return None
            else:
                if ci and val >= 0x80:
                    # Python folds Unicode over the latin-1 decode;
                    # ASCII lowering can't reproduce that, so the set
                    # would not be necessary
                    return None
                alts.add(_lower_ascii(bytes([val])))
            if len(alts) > max_alts:
                return None
        return sorted(alts)

    def expansions(seq, ci: bool) -> Optional[list[bytes]]:
        """All full literal expansions of ``seq`` (lowered, deduped):
        None if any part is not literal/branch/class/fixed-repeat, []
        if the sequence is DEAD (can never match — see below).
        Lowering is sound: the probe always scans the lowered stream.

        Deadness: the oracle matches over the latin-1 decode
        (cpu_ref._decode), whose code points are all ≤ 0xFF — a
        case-sensitive LITERAL above 0xFF (e.g. the ⚡ in tech-detect's
        amp matcher) can never match, so an alternation branch
        containing one contributes nothing and the LIVE branches'
        literals remain necessary. Under IGNORECASE this is unsound
        (U+212A KELVIN SIGN folds to 'k') and stays unsupported."""
        outs = [b""]

        def cross(alts: list[bytes]) -> bool:
            nonlocal outs
            outs = sorted({o + a for o in outs for a in alts})
            return len(outs) <= max_alts

        for op, arg in seq:
            opname = str(op)
            if opname == "LITERAL" and arg >= 0:
                if ci and arg >= 0x80:
                    return None  # Unicode folding ≠ ASCII lowering
                if arg > 0xFF:
                    return []  # dead: can't occur in latin-1 text
                if not cross([_lower_ascii(bytes([arg]))]):
                    return None
            elif opname == "IN":
                alts = class_alts(arg, ci)
                if alts is None or not cross(alts):
                    return None
            elif opname == "SUBPATTERN":
                child_ci = (ci or bool(arg[1] & re.IGNORECASE)) and not bool(
                    arg[2] & re.IGNORECASE
                )
                child = expansions(arg[3], child_ci)
                if child is None or not cross(child):
                    return None
                if child == []:
                    return []  # dead group ⇒ dead sequence
            elif opname == "BRANCH":
                alts = []
                saw_live = False
                for branch in arg[1]:
                    exp = expansions(branch, ci)
                    if exp is None:
                        return None
                    if exp == []:
                        continue  # dead branch: drop it
                    saw_live = True
                    alts.extend(exp)
                if not saw_live:
                    return []  # every branch dead ⇒ dead sequence
                if not cross(alts):
                    return None
            elif opname == "MAX_REPEAT" or opname == "MIN_REPEAT":
                lo, hi, child = arg
                if lo == 0 and int(hi) == 1:
                    # optional: each match contains zero or one copy —
                    # {""} ∪ expansions keeps the sequence literal
                    exp = expansions(child, ci)
                    if exp is None:
                        return None
                    if exp == []:
                        continue  # dead optional: only the 0-copy path
                    if not cross([b""] + exp):
                        return None
                    continue
                if lo != hi:
                    return None
                exp = expansions(child, ci)
                if exp is None:
                    return None
                if exp == [] and lo >= 1:
                    return []  # dead child with a mandatory copy
                for _ in range(int(lo)):
                    if not cross(exp):
                        return None
            elif opname == "AT":
                continue
            else:
                return None
        return outs

    def prefix_exps(seq, ci: bool) -> Optional[list[bytes]]:
        """Literal expansions of the longest expandable PREFIX of
        ``seq`` (every member ≥ 1 byte), or None. Every match of the
        sequence *starts* with one member, so extending the current
        runs by these preserves necessity-with-adjacency even when the
        tail of the sequence has no full expansion."""

        def crossed(base: list[bytes], alts: list[bytes]):
            new = sorted({o + a for o in base for a in alts})
            return new if len(new) <= max_alts else None

        outs = [b""]
        for op, arg in seq:
            opname = str(op)
            nxt = None
            stop_after = False
            if opname == "AT":
                continue
            elif opname == "LITERAL" and 0 <= arg <= 0xFF:
                if not (ci and arg >= 0x80):
                    nxt = crossed(outs, [_lower_ascii(bytes([arg]))])
            elif opname == "IN":
                alts = class_alts(arg, ci)
                if alts is not None:
                    nxt = crossed(outs, alts)
            elif opname == "SUBPATTERN":
                child_ci = (
                    ci or bool(arg[1] & re.IGNORECASE)
                ) and not bool(arg[2] & re.IGNORECASE)
                exp = expansions(arg[3], child_ci)
                if exp is not None and exp != []:
                    nxt = crossed(outs, exp)
                if nxt is None:
                    child = prefix_exps(arg[3], child_ci)
                    if child is not None:
                        nxt = crossed(outs, child)
                    stop_after = True  # tail of a partial group unknown
            elif opname == "BRANCH":
                exp = expansions([(op, arg)], ci)
                if exp is not None and exp != []:
                    nxt = crossed(outs, exp)
                if nxt is None:
                    pres = [prefix_exps(b, ci) for b in arg[1]]
                    if all(p is not None for p in pres):
                        union = sorted({m for p in pres for m in p})
                        nxt = crossed(outs, union)
                    stop_after = True
            elif opname == "MAX_REPEAT" or opname == "MIN_REPEAT":
                lo, hi, child = arg
                if lo >= 1:
                    exp = expansions(child, ci)
                    if exp is not None and exp != []:
                        nxt = crossed(outs, exp)
                        if nxt is not None and lo == hi:
                            for _ in range(int(lo) - 1):
                                nxt = crossed(nxt, exp)
                                if nxt is None:
                                    break
                        else:
                            stop_after = True  # variable tail
            if nxt is None:
                break
            outs = nxt
            if stop_after:
                break
        if outs == [b""] or not all(outs):
            return None
        return outs

    def nec_set(seq, ci: bool) -> Optional[list[bytes]]:
        """Best necessary literal set of a subsequence (its own walk).
        Branch-local: candidates found here are necessary only for one
        alternation branch, so CNF collection is suspended."""
        saved = best[0]
        best[0] = None
        branch_local[0] += 1
        walk(seq, ci)
        branch_local[0] -= 1
        out = best[0]
        best[0] = saved
        return out

    def walk(seq, ci: bool) -> None:
        # runs: every member lowered; every match of the consumed prefix
        # contains one member as a contiguous substring
        runs: list[bytes] = [b""]

        def runs_candidate() -> None:
            if all(runs) and runs != [b""]:
                consider(sorted(set(runs)))

        def flush() -> None:
            nonlocal runs
            runs_candidate()
            runs = [b""]

        def extend(alts: list[bytes]) -> None:
            nonlocal runs
            if len(alts) > 1:
                # The pre-extension runs are already a sound necessary
                # set (bytes forced by the consumed prefix — necessity
                # holds for any prefix of the walk). A multiplying
                # extension can score WORSE than what it extends: ten
                # digit variants of one signature tail share every rare
                # gram and overflow a word-table hash group, where the
                # one-member run prunes just as hard. Offer the cheap
                # set; _litset_score picks.
                runs_candidate()
            new = sorted({r + a for r in runs for a in alts})
            if len(new) > max_alts:
                flush()
            else:
                runs = new

        for op, arg in seq:
            opname = str(op)
            if opname == "LITERAL" and 0 <= arg < 256:
                if ci and arg >= 0x80:
                    flush()
                else:
                    extend([_lower_ascii(bytes([arg]))])
            elif opname == "IN":
                alts = class_alts(arg, ci)
                if alts is not None:
                    extend(alts)
                else:
                    flush()
            elif opname == "SUBPATTERN":
                # groups are transparent: expand inline when possible so
                # literals on both sides stay adjacent
                child_ci = (ci or bool(arg[1] & re.IGNORECASE)) and not bool(
                    arg[2] & re.IGNORECASE
                )
                exp = expansions(arg[3], child_ci)
                if exp is not None:
                    extend(exp)
                else:
                    # partial group: its literal prefix is forced
                    # contiguous with the consumed left context —
                    # extend before flushing so e.g. [.](com|co.uk)
                    # keeps the dot (".com"/".co", not "com"/"co")
                    pre = prefix_exps(arg[3], child_ci)
                    if pre is not None:
                        extend(pre)
                    flush()
                    walk(arg[3], child_ci)
                    flush()
            elif opname == "BRANCH":
                exp = expansions([(op, arg)], ci)
                if exp is not None:
                    extend(exp)
                    continue
                pres = [prefix_exps(b, ci) for b in arg[1]]
                if all(p is not None for p in pres):
                    union = sorted({m for p in pres for m in p})
                    if len(union) <= max_alts:
                        extend(union)
                flush()
                # every branch with its own necessary set → the union
                # is necessary for the alternation as a whole
                sets = [nec_set(b, ci) for b in arg[1]]
                if all(s is not None for s in sets):
                    union = sorted({m for s in sets for m in s})
                    if len(union) <= max_alts:
                        consider(union)
            elif opname == "MAX_REPEAT" or opname == "MIN_REPEAT":
                lo, hi, child = arg
                if lo >= 1:
                    exp = expansions(child, ci)
                    if exp is not None:
                        # one guaranteed copy keeps runs adjacent; a
                        # variable tail breaks adjacency afterwards
                        extend(exp)
                        if hi == lo:
                            for _ in range(int(lo) - 1):
                                extend(exp)
                        else:
                            flush()
                    else:
                        flush()
                        walk(child, ci)
                        flush()
                elif lo == 0 and int(hi) == 1:
                    # optional node: every match contains zero or one
                    # copy — {""} ∪ expansions keeps runs adjacent
                    # (db[_-]?pw → dbpw|db_pw|db-pw)
                    exp = expansions(child, ci)
                    if exp is not None and exp != []:
                        extend([b""] + exp)
                    elif exp == []:
                        pass  # dead optional: only the 0-copy path
                    else:
                        flush()
                else:
                    flush()
            elif opname == "AT":
                # zero-width assertion: consumes nothing, so bytes on
                # either side are still adjacent in any match
                continue
            else:
                # ANY, CATEGORY, GROUPREF… — not a required literal
                flush()
        flush()

    walk(tree, global_ci)
    return best[0]


def required_literal_ladder(
    pattern: str, min_lens: tuple = (4, 3, 2)
) -> Optional[list]:
    """``required_literal_set`` at the first ``min_len`` that yields a
    set — the shared relax ladder for every literal gate (device
    superset lowering, extraction prefilters, fastre's host gate), so
    the host and device can never disagree about which literals a
    pattern requires."""
    for ml in min_lens:
        s = required_literal_set(pattern, min_len=ml)
        if s is not None:
            return s
    return None


def required_literal_cnf(
    pattern: str, min_len: int = 1, max_groups: int = 8
) -> Optional[list[list[bytes]]]:
    """Every *independently necessary* literal OR-set of ``pattern``
    (CNF: a match must contain ≥1 member of EVERY group). The groups
    come from mandatory positions of the parse walk — top-level
    concatenation segments, mandatory group bodies, and branch-union
    sets — never from inside a single alternation branch.

    A conjunctive host gate over all groups is strictly stronger than
    the single best set (``[a-z0-9]{4,}@[a-z0-9]+[.](com|…)`` requires
    BOTH "@" AND one of ".com"/".org"/… — either absence is an exact
    no-match proof), while each group alone stays sound for the device
    prefilter. Deduped, best-scored first, capped at ``max_groups``."""
    groups: list = []
    required_literal_set(pattern, min_len=min_len, collect=groups)
    if not groups:
        return None
    seen = set()
    uniq = []
    for g in groups:
        key = tuple(g)
        if key in seen:
            continue
        # a group that is a superset of an already-kept group adds no
        # pruning power in the absent-check direction; keep it anyway
        # only if distinct — the cap keeps the gate cheap
        seen.add(key)
        uniq.append(g)
    uniq.sort(key=_litset_score, reverse=True)
    return uniq[:max_groups]


def required_literal(pattern: str, min_len: int = 4) -> Optional[bytes]:
    """Single required literal (longest member of a singleton set)."""
    lits = required_literal_set(pattern, min_len=min_len, max_alts=1)
    return lits[0] if lits else None


def full_literal_expansions(
    pattern: str, max_alts: int = MAX_LITERAL_ALTS
) -> Optional[tuple[list[bytes], bool]]:
    """(alternatives, case_insensitive) when ``re.search(pattern, s)``
    is *exactly* equivalent to "s contains one of the alternatives" —
    i.e. the pattern is pure literals/alternations/fixed repeats with
    no classes, anchors, or variable quantifiers. Alternatives are
    lowered when ci (probe the lowered stream), raw bytes otherwise.

    This turns literal-shaped corpus "regexes" (MySqlException,
    (?i)x-frame-options, …) into exact word slots instead of
    uncertain prefilters.
    """
    try:
        tree = regexlin.parse_quiet(pattern)
    except re.error:
        return None
    ci = bool(tree.state.flags & re.IGNORECASE)

    def expand(seq, ci: bool) -> Optional[list[bytes]]:
        outs = [b""]
        for op, arg in seq:
            opname = str(op)
            if opname == "LITERAL" and 0 <= arg < 256:
                if ci and arg >= 0x80:
                    return None  # Unicode folding ≠ ASCII lowering
                b = bytes([arg])
                outs = [o + (_lower_ascii(b) if ci else b) for o in outs]
            elif opname == "SUBPATTERN":
                child_ci = (ci or bool(arg[1] & re.IGNORECASE)) and not bool(
                    arg[2] & re.IGNORECASE
                )
                if child_ci != ci:
                    return None  # mixed-case scopes don't map to one slot case
                child = expand(arg[3], ci)
                if child is None:
                    return None
                outs = [o + c for o in outs for c in child]
            elif opname == "BRANCH":
                alts = []
                for branch in arg[1]:
                    exp = expand(branch, ci)
                    if exp is None:
                        return None
                    alts.extend(exp)
                outs = [o + a for o in outs for a in alts]
            elif opname == "MAX_REPEAT" or opname == "MIN_REPEAT":
                lo, hi, child = arg
                if lo != hi:
                    return None
                exp = expand(child, ci)
                if exp is None:
                    return None
                for _ in range(int(lo)):
                    outs = [o + c for o in outs for c in exp]
                    if len(outs) > max_alts:
                        return None
            else:
                # IN, ANY, AT, CATEGORY… — not a pure literal pattern
                return None
            if len(outs) > max_alts:
                return None
        return outs

    outs = expand(tree, ci)
    if outs is None or any(not o for o in outs):
        return None  # an empty alternative matches everything
    return sorted(set(outs)), ci


# ---------------------------------------------------------------------------
# DSL lowering: conjunctive scalar programs + contains/md5 residues
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ScalarProgram:
    conjuncts: list[tuple[int, int, float]]  # (var, op, value)
    contains: list[tuple[bytes, str, bool]]  # (needle, stream, case_insensitive)
    residue: bool = False  # sha/mmh3 residue → hit needs host confirm
    never: bool = False  # statically unsatisfiable (e.g. "AbC" in tolower(x))
    any_of: bool = False  # contains are OR-reduced (no conjuncts/residue)
    negated: bool = False  # value = NOT(OR of contains) — !contains() exprs
    # md5(body) == "<hex>" conjunct, lowered to the device digest
    # comparison (ops/md5.py) — exact, no host confirm
    md5: Optional[bytes] = None
    # conjuncts of the form !contains(...)/!regex('literal',...): every
    # listed needle must be ABSENT (NOT(OR)) — the missing-header
    # template shape (misconfiguration/http-missing-security-headers)
    neg_contains: list = dataclasses.field(default_factory=list)


def _lower_contains_call(node):
    """contains(part_var, "lit") → (needle, stream, ci) | "never" | None."""
    if not (node[0] == "call" and node[1] == "contains" and len(node[2]) == 2):
        return None
    hay, needle = node[2]
    loc = _part_stream_of_var(hay)
    if not (loc and needle[0] == "lit" and isinstance(needle[1], str)):
        return None
    stream, wrap = loc
    data = needle[1].encode()
    if len(data) == 0:
        return None
    if wrap is None:
        return (data, stream, False)
    if wrap == "lower":
        # an uppercase needle can never occur in a lowercased haystack
        return (data, stream, True) if data == data.lower() else "never"
    return (data.lower(), stream, True) if data == data.upper() else "never"


def _contains_equiv(node):
    """Substring-equivalence of a dsl node: a list of (needle, stream,
    ci) tuples whose OR is *exactly* the node's value, or "never"
    (statically False), or None (no equivalence).

    Covers contains() calls and pure-literal regex()/=~ applications —
    ``regex('(?i)x-frame-options', all_headers)`` is exactly a ci
    substring check, so security-header style matchers lower without
    any prefilter uncertainty.
    """
    c = _lower_contains_call(node)
    if c is not None:
        return c if c == "never" else [c]
    if node[0] == "call" and node[1] == "regex" and len(node[2]) == 2:
        pat, hay = node[2]
    elif node[0] == "bin" and node[1] == "=~":
        hay, pat = node[2], node[3]
    else:
        return None
    if pat[0] != "lit" or not isinstance(pat[1], str):
        return None
    loc = _part_stream_of_var(hay)
    if loc is None:
        return None
    stream, wrap = loc
    full = full_literal_expansions(pat[1])
    if full is None:
        return None
    alts, pat_ci = full
    out = []
    for alt in alts:
        if pat_ci or wrap is None:
            # ci alternatives are pre-lowered; raw ones keep their case
            out.append((alt, stream, pat_ci))
        elif wrap == "lower":
            if alt != alt.lower():
                continue  # can't occur in a lowered haystack
            out.append((alt, stream, True))
        else:  # upper wrap, case-sensitive pattern
            if alt != alt.upper():
                continue
            out.append((alt.lower(), stream, True))
    return out if out else "never"


def _regex_conjunct_prefilter(node):
    """regex("pat", part_var) / part_var =~ "pat" → one contains tuple
    when the pattern has a singleton required-literal set (prog.contains
    entries are AND-reduced, so only singletons are expressible)."""
    if node[0] == "call" and node[1] == "regex" and len(node[2]) == 2:
        pat, hay = node[2]
    elif node[0] == "bin" and node[1] == "=~":
        hay, pat = node[2], node[3]
    else:
        return None
    if pat[0] != "lit" or not isinstance(pat[1], str):
        return None
    loc = _part_stream_of_var(hay)
    if loc is None:
        return None
    stream, _wrap = loc  # tolower/toupper wrap is moot: probe is lowered
    lits = required_literal_set(pat[1])
    if lits is None or len(lits) != 1:
        return None
    return (lits[0], stream, True)


def _lower_negated_contains_conj(node):
    """``!contains(a) && !contains(b) && …`` → the [a, b, …] slot list
    (the value is NOT(a || b || …)); None if any conjunct differs.
    A "never" branch (statically-absent needle) drops out: !never ≡ True
    is the AND identity."""
    if node[0] == "bin" and node[1] == "&&":
        lhs = _lower_negated_contains_conj(node[2])
        if lhs is None:
            return None
        rhs = _lower_negated_contains_conj(node[3])
        if rhs is None:
            return None
        return lhs + rhs
    if node[0] == "un" and node[1] == "!":
        eq = _contains_equiv(node[2])
        if eq is None:
            return None
        return [] if eq == "never" else eq
    return None


def _lower_or_contains(node):
    """Flatten an ||-tree of contains() calls to its slot list, or None
    if the tree has any other node. Statically-false branches drop out
    (OR identity); an all-false tree returns []."""
    if node[0] == "bin" and node[1] == "||":
        lhs = _lower_or_contains(node[2])
        if lhs is None:
            return None
        rhs = _lower_or_contains(node[3])
        if rhs is None:
            return None
        return lhs + rhs
    eq = _contains_equiv(node)
    if eq is None:
        return None
    return [] if eq == "never" else eq


_CMP_OPS = {"==": SOP_EQ, "!=": SOP_NE, "<": SOP_LT, ">": SOP_GT, "<=": SOP_LE, ">=": SOP_GE}
_SWAP = {SOP_LT: SOP_GT, SOP_GT: SOP_LT, SOP_LE: SOP_GE, SOP_GE: SOP_LE}


def _scalar_var(node) -> Optional[int]:
    if node[0] == "var" and node[1] == "status_code":
        return SV_STATUS
    if node[0] == "var" and node[1] == "content_length":
        return SV_CONTENT_LENGTH
    if node[0] == "call" and node[1] == "len" and len(node[2]) == 1:
        inner = node[2][0]
        if inner[0] == "var":
            return {
                "body": SV_LEN_BODY,
                "header": SV_LEN_HEADER,
                "all_headers": SV_LEN_HEADER,
                "raw": SV_LEN_ALL,
            }.get(inner[1])
    return None


def _part_stream_of_var(node) -> Optional[tuple[str, Optional[str]]]:
    """(stream, case_wrap) for body/header vars; case_wrap ∈ {None,
    'lower', 'upper'} from a tolower()/toupper() wrapper."""
    wrap: Optional[str] = None
    while node[0] == "call" and node[1] in ("tolower", "toupper") and len(node[2]) == 1:
        wrap = "lower" if node[1] == "tolower" else "upper"
        node = node[2][0]
    if node[0] == "var":
        stream = {
            "body": "body",
            "header": "header",
            "all_headers": "header",
            "raw": "all",
            # OOB interaction vars lower onto their own (tiny) streams
            # — e.g. contains(interactsh_protocol, "dns") in
            # cves/2022/CVE-2022-26134.yaml-style dsl matchers
            "interactsh_protocol": "oobp",
            "interactsh_request": "oobr",
        }.get(node[1])
        if stream:
            return stream, wrap
    return None


_HASH_FNS = ("md5", "sha1", "sha256", "mmh3")


def lower_dsl(ast) -> Optional[ScalarProgram]:
    """Lower one dsl expression to a scalar program.

    Top-level conjuncts that fit the supported shape (scalar compares,
    contains/part-equality, hash equality, negated contains) lower
    exactly. Any other conjunct is *dropped*, keeping its required
    literal (if one exists) as a contains prefilter and flagging
    ``residue`` — the program is then a sound necessary condition whose
    fired rows are host-confirmed per matcher (sound under negation
    too: uncertainty is captured pre-negation, and a non-fired superset
    is exactly False pre-negation). None is only returned for
    whole-expression shapes with no conjunctive form (handled by the
    or-shape branches below returning None).
    """
    prog = ScalarProgram(conjuncts=[], contains=[])

    def handle(node) -> bool:
        ok = handle_exact(node)
        if not ok:
            # Drop the conjunct, keep its required literal (if any) as
            # a contains prefilter, and flag the residue: the lowered
            # program is a sound necessary condition whose fired rows
            # are host-confirmed PER MATCHER (m_residue & fired ⇒
            # m_unc) — this keeps one exotic conjunct from demoting a
            # whole op to the host-confirmed prefilter path. Sound for
            # negated matchers too: uncertainty is captured
            # pre-negation, and a non-fired superset is exactly False
            # pre-negation.
            c = _regex_conjunct_prefilter(node)
            if c is not None:
                prog.contains.append(c)
            prog.residue = True
            return True
        return ok

    def handle_exact(node) -> bool:
        if node[0] == "bin" and node[1] == "&&":
            return handle(node[2]) and handle(node[3])
        if node[0] == "bin" and node[1] in _CMP_OPS:
            op = _CMP_OPS[node[1]]
            lhs, rhs = node[2], node[3]
            for a, b, swapped in ((lhs, rhs, False), (rhs, lhs, True)):
                var = _scalar_var(a)
                if var is not None and b[0] == "lit" and isinstance(b[1], (int, float)):
                    real_op = _SWAP.get(op, op) if swapped else op
                    prog.conjuncts.append((var, real_op, float(b[1])))
                    return True
            # whole-part string equality:  body == "literal"  — exactly
            # len(part)==len(lit) AND contains(part, lit) (a substring
            # of equal length IS the part). The evaluator compares
            # utf-8 bytes (_cmp_coerce/_to_bytes) and tolower is ASCII
            # bytes.lower(), both matching the device streams.
            if op == SOP_EQ:
                for a, b in ((lhs, rhs), (rhs, lhs)):
                    loc = _part_stream_of_var(a)
                    if not (
                        loc and b[0] == "lit" and isinstance(b[1], str)
                    ):
                        continue
                    stream, wrap = loc
                    data = b[1].encode("utf-8", "surrogateescape")
                    if wrap == "lower" and data != data.lower():
                        prog.never = True  # uppercase can't survive
                        return True
                    if wrap == "upper" and data != data.upper():
                        prog.never = True
                        return True
                    lenvar = {
                        "body": SV_LEN_BODY,
                        "header": SV_LEN_HEADER,
                        "all": SV_LEN_ALL,
                    }.get(stream)
                    if lenvar is None:
                        # no scalar length var for this stream (oob):
                        # can't express whole-part equality exactly —
                        # drop to the residue path
                        continue
                    prog.conjuncts.append(
                        (lenvar, SOP_EQ, float(len(data)))
                    )
                    if data:
                        prog.contains.append(
                            (
                                data.lower() if wrap else data,
                                stream,
                                wrap is not None,
                            )
                        )
                    return True
            # hash equality:  md5(body) == "…"  (either side). The
            # md5-of-plain-body shape lowers to the on-device digest
            # compare (ops/md5.py) — exact; other hash fns / wrapped
            # args stay residues (host confirms fired rows).
            for a, b in ((lhs, rhs), (rhs, lhs)):
                if (
                    op == SOP_EQ
                    and a[0] == "call"
                    and a[1] in _HASH_FNS
                    and b[0] == "lit"
                    and isinstance(b[1], str)
                ):
                    digest = None
                    if (
                        a[1] == "md5"
                        and list(a[2]) == [("var", "body")]
                        and re.fullmatch(r"[0-9a-fA-F]{32}", b[1])
                    ):
                        digest = bytes.fromhex(b[1].lower())
                    if digest is not None:
                        if prog.md5 is not None and prog.md5 != digest:
                            prog.never = True  # two different body digests
                        prog.md5 = digest
                    else:
                        prog.residue = True
                    return True
            return False
        if node[0] == "un" and node[1] == "!":
            # negated substring conjunct: !contains(...) / !regex('lit')
            # ≡ "none of the needles present" — slot bits are exact, so
            # negation is exact (per-matcher neg_contains bucket)
            eq = _contains_equiv(node[2])
            if eq == "never":
                return True  # !False — vacuous conjunct
            if eq is not None:
                prog.neg_contains.extend(eq)
                return True
            return False
        eq = _contains_equiv(node)
        if eq is not None:
            if eq == "never":
                prog.never = True
                return True
            if len(eq) == 1:
                prog.contains.append(eq[0])
                return True
            # an embedded multi-alternative OR can't sit in the AND
            # bucket — only the whole-expression or-shape handles it
            return False
        return False

    # the whole expression is an OR over contains() calls — exactly an
    # OR-reduced slot bucket (jsf-detection-style fingerprint dsl)
    ors = _lower_or_contains(ast)
    if ors is not None:
        if not ors:
            return ScalarProgram(conjuncts=[], contains=[], never=True)
        # a singleton stays conjunctive so AND-merging keeps working
        return ScalarProgram(conjuncts=[], contains=ors, any_of=len(ors) > 1)

    # De Morgan: !contains(a) [&& !contains(b)…] ≡ NOT(a || b) — an
    # OR-reduced bucket under matcher-level negation (exact, since the
    # slots themselves are byte-verified)
    negs = _lower_negated_contains_conj(ast)
    if negs is not None:
        if not negs:
            # every negated branch is statically absent ⇒ always True
            return ScalarProgram(conjuncts=[], contains=[])
        return ScalarProgram(
            conjuncts=[], contains=negs, any_of=True, negated=True
        )

    handle(ast)  # always succeeds: unsupported conjuncts drop to residue
    if len(prog.conjuncts) > MAX_SCALAR_CONJUNCTS:
        # dropping conjuncts keeps the necessary-condition property;
        # the residue flag host-confirms fired rows per matcher
        prog.conjuncts = prog.conjuncts[:MAX_SCALAR_CONJUNCTS]
        prog.residue = True
    return prog


def _merge_dsl_progs(
    progs: list[ScalarProgram], condition: str, superset: bool = False
) -> Optional[ScalarProgram]:
    """Merge one program per dsl expression under the matcher's
    expression-list condition. Exact when the shapes allow it; with
    ``superset=True`` an OR-list weakens each branch to its most
    selective contains (a sound necessary condition), never failing
    unless some branch has no contains at all."""
    if len(progs) == 1:
        return progs[0]
    if condition == "and":
        if any(p.never for p in progs):
            return ScalarProgram(conjuncts=[], contains=[], never=True)
        negated = [p for p in progs if p.negated]
        plain = [p for p in progs if not p.negated]
        if negated and not plain:
            # !(A) && !(B) ≡ !(A ∪ B): one OR bucket under negation
            return ScalarProgram(
                conjuncts=[],
                contains=[c for p in negated for c in p.contains],
                any_of=True,
                negated=True,
            )
        if negated and not any(p.residue for p in negated):
            # a negated-OR branch is exactly a neg_contains conjunct:
            # NOT(OR(needles)) ≡ "none present" — fold it into the AND
            # bucket instead of failing the merge (the
            # missing-security-headers matcher shape: !regex(lit) in
            # one expression, scalar compares in the next)
            fold = ScalarProgram(
                conjuncts=[],
                contains=[],
                neg_contains=[c for p in negated for c in p.contains],
            )
            plain = plain + [fold]
            negated = []
        if negated or any(p.any_of for p in plain):
            # negated/OR-group members can't fold into the AND bucket;
            # superset mode drops them (widening an AND is sound)
            if not superset:
                return None
            plain = [p for p in plain if not p.any_of]
            out = _merge_dsl_progs(
                plain or [ScalarProgram(conjuncts=[], contains=[])],
                "and",
                superset=True,
            )
            out.residue = True
            return out
        out = ScalarProgram(conjuncts=[], contains=[])
        for p in plain:
            out.conjuncts += p.conjuncts
            out.contains += p.contains
            out.neg_contains += p.neg_contains
            out.residue |= p.residue
            if p.md5 is not None:
                if out.md5 is not None and out.md5 != p.md5:
                    out.never = True
                out.md5 = p.md5
        if len(out.conjuncts) > MAX_SCALAR_CONJUNCTS:
            if not superset:
                return None
            out.conjuncts = out.conjuncts[:MAX_SCALAR_CONJUNCTS]
            out.residue = True
        return out
    # condition "or"
    live = [p for p in progs if not p.never]
    if not live:
        return ScalarProgram(conjuncts=[], contains=[], never=True)
    if any(
        not p.contains
        and not p.conjuncts
        and not p.residue
        and p.md5 is None
        and not p.neg_contains
        for p in live
    ):
        # an always-True branch (e.g. every negated needle statically
        # absent) makes the whole OR always True
        return ScalarProgram(conjuncts=[], contains=[])
    if any(p.negated for p in live):
        return None  # !(…) under OR has no bucket form
    if all(
        not p.conjuncts
        and not p.residue
        and p.md5 is None
        and not p.neg_contains
        # AND-reduced multi-contains branches can't flatten into an OR
        and (p.any_of or len(p.contains) == 1)
        for p in live
    ):
        return ScalarProgram(
            conjuncts=[],
            contains=[c for p in live for c in p.contains],
            any_of=True,
        )
    if not superset:
        return None
    picked = []
    for p in live:
        if not p.contains:
            return None  # a literal-less OR branch widens to always-True
        if p.any_of:
            # the branch is itself an OR: every member must stay (the
            # union is the branch's necessary condition)
            picked.extend(p.contains)
        else:
            # AND branch: any single member is a sound weakening
            picked.append(max(p.contains, key=lambda c: len(c[0])))
    return ScalarProgram(conjuncts=[], contains=picked, any_of=True, residue=True)


# ---------------------------------------------------------------------------
# The compiled database
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class WordTable:
    """One (stream, case, gram-size) hash table.

    A window hit must match the entry's (h1, h2) *and* the word's
    suffix-gram hashes at position ``pos + suf_delta`` — 128 hash bits
    total, computed entirely from the rolling-hash arrays the kernel
    already has (no byte gathers). Hits are still marked *uncertain*
    and host-confirmed, so a hash collision can never corrupt a verdict;
    the hashes exist to make candidate traffic ≈ true-hit traffic.
    """

    stream: str
    lowered: bool
    q: int
    group_h1: np.ndarray  # uint32 [G] sorted unique
    entry_start: np.ndarray  # int32 [G]
    entry_count: np.ndarray  # int32 [G]
    entry_h2: np.ndarray  # uint32 [E]
    entry_slot: np.ndarray  # int32 [E]
    entry_off: np.ndarray  # int32 [E] gram offset within the slot bytes
    entry_len: np.ndarray  # int32 [E] true word length
    entry_suf_delta: np.ndarray  # int32 [E] = (len - q) - off  (suffix pos - window pos)
    entry_suf_h1: np.ndarray  # uint32 [E]
    entry_suf_h2: np.ndarray  # uint32 [E]
    bloom: np.ndarray  # uint32 [BLOOM_WORDS]
    max_group: int = 1

    @property
    def num_groups(self) -> int:
        return int(self.group_h1.shape[0])


@dataclasses.dataclass
class IndexBucket:
    """One width-class of a ragged index table.

    ``rows[i]`` owns ``idx[i, :width]``; rows with fewer real entries are
    padded by repeating their first entry (neutral for both AND and OR
    reductions).
    """

    width: int
    rows: np.ndarray  # int32 [NB] — owner ids (matcher / op / template)
    idx: np.ndarray  # int32 [NB, width]


def bucket_ragged(ragged: list[list[int]], owner_count: int) -> list[IndexBucket]:
    """Ragged owner→members lists → power-of-two width buckets.

    Total gather volume stays Σ|members| × (≤2) instead of
    owners × max(|members|).
    """
    by_width: dict[int, list[tuple[int, list[int]]]] = {}
    for owner, members in enumerate(ragged):
        if not members:
            continue
        width = 1
        while width < len(members):
            width *= 2
        by_width.setdefault(width, []).append((owner, members))
    buckets = []
    for width in sorted(by_width):
        rows = np.array([o for o, _ in by_width[width]], dtype=np.int32)
        idx = np.zeros((len(rows), width), dtype=np.int32)
        for i, (_o, members) in enumerate(by_width[width]):
            for j in range(width):
                idx[i, j] = members[j] if j < len(members) else members[0]
        buckets.append(IndexBucket(width=width, rows=rows, idx=idx))
    return buckets


@dataclasses.dataclass
class CompiledDB:
    # --- word slots ---
    slot_bytes: np.ndarray  # uint8 [NW, VERIFY_WIDTH] (lowered for ci slots)
    slot_len: np.ndarray  # int32 [NW] true length (may exceed VERIFY_WIDTH)
    slot_long: np.ndarray  # bool [NW] — len > VERIFY_WIDTH ⇒ hit is uncertain
    tables: list[WordTable]
    # tiny slots, dense path: per (stream, lowered) padded byte matrix
    tiny_bytes: np.ndarray  # uint8 [NTINY, TINY_MAX]
    tiny_len: np.ndarray  # int32 [NTINY]
    tiny_slot: np.ndarray  # int32 [NTINY]
    tiny_stream: np.ndarray  # int32 [NTINY] index into STREAMS
    tiny_lowered: np.ndarray  # bool [NTINY]

    # --- matchers ---
    m_kind: np.ndarray  # int32 [NM]
    m_negative: np.ndarray  # bool [NM]
    m_cond_and: np.ndarray  # bool [NM]
    m_slot_buckets: list  # list[IndexBucket] matcher → word-slot ids
    # negated-contains bucket: matcher requires NONE of these slots to
    # be present (http-missing-security-headers-style dsl conjuncts)
    m_negslot_buckets: list  # list[IndexBucket] matcher → word-slot ids
    m_scalar: np.ndarray  # float32 [NM, MAX_SCALAR_CONJUNCTS, 3] (var, op, val)
    m_residue: np.ndarray  # bool [NM] — scalar pass still needs host confirm
    # device md5 digest equality (ops/md5.py): md5(body) == digest
    m_md5: np.ndarray  # uint32 [NM, 4] little-endian digest words
    m_md5_check: np.ndarray  # bool [NM]

    # --- device regex verify (ops/regexdev.py) ---
    # matchers whose every pattern compiled to linear shift-and
    # programs: fired rows re-check exactly on device, no host confirm
    rx_m_ids: np.ndarray  # int32 [NRXM] device matcher ids
    rx_seq_slot_buckets: list  # list[IndexBucket] seq → gate slot ids
    rx_seq_always: np.ndarray  # bool [NSEQ] — no gate: scan every row
    rx_seq_matcher: np.ndarray  # int32 [NSEQ] → index into rx_m_ids
    rx_seq_stream: np.ndarray  # int32 [NSEQ] index into STREAMS
    rx_seq_ci: np.ndarray  # bool [NSEQ] — run on the lowered stream
    rx_classes: np.ndarray  # uint32 [NSEQ, RX_MAX_M, 8] byte-class bitmaps
    rx_bytemap: np.ndarray  # uint32 [NSEQ, 256, L] byte → state-lane bits
    rx_m_count: np.ndarray  # int32 [NSEQ] positions used
    rx_seed: np.ndarray  # uint32 [NSEQ, L] start-closure mask
    rx_skip: np.ndarray  # uint32 [NSEQ, L] skippable positions
    rx_accept: np.ndarray  # uint32 [NSEQ, L] accepting positions
    rx_self: np.ndarray  # uint32 [NSEQ, L] self-loop positions
    rx_anchored: np.ndarray  # bool [NSEQ] — \A/^: seed only at byte 0
    rx_end_mode: np.ndarray  # int32 [NSEQ] — regexlin.END_* ($ / \Z)
    rx_start_wb: np.ndarray  # bool [NSEQ] — leading \b seed guard
    rx_end_wb: np.ndarray  # bool [NSEQ] — trailing \b accept guard
    rx_max_skip_run: int
    m_status: np.ndarray  # int32 [NM, MAX_STATUS] (pad = -1)
    m_size: np.ndarray  # int32 [NM, MAX_STATUS] (pad = -1)
    m_size_stream: np.ndarray  # int32 [NM] stream index for size matchers

    # --- operations & templates ---
    op_cond_and: np.ndarray  # bool [NOP]
    op_prefilter: np.ndarray  # bool [NOP] — superset-lowered: fired ⇒ host confirm
    op_m_buckets: list  # list[IndexBucket] op → matcher ids
    t_op_buckets: list  # list[IndexBucket] template → op ids
    t_prefilter: np.ndarray  # bool [NT] — any op superset-lowered (reporting)

    # host-side provenance (sparse confirmation, engine.py): device ids
    # back to source template/operation/matcher indices + ragged lists
    m_src: np.ndarray  # int32 [NM, 3] (template_idx, op_local, matcher_local)
    # (extractor_local, pattern_idx) for synthesized per-pattern
    # extraction prefilters; (-1, -1) for everything else
    m_ext_src: np.ndarray  # int32 [NM, 2]
    op_src: np.ndarray  # int32 [NOP, 2] (template_idx, op_local)
    op_matchers: list  # list[list[int]] op id → device matcher ids
    t_ops: list  # list[list[int]] template id → device op ids

    template_ids: list  # str [NT] — device-evaluated templates
    host_always: list  # list[Template] — exact-CPU-only tail
    templates: list  # the NT Template objects (for host confirmation)
    stats: dict

    # --- workflow DAG gate planes (docs/WORKFLOWS.md) ---
    # class-attribute default so pre-workflow dbcache pickles unpickle
    # to a plan-less db (engine then keeps the host twin for workflows)
    wf: Optional["WorkflowPlan"] = None

    def __getstate__(self):
        # the derived device layout (build_device_layout cache) must
        # not ride dbcache pickles: it duplicates every array and is
        # cheap to rebuild per process
        state = dict(self.__dict__)
        state.pop("_device_layout", None)
        return state

    @property
    def num_slots(self) -> int:
        return int(self.slot_bytes.shape[0])

    @property
    def num_templates(self) -> int:
        return len(self.template_ids)

    def rx_k_pairs(self, batch_rows: int) -> int:
        """Regex-verify compaction budget for one batch: up to 8 gated
        fires per row plus every always-on sequence's guaranteed row.
        Shared by the single-chip and sharded paths so overflow (and
        therefore host-confirm volume) behaves identically."""
        return (8 + int(self.rx_seq_always.sum())) * batch_rows


# ---------------------------------------------------------------------------
# Workflow DAG lowering (docs/WORKFLOWS.md)
# ---------------------------------------------------------------------------
# A workflow's trigger→subtemplate DAG flattens to DNF: every leaf emit
# (workflow id, reported template id) is reached through one or more
# conjunctions of *conditions* — trigger hits and named-matcher gates.
# Conditions reference the verdict planes eval_verdicts already builds,
# so the gate-apply stage is a gather + Kleene AND/OR over the batch.

#: condition kinds (cond_kind values)
WFC_HIT_DEV = 0  # device template verdict column (cond_idx = t_idx)
WFC_OP = 1  # device operation verdict (AND-op gate: op ⇒ matcher)
WFC_MATCHER = 2  # device matcher verdict (OR-op gate: matcher ⇒ op)
WFC_HIT_HOST = 3  # template not device-lowered — host hit set decides
WFC_GATE_HOST = 4  # gate needs the exact CPU oracle (cpu_ref names)

#: DNF shape caps — a workflow that exceeds them is NOT device-lowered
#: (it stays on the bit-identical host twin), never silently truncated
WF_MAX_CONDS = 8  # conditions per term (bounds DAG nesting depth)
WF_MAX_TERMS = 4096  # corpus-wide term budget
WF_MAX_TERMS_PER_WF = 512  # per-workflow fan-out budget


@dataclasses.dataclass
class WorkflowPlan:
    """Device-resident workflow gate tables (one per CompiledDB).

    Kleene semantics ride the existing verdict planes: a term is
    certainly-false as soon as one cond is certainly-false (the
    dominant no-trigger case — decided on device), certainly-true only
    when every cond is certainly-true; host kinds (3/4) are
    (False, uncertain) on device and resolved per row at condition
    granularity by the runner.
    """

    cond_kind: np.ndarray  # int32 [NC] — WFC_*
    cond_idx: np.ndarray  # int32 [NC] — t/op/m id (-1 for host kinds)
    cond_template: list  # str [NC] — source template id
    cond_name: list  # str [NC] — gate name ("" for hit conds)
    term_cond: np.ndarray  # int32 [NTERM, WF_MAX_CONDS] — pad -1 = TRUE
    term_emit: np.ndarray  # int32 [NTERM] — emit column this term sets
    emits: list  # [(workflow_id, template_id)] [NE]
    workflow_ids: list  # str — workflows lowered onto the device
    host_only_ids: list  # str — workflows the host twin still owns
    stats: dict

    @property
    def num_conds(self) -> int:
        return int(self.cond_kind.shape[0])

    @property
    def num_terms(self) -> int:
        return int(self.term_cond.shape[0])

    @property
    def num_emits(self) -> int:
        return len(self.emits)


def _empty_workflow_plan(host_only_ids: list, stats: dict) -> WorkflowPlan:
    return WorkflowPlan(
        cond_kind=np.zeros((0,), dtype=np.int32),
        cond_idx=np.zeros((0,), dtype=np.int32),
        cond_template=[],
        cond_name=[],
        term_cond=np.zeros((0, WF_MAX_CONDS), dtype=np.int32),
        term_emit=np.zeros((0,), dtype=np.int32),
        emits=[],
        workflow_ids=[],
        host_only_ids=host_only_ids,
        stats=stats,
    )


class _WfBail(Exception):
    """A workflow blew a DNF cap — fall back to the host twin."""


def lower_workflows(all_templates: list, db: "CompiledDB") -> WorkflowPlan:
    """Flatten every workflow DAG into the device gate tables.

    Gate decomposition mirrors ``cpu_ref`` name semantics exactly (a
    name fires iff its matcher individually matched AND its operation
    matched): AND-condition op ⇒ the op verdict suffices; OR-condition
    op ⇒ the matcher verdict suffices. Any alternative that is not
    device-exact demotes the WHOLE gate to one ``WFC_GATE_HOST`` cond —
    host resolution computes full gate truth anyway, and mixing exact
    and host alternatives would double-count terms.
    """
    from swarm_tpu.fingerprints.workflows import TemplateIndex, parse_workflow

    workflows = [
        parse_workflow(t) for t in all_templates if t.protocol == "workflow"
    ]
    if not workflows:
        return _empty_workflow_plan([], {"workflows_total": 0})
    index = TemplateIndex(
        [t for t in all_templates if t.protocol != "workflow"]
    )
    tidx_of = {t.id: i for i, t in enumerate(db.templates)}
    op_of: dict[tuple, int] = {}
    for op_id in range(db.op_src.shape[0]):
        op_of[(int(db.op_src[op_id, 0]), int(db.op_src[op_id, 1]))] = op_id
    m_of: dict[tuple, int] = {}
    for m_id in range(db.m_src.shape[0]):
        ti, ol, ml = (int(x) for x in db.m_src[m_id])
        if ml >= 0:
            m_of[(ti, ol, ml)] = m_id

    cond_rows: list[tuple[int, int, str, str]] = []
    cond_index: dict[tuple, int] = {}

    def cond_id(kind: int, idx: int, tid: str, name: str = "") -> int:
        key = (kind, idx, tid, name)
        ci = cond_index.get(key)
        if ci is None:
            ci = len(cond_rows)
            cond_index[key] = ci
            cond_rows.append(key)
        return ci

    def hit_cond(t) -> int:
        ti = tidx_of.get(t.id)
        if ti is None:
            return cond_id(WFC_HIT_HOST, -1, t.id)
        return cond_id(WFC_HIT_DEV, ti, t.id)

    def gate_alts(t, name: str):
        """→ list of alternative cond ids (ORed via term duplication),
        or None when no matcher carries the name (dead gate)."""
        found = False
        host = False
        alts: list[int] = []
        ti = tidx_of.get(t.id)
        for ol, op in enumerate(t.operations):
            for ml, m in enumerate(op.matchers):
                if m.name != name:
                    continue
                found = True
                if ti is None:
                    host = True
                    continue
                op_id = op_of.get((ti, ol))
                if op_id is None:
                    host = True  # op not lowered (e.g. extractor-only)
                elif (op.matchers_condition or "or").lower() == "and":
                    # AND op: op fired ⇒ every matcher fired ⇒ name
                    alts.append(cond_id(WFC_OP, op_id, t.id, name))
                elif bool(db.op_prefilter[op_id]):
                    # superset-lowered op: per-matcher bits weakened
                    host = True
                else:
                    m_id = m_of.get((ti, ol, ml))
                    if m_id is None:
                        host = True
                    else:
                        alts.append(cond_id(WFC_MATCHER, m_id, t.id, name))
        if not found:
            return None
        if host or not alts:
            return [cond_id(WFC_GATE_HOST, -1, t.id, name)]
        return alts

    # (sorted cond tuple, (workflow_id, template_id)) — dedup via set
    term_list: list[tuple[tuple, tuple]] = []
    term_seen: set = set()
    workflow_ids: list = []
    host_only_ids: list = []
    steps_compiled = 0

    for wf in workflows:
        wf_terms: list[tuple[tuple, tuple]] = []

        def add_term(conds: list, tid: str, _wf=wf, _acc=wf_terms) -> None:
            cs = tuple(sorted(set(conds)))
            if len(cs) > WF_MAX_CONDS or len(_acc) >= WF_MAX_TERMS_PER_WF:
                raise _WfBail()
            _acc.append((cs, (_wf.id, tid)))

        def walk_ref(ref, conds: list) -> None:
            for t in index.resolve(ref):
                base = conds + [hit_cond(t)]
                if ref.matchers:
                    for gate in ref.matchers:
                        alts = gate_alts(t, gate.name)
                        if alts is None:
                            continue
                        for a in alts:
                            for sub in gate.subtemplates:
                                walk_ref(sub, base + [a])
                elif ref.subtemplates:
                    for sub in ref.subtemplates:
                        walk_ref(sub, base)
                else:
                    add_term(base, t.id)

        try:
            for step in wf.steps:
                triggers = []
                if step.template:
                    t = index.by_path(step.template)
                    if t is not None:
                        triggers.append(t)
                for tag in step.tags:
                    triggers.extend(index.by_tag.get(tag.lower(), []))
                for trigger in triggers:
                    base = [hit_cond(trigger)]
                    if step.matchers:
                        for gate in step.matchers:
                            alts = gate_alts(trigger, gate.name)
                            if alts is None:
                                continue
                            for a in alts:
                                for ref in gate.subtemplates:
                                    walk_ref(ref, base + [a])
                    elif step.subtemplates:
                        for ref in step.subtemplates:
                            walk_ref(ref, base)
                    else:
                        add_term(base, trigger.id)
            if len(term_list) + len(wf_terms) > WF_MAX_TERMS:
                raise _WfBail()
        except _WfBail:
            host_only_ids.append(wf.id)
            continue
        workflow_ids.append(wf.id)
        steps_compiled += len(wf.steps)
        for entry in wf_terms:
            if entry not in term_seen:
                term_seen.add(entry)
                term_list.append(entry)

    stats = {
        "workflows_total": len(workflows),
        "workflows_device": len(workflow_ids),
        "workflows_host_only": len(host_only_ids),
        "steps_compiled": steps_compiled,
        "terms": len(term_list),
    }
    if not term_list:
        return _empty_workflow_plan(host_only_ids, stats)

    # compact to the conds actually referenced (bailed workflows may
    # have allocated strays) and allocate emit columns
    used = sorted({c for cs, _ in term_list for c in cs})
    remap = {c: i for i, c in enumerate(used)}
    emits: list = []
    emit_of: dict[tuple, int] = {}
    term_cond = np.full((len(term_list), WF_MAX_CONDS), -1, dtype=np.int32)
    term_emit = np.zeros((len(term_list),), dtype=np.int32)
    for row, (cs, emit_key) in enumerate(term_list):
        for j, c in enumerate(cs):
            term_cond[row, j] = remap[c]
        ei = emit_of.get(emit_key)
        if ei is None:
            ei = len(emits)
            emit_of[emit_key] = ei
            emits.append(emit_key)
        term_emit[row] = ei
    stats["conds"] = len(used)
    stats["emits"] = len(emits)
    return WorkflowPlan(
        cond_kind=np.array([cond_rows[c][0] for c in used], dtype=np.int32),
        cond_idx=np.array([cond_rows[c][1] for c in used], dtype=np.int32),
        cond_template=[cond_rows[c][2] for c in used],
        cond_name=[cond_rows[c][3] for c in used],
        term_cond=term_cond,
        term_emit=term_emit,
        emits=emits,
        workflow_ids=workflow_ids,
        host_only_ids=host_only_ids,
        stats=stats,
    )


def wf_arrays_np(plan: WorkflowPlan) -> dict:
    """The workflow gate tables as one host pytree (the wf sub-layout
    of the verdict arguments). Host kinds gather with a clipped index
    and are masked to (False, uncertain) by ``cond_host``."""
    return {
        "cond_kind": plan.cond_kind,
        "cond_idx": np.maximum(plan.cond_idx, 0).astype(np.int32),
        "cond_host": (plan.cond_kind >= WFC_HIT_HOST),
        "term_cond": plan.term_cond,
        "term_emit": plan.term_emit,
        # zeros of shape [NE]: gives the kernel a static emit width
        "emit_pad": np.zeros((plan.num_emits,), dtype=np.bool_),
    }


# ---------------------------------------------------------------------------
# Compiler
# ---------------------------------------------------------------------------


class _SlotSpace:
    """Dedup (bytes, stream, lowered) → slot id."""

    def __init__(self) -> None:
        self.index: dict[tuple[bytes, str, bool], int] = {}
        self.entries: list[tuple[bytes, str, bool]] = []

    def get(self, data: bytes, stream: str, lowered: bool) -> int:
        if lowered:
            data = bytes(lower_bytes_np(np.frombuffer(data, np.uint8)).tobytes()) if data else data
        key = (data, stream, lowered)
        slot = self.index.get(key)
        if slot is None:
            slot = len(self.entries)
            self.index[key] = slot
            self.entries.append(key)
        return slot


def _word_payloads(matcher: Matcher) -> Optional[list[bytes]]:
    if matcher.type == "word":
        return [w.encode("utf-8", "surrogateescape") for w in matcher.words]
    if matcher.type == "binary":
        out = []
        for hexstr in matcher.binary:
            try:
                out.append(binascii.unhexlify(re.sub(r"\s", "", hexstr)))
            except (binascii.Error, ValueError):
                return None
        return out
    return None


# ---------------------------------------------------------------------------
# Device layout: corpus arrays as jit ARGUMENTS (stacked table-major)
# ---------------------------------------------------------------------------
#
# The match kernel used to capture every corpus array as an XLA constant
# (jnp.asarray inside the traced function): each padded-width bucket
# then compiled a corpus-sized program (~2 min compiles, constant-fold
# alarms, one big executable per shape, cold persistent cache across
# corpus refreshes). The layout below is the other calling convention:
# every array the kernel reads, gathered into ONE host pytree that
# DeviceDB / ShardedMatcher upload to the device once and pass as jit
# arguments on every call — the traced program is corpus-size-free, so
# one executable serves every corpus and the XLA cache keys stop
# depending on the corpus bytes. (The arrays are NOT donated: they are
# reused by every subsequent call, donation would invalidate them.)
#
# Word tables ship in a stacked TABLE-MAJOR layout ([T, Gmax]/[T, Emax]
# with sentinel padding, exactly the scheme parallel/sharded.py already
# uses per rank) so the kernel's prefilter runs over all tables at once
# instead of a per-table Python loop.


#: base rung of the survivor-compaction bucket ladder (ops/match.py,
#: docs/DEVICE_MATCH.md): phase B launches at the smallest power-of-two
#: candidate width that covers the batch's survivors, so a sparse fleet
#: batch (typically 0-2 fired windows per row) verifies at width 8
#: instead of the worst-case global budget. Power-of-two rungs bound the
#: live phase-B executable count at log2(budget / 8) + 1 per shape
#: class.
SURVIVOR_LADDER_MIN = 8


def survivor_bucket(n_survivors: int, budget: int) -> int:
    """Phase-B candidate width for a batch whose worst row fired
    ``n_survivors`` windows: the smallest ladder rung covering them,
    clamped to the global candidate ``budget`` (rows past the budget
    overflow to the host row-redo — the exactness escape hatch, so a
    width above the budget could never matter)."""
    k = SURVIVOR_LADDER_MIN
    while k < n_survivors:
        k <<= 1
    return max(1, min(k, budget))


@dataclasses.dataclass(frozen=True)
class DeviceLayoutMeta:
    """Static (trace-time) facts about a CompiledDB — everything the
    argument-driven kernel needs for control flow, none of it traced.
    Hashable so it can key jit caches if ever needed."""

    table_stream: tuple  # per table: stream name
    table_lowered: tuple  # per table: probe the lowered stream
    table_q: tuple  # per table: gram size
    max_group: int  # global verify unroll bound (max over tables)
    tiny: tuple  # per tiny slot: (length, stream name, lowered)
    has_md5: bool
    n_rx: int  # len(db.rx_m_ids)


def scalar_onehot_np(m_scalar: np.ndarray) -> np.ndarray:
    """[NCHECKS, NM, C] bool one-hot of the scalar-program op ids,
    computed ON HOST. Feeding this as an array (argument or ready-made
    constant) replaces the kernel's former per-op ``op_id == i``
    comparisons over the [NM, C] id plane — the ``pred[1,NM,C]`` reduce
    XLA's constant folder ground through on every compile
    (slow_operation_alarm, MULTICHIP_r05)."""
    op_id = m_scalar[:, :, 1].astype(np.int32)
    nchecks = SOP_TRUE + 1
    return np.stack([op_id == i for i in range(nchecks)])


def stack_tables_np(tables: list) -> dict:
    """WordTables → stacked table-major arrays with sentinel padding.

    Padding mirrors :func:`swarm_tpu.parallel.sharded.shard_tables_np`:
    group_h1 pads with 0xFFFFFFFF and zero entry counts (a padded group
    can be "found" but yields no entries), entry_len pads with 2^30 (a
    padded entry can never fit in a stream). ``n_groups`` bounds the
    kernel's per-candidate binary search."""
    T = len(tables)
    gmax = max((t.num_groups for t in tables), default=0) or 1
    emax = max((int(t.entry_h2.shape[0]) for t in tables), default=0) or 1
    out = {
        "group_h1": np.full((max(T, 1), gmax), 0xFFFFFFFF, dtype=np.uint32),
        "entry_start": np.zeros((max(T, 1), gmax), dtype=np.int32),
        "entry_count": np.zeros((max(T, 1), gmax), dtype=np.int32),
        "entry_h2": np.zeros((max(T, 1), emax), dtype=np.uint32),
        "entry_slot": np.zeros((max(T, 1), emax), dtype=np.int32),
        "entry_off": np.zeros((max(T, 1), emax), dtype=np.int32),
        "entry_len": np.full((max(T, 1), emax), 1 << 30, dtype=np.int32),
        "entry_suf_delta": np.zeros((max(T, 1), emax), dtype=np.int32),
        "entry_suf_h1": np.zeros((max(T, 1), emax), dtype=np.uint32),
        "entry_suf_h2": np.zeros((max(T, 1), emax), dtype=np.uint32),
        "bloom": np.zeros(
            (max(T, 1), hashing.BLOOM_WORDS), dtype=np.uint32
        ),
        "n_groups": np.zeros((max(T, 1),), dtype=np.int32),
    }
    for t_idx, t in enumerate(tables):
        G = t.num_groups
        E = int(t.entry_h2.shape[0])
        out["group_h1"][t_idx, :G] = t.group_h1
        out["entry_start"][t_idx, :G] = t.entry_start
        out["entry_count"][t_idx, :G] = t.entry_count
        out["entry_h2"][t_idx, :E] = t.entry_h2
        out["entry_slot"][t_idx, :E] = t.entry_slot
        out["entry_off"][t_idx, :E] = t.entry_off
        out["entry_len"][t_idx, :E] = t.entry_len
        out["entry_suf_delta"][t_idx, :E] = t.entry_suf_delta
        out["entry_suf_h1"][t_idx, :E] = t.entry_suf_h1
        out["entry_suf_h2"][t_idx, :E] = t.entry_suf_h2
        out["bloom"][t_idx] = t.bloom
        out["n_groups"][t_idx] = G
    return out


def _bucket_arrays(buckets: list) -> tuple:
    """IndexBuckets → ((rows, idx), ...) array pairs (a pytree whose
    leaves the kernel gathers/scatters with — bucket COUNT and widths
    stay static via the array shapes)."""
    return tuple((b.rows, b.idx) for b in buckets)


def verdict_arrays_np(db: "CompiledDB") -> dict:
    """Every matcher/op/template array ``eval_verdicts`` reads, as one
    host pytree (the verdict half of the argument layout)."""
    kind = db.m_kind
    wf = getattr(db, "wf", None)
    out = {
        "m_cond_and": db.m_cond_and,
        "m_negative": db.m_negative,
        "m_residue": db.m_residue,
        "m_md5": db.m_md5,
        "m_md5_check": db.m_md5_check,
        "m_status": db.m_status,
        "m_size": db.m_size,
        "m_size_stream": db.m_size_stream.astype(np.int32),
        "scalar_var": db.m_scalar[:, :, 0].astype(np.int32),
        "scalar_cmp": db.m_scalar[:, :, 2].astype(np.float32),
        "scalar_onehot": scalar_onehot_np(db.m_scalar),
        "is_words": (kind == MK_WORDS) | (kind == MK_REGEX_PREFILTER),
        "is_rx_prefilter": kind == MK_REGEX_PREFILTER,
        "is_scalar": kind == MK_SCALAR_DSL,
        "is_status": kind == MK_STATUS,
        "is_size": kind == MK_SIZE,
        "m_slot_buckets": _bucket_arrays(db.m_slot_buckets),
        "m_negslot_buckets": _bucket_arrays(db.m_negslot_buckets),
        "op_cond_and": db.op_cond_and,
        "op_prefilter": db.op_prefilter,
        "op_m_buckets": _bucket_arrays(db.op_m_buckets),
        "t_op_buckets": _bucket_arrays(db.t_op_buckets),
        "rx_m_ids": db.rx_m_ids,
    }
    # workflow gate tables ride the same pytree — only when the corpus
    # actually lowered terms (keeps plan-less pytrees byte-identical)
    if wf is not None and wf.num_terms:
        out["wf"] = wf_arrays_np(wf)
    return out


def rx_variants(db: "CompiledDB") -> list:
    """Distinct (stream index, ci) pairs the rx sequences scan, in the
    canonical sorted order BOTH the static loop and ``var_of_seq``
    use — a single definition so they can never disagree."""
    return sorted(
        {(int(s), bool(c)) for s, c in zip(db.rx_seq_stream, db.rx_seq_ci)}
    )


def rx_arrays_np(db: "CompiledDB") -> dict:
    """Every array the device regex verify reads (ops/regexdev.py)."""
    variants = rx_variants(db)
    NSEQ = db.rx_seq_matcher.shape[0]
    var_of_seq = np.zeros((max(NSEQ, 1),), dtype=np.int32)
    for si in range(NSEQ):
        var_of_seq[si] = variants.index(
            (int(db.rx_seq_stream[si]), bool(db.rx_seq_ci[si]))
        )
    return {
        "seq_matcher": db.rx_seq_matcher,
        "seq_always": db.rx_seq_always,
        "slot_buckets": _bucket_arrays(db.rx_seq_slot_buckets),
        "var_of_seq": var_of_seq,
        "bytemap": db.rx_bytemap,
        "seed": db.rx_seed,
        "skip": db.rx_skip,
        "accept": db.rx_accept,
        "self": db.rx_self,
        "anchored": db.rx_anchored,
        "end_mode": db.rx_end_mode,
        "start_wb": db.rx_start_wb,
        "end_wb": db.rx_end_wb,
    }


def layout_meta(db: "CompiledDB") -> DeviceLayoutMeta:
    """Static layout metadata alone (the sharded path pairs it with
    per-rank table slices instead of the unsharded stack)."""
    tiny_count = int((np.asarray(db.tiny_len) > 0).sum())
    return DeviceLayoutMeta(
        table_stream=tuple(t.stream for t in db.tables),
        table_lowered=tuple(bool(t.lowered) for t in db.tables),
        table_q=tuple(int(t.q) for t in db.tables),
        max_group=max((int(t.max_group) for t in db.tables), default=1),
        tiny=tuple(
            (
                int(db.tiny_len[i]),
                STREAMS[int(db.tiny_stream[i])],
                bool(db.tiny_lowered[i]),
            )
            for i in range(tiny_count)
        ),
        has_md5=bool(db.m_md5_check.any()),
        n_rx=int(len(db.rx_m_ids)),
    )


def build_device_layout(db: "CompiledDB"):
    """→ (meta, arrays): the static metadata + the full host argument
    pytree for one CompiledDB. Cached on the instance — the arrays are
    views of the db's own numpy buffers wherever possible, so the
    layout costs one stacked-table copy, once."""
    cached = getattr(db, "_device_layout", None)
    if cached is not None:
        return cached
    meta = layout_meta(db)
    arrays = {
        "tab": stack_tables_np(db.tables),
        "slot_bytes": db.slot_bytes,
        "slot_len": db.slot_len,
        "tiny_bytes": db.tiny_bytes,
        "tiny_slot": db.tiny_slot,
        "verdict": verdict_arrays_np(db),
        "rx": rx_arrays_np(db),
    }
    db._device_layout = (meta, arrays)
    return meta, arrays


# ---------------------------------------------------------------------------
# Corpus-delta path (docs/AOT.md): a template add/remove/edit rebuilds
# only the touched stacked-table rows instead of the whole layout
# ---------------------------------------------------------------------------


def compile_corpus_delta(
    templates_new: list,
    db_old: "CompiledDB",
    verify_width: int = VERIFY_WIDTH,
) -> tuple["CompiledDB", dict]:
    """Recompile a corpus against its previous build: unchanged word
    tables are adopted by object identity (see ``compile_corpus``'s
    ``reuse_from``), then the device layout is delta-built so only
    the touched stacked-table rows are rewritten and every equal leaf
    keeps the OLD array object (→ zero re-upload for it). Returns
    ``(db_new, stats)``; the result is bit-identical to a from-scratch
    ``compile_corpus`` + ``build_device_layout``."""
    stats: dict = {}
    db_new = compile_corpus(
        templates_new, verify_width, reuse_from=db_old, delta_stats=stats
    )
    build_device_layout_delta(db_new, db_old, stats)
    return db_new, stats


def stack_tables_delta(
    tables_new: list, tables_old: list, tab_old: dict, stats: dict
) -> dict:
    """Delta twin of :func:`stack_tables_np`: stacked rows for tables
    adopted from the old build (object identity — the
    ``compile_corpus`` reuse contract) are COPIED from the old stacked
    arrays; only changed tables stack from their WordTable. When
    nothing changed and the padded widths are identical, the old
    stacked arrays are returned OUTRIGHT (array identity → the device
    skips their re-upload entirely). ``stats`` gains ``rows_reused`` /
    ``rows_rebuilt``."""
    old_pos = {id(t): i for i, t in enumerate(tables_old)}
    reused = [
        old_pos.get(id(t)) for t in tables_new
    ]  # old row index, or None = rebuild
    rows_reused = sum(1 for r in reused if r is not None)
    stats["rows_reused"] = rows_reused
    stats["rows_rebuilt"] = len(tables_new) - rows_reused
    gmax_new = max((t.num_groups for t in tables_new), default=0) or 1
    emax_new = (
        max((int(t.entry_h2.shape[0]) for t in tables_new), default=0) or 1
    )
    same_shape = (
        tables_old
        and len(tables_new) == len(tables_old)
        and tab_old["group_h1"].shape[1] == gmax_new
        and tab_old["entry_h2"].shape[1] == emax_new
    )
    if same_shape and all(r == i for i, r in enumerate(reused)):
        # nothing to do: every row identical, padding identical
        return tab_old
    if rows_reused == 0 or not tables_new:
        return stack_tables_np(tables_new)
    # mixed case: allocate at the new padded widths, copy reused rows
    # from the old stack (bit-identical to re-stacking them — old rows
    # hold real data up to the table's own G/E, sentinel padding
    # beyond), stack only the changed tables
    T = max(len(tables_new), 1)
    base = {
        "group_h1": (np.uint32, 0xFFFFFFFF, gmax_new),
        "entry_start": (np.int32, 0, gmax_new),
        "entry_count": (np.int32, 0, gmax_new),
        "entry_h2": (np.uint32, 0, emax_new),
        "entry_slot": (np.int32, 0, emax_new),
        "entry_off": (np.int32, 0, emax_new),
        "entry_len": (np.int32, 1 << 30, emax_new),
        "entry_suf_delta": (np.int32, 0, emax_new),
        "entry_suf_h1": (np.uint32, 0, emax_new),
        "entry_suf_h2": (np.uint32, 0, emax_new),
        "bloom": (np.uint32, 0, hashing.BLOOM_WORDS),
    }
    out = {
        name: np.full((T, width), fill, dtype=dt)
        for name, (dt, fill, width) in base.items()
    }
    out["n_groups"] = np.zeros((T,), dtype=np.int32)
    for t_idx, table in enumerate(tables_new):
        r_old = reused[t_idx]
        if r_old is not None:
            for name, (dt, _fill, width) in base.items():
                src_row = tab_old[name][r_old]
                w = min(width, src_row.shape[0])
                out[name][t_idx, :w] = src_row[:w]
            out["n_groups"][t_idx] = tab_old["n_groups"][r_old]
            continue
        G = table.num_groups
        E = int(table.entry_h2.shape[0])
        out["group_h1"][t_idx, :G] = table.group_h1
        out["entry_start"][t_idx, :G] = table.entry_start
        out["entry_count"][t_idx, :G] = table.entry_count
        out["entry_h2"][t_idx, :E] = table.entry_h2
        out["entry_slot"][t_idx, :E] = table.entry_slot
        out["entry_off"][t_idx, :E] = table.entry_off
        out["entry_len"][t_idx, :E] = table.entry_len
        out["entry_suf_delta"][t_idx, :E] = table.entry_suf_delta
        out["entry_suf_h1"][t_idx, :E] = table.entry_suf_h1
        out["entry_suf_h2"][t_idx, :E] = table.entry_suf_h2
        out["bloom"][t_idx] = table.bloom
        out["n_groups"][t_idx] = G
    return out


def _adopt_equal_leaves(new_tree, old_tree, stats: dict):
    """Replace every leaf of ``new_tree`` that is byte-equal to the
    same-path leaf of ``old_tree`` with the OLD ARRAY OBJECT, so the
    device update can skip its re-upload by identity. Only paths
    present in both trees with matching shape/dtype participate;
    structural changes (bucket counts) simply upload."""
    import jax

    old_leaves = {
        jax.tree_util.keystr(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(old_tree)[0]
    }
    flat, treedef = jax.tree_util.tree_flatten_with_path(new_tree)
    out = []
    adopted = total = 0
    for path, leaf in flat:
        total += 1
        old = old_leaves.get(jax.tree_util.keystr(path))
        if (
            old is not None
            and isinstance(old, np.ndarray)
            and isinstance(leaf, np.ndarray)
            and old.dtype == leaf.dtype
            and old.shape == leaf.shape
            and (old is leaf or np.array_equal(old, leaf))
        ):
            out.append(old)
            adopted += 1
        else:
            out.append(leaf)
    stats["leaves_reused"] = adopted
    stats["leaves_total"] = total
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(new_tree), out
    )


def build_device_layout_delta(
    db_new: "CompiledDB", db_old: "CompiledDB", stats: Optional[dict] = None
):
    """Delta twin of :func:`build_device_layout`: rebuild only the
    touched stacked-table rows (``stack_tables_delta``) and adopt
    every unchanged leaf from the old layout by identity, so a
    one-template corpus refresh re-uploads a handful of arrays
    instead of the whole layout. Bit-identical to a from-scratch
    build; the result is cached on ``db_new`` exactly like
    :func:`build_device_layout` so every later consumer sees it."""
    if stats is None:
        stats = {}
    cached = getattr(db_new, "_device_layout", None)
    if cached is not None:
        return (*cached, stats)
    old = getattr(db_old, "_device_layout", None)
    if old is None:
        old = build_device_layout(db_old)
    _old_meta, old_arrays = old
    meta = layout_meta(db_new)
    arrays = {
        "tab": stack_tables_delta(
            db_new.tables, db_old.tables, old_arrays["tab"], stats
        ),
        "slot_bytes": db_new.slot_bytes,
        "slot_len": db_new.slot_len,
        "tiny_bytes": db_new.tiny_bytes,
        "tiny_slot": db_new.tiny_slot,
        "verdict": verdict_arrays_np(db_new),
        "rx": rx_arrays_np(db_new),
    }
    arrays = _adopt_equal_leaves(arrays, old_arrays, stats)
    db_new._device_layout = (meta, arrays)
    return meta, arrays, stats


def compile_corpus(
    templates: list[Template],
    verify_width: int = VERIFY_WIDTH,
    reuse_from: Optional["CompiledDB"] = None,
    delta_stats: Optional[dict] = None,
) -> CompiledDB:
    """Compile a template corpus into a :class:`CompiledDB`.

    ``reuse_from`` is the corpus-delta lever (docs/AOT.md): pass the
    PREVIOUS corpus's CompiledDB and every word table whose content —
    the exact post-shedding (h1, h2, slot, offset) member list plus
    the member payload bytes — is unchanged is adopted by OBJECT
    IDENTITY instead of re-derived (gram hashing, suffix selection,
    bloom build all skipped), which also lets the stacked-layout delta
    (:func:`build_device_layout_delta`) reuse the old stacked rows and
    :class:`~swarm_tpu.ops.match.DeviceDB` skip their re-upload. The
    result is BIT-IDENTICAL to a from-scratch compile by construction
    (the reuse key captures every input of the table build).
    ``delta_stats`` (optional dict) receives the rebuild accounting
    (``tables_total`` / ``tables_reused`` / ``tables_rebuilt``)."""
    slots = _SlotSpace()
    matchers: list[dict] = []
    ops: list[dict] = []
    t_ops: list[list[int]] = []
    kept_templates: list[Template] = []
    t_prefilter_flags: list[bool] = []
    host_always: list[Template] = []
    # regex sequences with no gating literal scan every row — ration
    # them corpus-wide so the verify stage's worklist stays bounded
    rx_always_budget = [4]

    def lower_matcher(m: Matcher) -> Optional[dict]:
        """→ matcher record dict, or None if not device-loweable."""
        rec = {
            "kind": MK_CONST_FALSE,
            "negative": m.negative,
            "cond_and": m.condition == "and",
            "slots": [],
            "scalar": [],
            "residue": False,
            "status": [],
            "size": [],
            "size_stream": 0,
            "md5": None,
            "neg_slots": [],
            "rx": None,
        }

        def const(value: bool) -> dict:
            # constant matcher: encode as MK_CONST_FALSE with the
            # negation flag folded in (negative ^ value ≡ value after
            # the kernel's generic `value ^= negative` step)
            rec["kind"] = MK_CONST_FALSE
            rec["negative"] = bool(m.negative) ^ bool(value)
            return rec
        if m.type in ("word", "binary"):
            payloads = _word_payloads(m)
            if payloads is None:
                return None
            if not payloads:
                # oracle: empty word list → no results → verdict False
                # (negation applies) — a compile-time constant
                return const(False)
            if m.part in HOST_ONLY_PARTS:
                return None  # oracle has real bytes here; not device-loweable
            stream = stream_for_part(m.part)
            if stream is None:
                return rec  # unknown/OOB part: constant False on both engines
            if any(len(p) == 0 for p in payloads):
                # an empty needle is always present (b"" in hay ≡ True):
                # under OR the matcher is constantly True; under AND the
                # empty words are identity conjuncts — drop them
                if m.condition != "and":
                    return const(True)
                payloads = [p for p in payloads if p]
                if not payloads:
                    return const(True)
            # cpu_ref (like nuclei) ignores case-insensitive for binary
            # payloads — keep the device identical.
            lowered = m.case_insensitive and m.type == "word"
            rec["kind"] = MK_WORDS
            rec["slots"] = [slots.get(p, stream, lowered) for p in payloads]
            return rec
        if m.type == "status":
            if not m.status:
                return None
            rec["kind"] = MK_STATUS
            rec["status"] = list(m.status)
            return rec
        if m.type == "size":
            stream = stream_for_part(m.part)
            if not m.size:
                return None
            if stream is None:
                # oracle sees b"" for this part: len==0 is a compile-time
                # constant (size [0] matches the empty part!)
                return const(0 in m.size)
            rec["kind"] = MK_SIZE
            rec["size"] = list(m.size)
            rec["size_stream"] = STREAMS.index(stream)
            return rec
        if m.type == "regex":
            # a pattern Python's re rejects makes the oracle return
            # "unsupported" → constant False, negation NOT applied
            # (cpu_ref.match_matcher returns None pre-negation) — e.g.
            # waf-detect's '(?)content="CloudWAF"'. Exact, and it keeps
            # one broken pattern from demoting 86 siblings to a
            # host-confirmed prefilter op.
            try:
                for pattern in m.regex:
                    dslc.compile_cached(pattern)
            except re.error:
                rec["negative"] = False
                return rec
            stream = stream_for_part(m.part)
            if stream is None:
                # oracle runs the regex over the empty string — also a
                # compile-time constant (e.g. `.*` matches empty)
                results = []
                for pattern in m.regex:
                    results.append(
                        dslc.compile_cached(pattern).search("") is not None
                    )
                if not results:
                    return None
                value = all(results) if m.condition == "and" else any(results)
                return const(value)
            # pure-literal patterns are *exact* substring checks — no
            # prefilter uncertainty at all (MySqlException,
            # (?i)x-drupal, Set-Cookie: (Craft|CRAFT) …)
            pure = [full_literal_expansions(p) for p in m.regex]
            if all(p is not None for p in pure) and (
                m.condition != "and"
                or all(len(alts) == 1 for alts, _ in pure)
            ):
                rec["kind"] = MK_WORDS
                rec["cond_and"] = m.condition == "and"
                rec["slots"] = [
                    slots.get(lit, stream, ci)
                    for alts, ci in pure
                    for lit in alts
                ]
                return rec
            # every regex in the list needs a required literal *set*
            # (any-of — alternations yield several members). The matcher
            # bit is AND of singletons when condition=and, else the flat
            # OR union — both sound supersets, and MK_REGEX_PREFILTER is
            # uncertain-on-fire either way, so weaker only costs extra
            # confirms, never misses. Literals probe the lowered stream.
            lit_sets = []
            for pattern in m.regex:
                # relax the length floor before failing: a 2–3 byte
                # anchor is a weak but still exact-on-miss prefilter
                # (waf-detect's '(?i)ray.id' family)
                lits = None
                for ml in (4, 3, 2):
                    lits = required_literal_set(pattern, min_len=ml)
                    if lits is not None:
                        break
                lit_sets.append(lits)  # None = no gating literal
            # device regex verify (ops/regexdev.py): when every pattern
            # compiles to linear shift-and programs (and the matcher is
            # OR-reduced, the corpus norm), fired rows are re-checked
            # ON DEVICE — the matcher becomes exact, no host confirm.
            # A pattern with no gating literal runs on EVERY row, so
            # those are rationed (rx_always_budget).
            rx_progs = None
            if m.condition != "and" or len(m.regex) == 1:
                progs = [regexlin.compile_linear(p) for p in m.regex]
                if all(p is not None for p in progs):
                    # budget counts expanded SEQUENCES (each always-on
                    # sequence scans every row of every batch)
                    n_always = sum(
                        len(pr[0])
                        for lits, pr in zip(lit_sets, progs)
                        if not lits
                    )
                    if n_always == 0 or rx_always_budget[0] >= n_always:
                        rx_progs = progs
                        rx_always_budget[0] -= n_always
            if rx_progs is None and any(s is None for s in lit_sets):
                # a literal-less pattern with no device program: one
                # bad pattern demotes the whole op (prefilter)
                return None
            rec["kind"] = MK_REGEX_PREFILTER
            rec["cond_and"] = (
                m.condition == "and"
                and all(s is not None and len(s) == 1 for s in lit_sets)
            )
            rec["slots"] = [
                slots.get(lit, stream, True)
                for s in lit_sets
                if s
                for lit in s
            ]
            if rx_progs is not None:
                rec["rx"] = []
                for lits, (alts, ci) in zip(lit_sets, rx_progs):
                    gate = (
                        [slots.get(lit, stream, True) for lit in lits]
                        if lits
                        else []
                    )
                    for lp in alts:
                        rec["rx"].append((lp, ci, stream, gate))
            return rec
        if m.type == "dsl":
            progs = []
            solo = m.condition == "and" or len(m.dsl) == 1
            for expr in m.dsl:
                ast = dslc.try_parse(expr)
                if ast is None or dslc.always_errors(ast):
                    # oracle semantics: a parse failure or an expression
                    # that errors in every env (unknown var/function —
                    # the multi-step status_code_2/body_1 tail) makes
                    # the whole matcher "unsupported" → constant False
                    # with negation NOT applied (cpu_ref.match_matcher
                    # returns None before the negation step)
                    rec["negative"] = False
                    return rec
                if solo and dslc.effectively_false(ast):
                    # every row either errors (matcher unsupported →
                    # False, unnegated) or yields False (expr False →
                    # under AND/single-expr the matcher is False, which
                    # negation could flip) — but False-by-error wins on
                    # exactly the rows where the guard passes, so only
                    # the unnegated constant is sound for both cases…
                    # unless the matcher is negated, where the two
                    # disagree; keep those on the uncertain path.
                    if not m.negative:
                        rec["negative"] = False
                        return rec
                prog = lower_dsl(ast)
                if prog is None:
                    return None
                progs.append(prog)
            merged = _merge_dsl_progs(progs, m.condition)
            if merged is None:
                return None
            if merged.never:
                return rec  # statically unsatisfiable: constant False
            rec["kind"] = MK_SCALAR_DSL
            rec["scalar"] = merged.conjuncts
            rec["residue"] = merged.residue
            rec["cond_and"] = not merged.any_of
            rec["negative"] = bool(m.negative) ^ merged.negated
            rec["slots"] = [
                slots.get(needle, stream, lowered)
                for needle, stream, lowered in merged.contains
            ]
            rec["md5"] = merged.md5
            rec["neg_slots"] = [
                slots.get(needle, stream, lowered)
                for needle, stream, lowered in merged.neg_contains
            ]
            return rec
        return None  # kval / json / xpath

    def const_true_unc() -> dict:
        """Fires on every row; the template-level prefilter flag routes
        fired rows to host confirmation (MK_SCALAR_DSL with an empty
        program evaluates vacuously True pre-negation)."""
        return {
            "kind": MK_SCALAR_DSL,
            "negative": False,
            "cond_and": True,
            "slots": [],
            "scalar": [],
            "residue": False,
            "status": [],
            "size": [],
            "size_stream": 0,
            "md5": None,
            "neg_slots": [],
            "rx": None,
        }

    def lower_matcher_superset(m: Matcher) -> dict:
        """Necessary-condition lowering — never fails. The matcher's
        device value is a superset of its oracle value (post-negation),
        so a template built from these can only over-fire; not-fired
        rows are exact. Only meaningful under a template prefilter flag.
        """
        rec = lower_matcher(m)
        if rec is not None:
            return rec
        if m.negative:
            # a partial (widened) pre-negation value would flip into a
            # *narrowed* post-negation value — unsound as a superset
            return const_true_unc()
        if m.type == "dsl":
            progs = []
            for expr in m.dsl:
                ast = dslc.try_parse(expr)
                if ast is None:  # unreachable: exact path consts these
                    return const_true_unc()
                progs.append(lower_dsl(ast))
            merged = _merge_dsl_progs(progs, m.condition, superset=True)
            if merged is None:
                return const_true_unc()
            if merged.never:
                rec = const_true_unc()
                rec["negative"] = True  # constant False, exact
                return rec
            if merged.negated:
                # negated buckets don't widen monotonically — play safe
                return const_true_unc()
            rec = const_true_unc()
            rec["scalar"] = merged.conjuncts
            # no m-level residue here: a weakened matcher firing every
            # row would make the template *always* uncertain; the
            # op_prefilter flag already confirms exactly the fired rows
            rec["cond_and"] = not merged.any_of
            rec["slots"] = [
                slots.get(needle, stream, lowered)
                for needle, stream, lowered in merged.contains
            ]
            return rec
        if m.type == "regex":
            stream = stream_for_part(m.part)
            if stream is not None:
                # relax the length floor before giving up: a 2–3 byte
                # anchor (binary protocol magic like "N\x00\x0e") takes
                # the exact tiny-slot path and still beats fire-always
                lit_sets = [required_literal_ladder(p) for p in m.regex]
                if m.condition == "and" or len(m.regex) == 1:
                    # any single pattern's set is already necessary —
                    # the union of the available ones is sound (weaker)
                    avail = [s for s in lit_sets if s]
                    lit_sets = avail if avail else None
                else:
                    # OR needs a set for every pattern
                    if any(s is None for s in lit_sets):
                        lit_sets = None
                if lit_sets:
                    rec = const_true_unc()
                    rec["kind"] = MK_REGEX_PREFILTER
                    rec["cond_and"] = False
                    rec["slots"] = [
                        slots.get(lit, stream, True)
                        for s in lit_sets
                        for lit in s
                    ]
                    return rec
            return const_true_unc()
        if m.type == "kval":
            # header KEY presence; the key bytes (either separator
            # form) occurring anywhere in the header is a necessary
            # condition, and OR over forms/keys is a superset of both
            # kval conditions
            slot_ids = []
            for key in m.kval:
                for form in {key.lower().replace("_", "-"), key.lower()}:
                    data = form.encode()
                    if data:
                        slot_ids.append(slots.get(data, "header", True))
            if slot_ids:
                rec = const_true_unc()
                rec["kind"] = MK_WORDS
                rec["cond_and"] = False
                rec["slots"] = slot_ids
                return rec
            return const_true_unc()
        return const_true_unc()

    def lower_extraction_prefilter(op) -> Optional[list]:
        """Pseudo-matchers for an operation with extractors but NO
        matchers: nuclei reports such templates iff any extractor
        extracts (reference worker/artifacts/templates/exposures/
        tokens/generic/credentials-disclosure.yaml:20-24 — the
        exposures/tokens family's entire mechanism).

        One MK_REGEX_PREFILTER pseudo-matcher PER extraction pattern,
        carrying that pattern's required literals: the device q-gram
        pass then reports WHICH patterns could match (the pm-plane
        uncertainty bits), so a fired multi-hundred-pattern extractor
        costs the host only the one or two literal-hit patterns — the
        gram work rides the kernel the corpus matchers already use,
        instead of a per-fire host scan over every pattern. No literal
        present anywhere ⇒ every pseudo-matcher is certain-false and
        the op resolves with zero host work. ``pseudo_ext`` on each
        rec records (extractor_local, pattern_idx) provenance
        (db.m_ext_src) for the engine's per-pattern confirm and the
        extraction pass's bit-driven gating.

        Returns None when any extractor is non-regex or any pattern
        has no required literal — the caller degrades to ONE
        fire-always prefilter rec for the whole op (every row
        host-confirmed — correct, just slower). The whole reference
        http/dns population lowers per-pattern
        (tests/test_extractor_only.py pins that)."""
        recs: list = []
        for ex_local, ex in enumerate(op.extractors):
            if ex.type != "regex" or not ex.regex:
                return None
            stream = stream_for_part(ex.part or "body")
            if stream is None:
                return None
            for p_idx, p in enumerate(ex.regex):
                s = required_literal_ladder(p)
                if s is None:
                    return None
                rec = const_true_unc()
                rec["kind"] = MK_REGEX_PREFILTER
                rec["cond_and"] = False
                rec["slots"] = [slots.get(lit, stream, True) for lit in s]
                rec["pseudo_ext"] = (ex_local, p_idx)
                recs.append(rec)
        return recs or None

    for template in templates:
        if template.protocol == "workflow" or not template.operations:
            continue
        lowered_ops: list[dict] = []
        for op_local, op in enumerate(template.operations):
            if not op.matchers:
                # extractor-only op: matches iff extraction succeeds —
                # but only for the protocol families THIS engine
                # executes. file/ssl/headless extractor-only templates
                # are owned by their subsystems (worker/filescan.py:79,
                # worker/sslscan.py:246, worker/headless.py), which
                # already implement extraction-implies-match; lowering
                # them here would double-claim them against http rows.
                if op.extractors and template.protocol in (
                    "http", "network", "dns",
                ):
                    recs = lower_extraction_prefilter(op)
                    if recs is not None:
                        # per-pattern matchers, OR'd; NOT an op-level
                        # prefilter — the walk confirms exactly the
                        # pattern-matchers whose literals fired
                        lowered_ops.append(
                            {
                                "cond_and": False,
                                "matchers": recs,
                                "prefilter": False,
                                "op_local": op_local,
                            }
                        )
                    else:
                        # degrade: one fire-always rec, whole-op
                        # host confirm on every row (correct, slower)
                        fallback = const_true_unc()
                        fallback["pseudo_ext"] = (-1, -1)
                        lowered_ops.append(
                            {
                                "cond_and": False,
                                "matchers": [fallback],
                                "prefilter": True,
                                "op_local": op_local,
                            }
                        )
                continue
            recs = []
            exact = True
            for m in op.matchers:
                rec = lower_matcher(m)
                if rec is None:
                    exact = False
                    break
                recs.append(rec)
            if not exact:
                # per-op superset re-lowering: this op becomes a device
                # *prefilter* — rows where it fires are host-confirmed
                # (op_prefilter & op_value ⇒ t_unc), rows where it
                # doesn't are exact; sibling exact ops are unaffected.
                # Refund any always-on rx budget the discarded sibling
                # recs had claimed.
                for rec in recs:
                    for _lp, _ci, _stream, gate in rec.get("rx") or []:
                        if not gate:
                            rx_always_budget[0] += 1
                recs = [lower_matcher_superset(m) for m in op.matchers]
            lowered_ops.append(
                {
                    "cond_and": op.matchers_condition == "and",
                    "matchers": recs,
                    "prefilter": not exact,
                    "op_local": op_local,
                }
            )
        op_ids = []
        prefiltered = False
        t_idx = len(t_ops)  # this template's index once kept
        for lop in lowered_ops:
            if not lop["matchers"]:
                continue
            m_ids = []
            for m_local, rec in enumerate(lop["matchers"]):
                m_ids.append(len(matchers))
                # provenance back to the source nuclei matcher so the
                # host can re-evaluate exactly this matcher (engine's
                # sparse confirmation path) instead of the whole template.
                # A synthesized extraction prefilter has no source
                # matcher: m_local = -1 (the op is always a prefilter,
                # so confirmation re-runs the whole op, never this slot)
                rec["src"] = (
                    t_idx,
                    lop["op_local"],
                    -1 if rec.get("pseudo_ext") else m_local,
                )
                matchers.append(rec)
            ops.append(
                {
                    "cond_and": lop["cond_and"],
                    "matchers": m_ids,
                    "prefilter": lop["prefilter"],
                    "src": (t_idx, lop["op_local"]),
                }
            )
            op_ids.append(len(ops) - 1)
            prefiltered |= lop["prefilter"]
        if not op_ids:
            # no matchers and no extractors anywhere: never matches
            # (same as oracle)
            continue
        t_ops.append(op_ids)
        kept_templates.append(template)
        t_prefilter_flags.append(prefiltered)

    # --- build slot arrays ---
    NW = len(slots.entries)
    slot_bytes = np.zeros((max(NW, 1), verify_width), dtype=np.uint8)
    slot_len = np.zeros((max(NW, 1),), dtype=np.int32)
    for i, (data, _stream, _lowered) in enumerate(slots.entries):
        view = data[:verify_width]
        slot_bytes[i, : len(view)] = np.frombuffer(view, dtype=np.uint8)
        slot_len[i] = len(data)
    slot_long = slot_len > verify_width

    # --- build q-gram tables + tiny path ---
    # Each slot picks its rarest gram; oversized (table, h1) groups then
    # shed members to their next-rarest gram so the kernel's per-group
    # loop bound stays small.
    table_members: dict[tuple[str, bool, int], list[tuple[int, int, int, int]]] = {}
    tiny: list[int] = []
    placements: dict[int, tuple[tuple, int, int, int]] = {}  # slot -> (tkey, h1, h2, off)
    candidates: dict[int, list[int]] = {}
    group_sizes: dict[tuple, int] = {}  # (tkey, h1) -> count

    def _hash_at(data: bytes, off: int, q: int) -> tuple[int, int]:
        return hashing.gram_hash_np(data[off : off + q], q)

    for slot_id, (data, stream, lowered) in enumerate(slots.entries):
        if len(data) < hashing.GRAM_SHORT:
            tiny.append(slot_id)
            continue
        q = hashing.GRAM_LONG if len(data) >= hashing.GRAM_LONG else hashing.GRAM_SHORT
        tkey = (stream, lowered, q)
        offs = _gram_offsets_by_rarity(data, q)
        candidates[slot_id] = offs
        off = offs[0]
        h1, h2 = _hash_at(data, off, q)
        placements[slot_id] = (tkey, h1, h2, off)
        group_sizes[(tkey, h1)] = group_sizes.get((tkey, h1), 0) + 1

    for _round in range(12):
        oversized = {k for k, n in group_sizes.items() if n > MAX_GROUP}
        if not oversized:
            break
        moved = False
        for slot_id, (tkey, h1, h2, off) in list(placements.items()):
            if (tkey, h1) not in oversized or group_sizes[(tkey, h1)] <= MAX_GROUP:
                continue
            data = slots.entries[slot_id][0]
            q = tkey[2]
            for alt in candidates[slot_id]:
                if alt == off:
                    continue
                ah1, ah2 = _hash_at(data, alt, q)
                if group_sizes.get((tkey, ah1), 0) < MAX_GROUP:
                    group_sizes[(tkey, h1)] -= 1
                    group_sizes[(tkey, ah1)] = group_sizes.get((tkey, ah1), 0) + 1
                    placements[slot_id] = (tkey, ah1, ah2, alt)
                    moved = True
                    break
        if not moved:
            break

    for slot_id, (tkey, h1, h2, off) in placements.items():
        table_members.setdefault(tkey, []).append((h1, h2, slot_id, off))

    tables: list[WordTable] = []
    # corpus-delta table reuse: content key = the sorted member list
    # (post-shedding placements) + a digest of the member payload
    # bytes — together they determine every output array, so a key
    # match makes the old WordTable bit-identical to what this build
    # would produce and it is adopted by object identity
    reuse_keys: dict = getattr(reuse_from, "_table_keys", None) or {}
    reuse_tables: dict = (
        {
            (t.stream, t.lowered, t.q): t
            for t in getattr(reuse_from, "tables", ())
        }
        if reuse_from is not None
        else {}
    )
    table_keys: dict = {}
    tables_reused = 0

    def _members_key(members: list) -> tuple:
        import hashlib as _hashlib

        h = _hashlib.sha256()
        for _h1, _h2, slot_id, _off in members:
            data = slots.entries[slot_id][0]
            h.update(len(data).to_bytes(8, "little"))
            h.update(data)
        return (tuple(members), h.hexdigest())

    for (stream, lowered, q), members in sorted(table_members.items()):
        members.sort()
        tkey = (stream, lowered, q)
        content_key = _members_key(members)
        table_keys[tkey] = content_key
        if (
            reuse_keys.get(tkey) == content_key
            and tkey in reuse_tables
        ):
            tables.append(reuse_tables[tkey])
            tables_reused += 1
            continue
        group_h1: list[int] = []
        entry_start: list[int] = []
        entry_count: list[int] = []
        e_h2: list[int] = []
        e_slot: list[int] = []
        e_off: list[int] = []
        e_len: list[int] = []
        e_sufd: list[int] = []
        e_sufh1: list[int] = []
        e_sufh2: list[int] = []
        for h1, h2, slot_id, off in members:
            if not group_h1 or group_h1[-1] != h1:
                group_h1.append(h1)
                entry_start.append(len(e_h2))
                entry_count.append(0)
            entry_count[-1] += 1
            data = slots.entries[slot_id][0]
            # suffix gram: the rarest window *different* from the main
            # gram. The last-q-bytes choice made same-suffix families
            # ("…</title>") share a trivially-true check (delta 0) —
            # the false-fire storm the device verify then had to absorb.
            suf_off = next(
                (a for a in candidates.get(slot_id, [0]) if a != off),
                len(data) - q,
            )
            sh1, sh2 = _hash_at(data, suf_off, q)
            e_h2.append(h2)
            e_slot.append(slot_id)
            e_off.append(off)
            e_len.append(len(data))
            e_sufd.append(suf_off - off)
            e_sufh1.append(sh1)
            e_sufh2.append(sh2)
        max_group = max(entry_count)
        if max_group > MAX_GROUP:
            # A pathological slot population (many near-identical
            # literals sharing every rare gram) can defeat the shedding
            # loop. Correctness never depends on the bound — every
            # entry hit is byte-verified in the kernel — so degrade:
            # this one table's unrolled verify loop grows to the actual
            # group size (device cost, not a verdict risk). Crashing
            # the compile would lose the whole DB to save device time.
            # The degrade is itself bounded: past HARD_GROUP the unroll
            # would dominate XLA compile and the hot loop, so that
            # stays a loud failure.
            if max_group > HARD_GROUP:
                raise ValueError(
                    f"word-table group overflow ({max_group} > hard cap "
                    f"{HARD_GROUP}); diversify gram offsets or split "
                    "the slot population"
                )
            print(
                f"[compile] word-table group overflow ({max_group} > "
                f"{MAX_GROUP}) on table {(stream, lowered, q)}; "
                f"unrolling that table's verify loop to {max_group}",
                file=sys.stderr,
            )
        # Bloom carries every entry's (h1, h2) pair so a probe can only
        # pass where some entry's gram might start.
        tables.append(
            WordTable(
                stream=stream,
                lowered=lowered,
                q=q,
                group_h1=np.array(group_h1, dtype=np.uint32),
                entry_start=np.array(entry_start, dtype=np.int32),
                entry_count=np.array(entry_count, dtype=np.int32),
                entry_h2=np.array(e_h2, dtype=np.uint32),
                entry_slot=np.array(e_slot, dtype=np.int32),
                entry_off=np.array(e_off, dtype=np.int32),
                entry_len=np.array(e_len, dtype=np.int32),
                entry_suf_delta=np.array(e_sufd, dtype=np.int32),
                entry_suf_h1=np.array(e_sufh1, dtype=np.uint32),
                entry_suf_h2=np.array(e_sufh2, dtype=np.uint32),
                bloom=hashing.build_bloom_np(
                    np.repeat(
                        np.array(group_h1, dtype=np.uint32),
                        np.array(entry_count, dtype=np.int64),
                    ),
                    np.array(e_h2, dtype=np.uint32),
                ),
                max_group=max_group,
            )
        )

    NTINY = len(tiny)
    tiny_bytes = np.zeros((max(NTINY, 1), hashing.TINY_MAX), dtype=np.uint8)
    tiny_len = np.zeros((max(NTINY, 1),), dtype=np.int32)
    tiny_slot = np.zeros((max(NTINY, 1),), dtype=np.int32)
    tiny_stream = np.zeros((max(NTINY, 1),), dtype=np.int32)
    tiny_lowered = np.zeros((max(NTINY, 1),), dtype=bool)
    for i, slot_id in enumerate(tiny):
        data, stream, lowered = slots.entries[slot_id]
        tiny_bytes[i, : len(data)] = np.frombuffer(data, dtype=np.uint8)
        tiny_len[i] = len(data)
        tiny_slot[i] = slot_id
        tiny_stream[i] = STREAMS.index(stream)
        tiny_lowered[i] = lowered

    # --- matcher arrays ---
    NM = max(len(matchers), 1)
    max_status = max(
        (max(len(r["status"]), len(r["size"])) for r in matchers), default=1
    ) or 1
    m_kind = np.zeros((NM,), dtype=np.int32)
    m_negative = np.zeros((NM,), dtype=bool)
    m_cond_and = np.zeros((NM,), dtype=bool)
    m_scalar = np.zeros((NM, MAX_SCALAR_CONJUNCTS, 3), dtype=np.float32)
    m_scalar[:, :, 1] = SOP_TRUE
    m_residue = np.zeros((NM,), dtype=bool)
    m_status = np.full((NM, max_status), -1, dtype=np.int32)
    m_size = np.full((NM, max_status), -1, dtype=np.int32)
    m_size_stream = np.zeros((NM,), dtype=np.int32)
    m_md5 = np.zeros((NM, 4), dtype=np.uint32)
    m_md5_check = np.zeros((NM,), dtype=bool)
    for i, rec in enumerate(matchers):
        m_kind[i] = rec["kind"]
        m_negative[i] = rec["negative"]
        m_cond_and[i] = rec["cond_and"]
        for j, (var, op, val) in enumerate(rec["scalar"][:MAX_SCALAR_CONJUNCTS]):
            m_scalar[i, j] = (var, op, val)
        m_residue[i] = rec["residue"]
        if rec.get("md5") is not None:
            m_md5[i] = np.frombuffer(rec["md5"], dtype="<u4")
            m_md5_check[i] = True
        for j, s in enumerate(rec["status"]):
            m_status[i, j] = s
        for j, s in enumerate(rec["size"]):
            m_size[i, j] = s
        m_size_stream[i] = rec["size_stream"]
    m_slot_buckets = bucket_ragged([r["slots"] for r in matchers], NM)
    m_negslot_buckets = bucket_ragged(
        [r.get("neg_slots", []) for r in matchers], NM
    )

    # --- device-regex sequence tables ---
    rx_matchers = [
        (i, rec) for i, rec in enumerate(matchers) if rec.get("rx")
    ]
    rx_m_ids = np.array([i for i, _ in rx_matchers], dtype=np.int32)
    seqs: list[tuple[int, object, bool, str, list]] = []
    for rxi, (_m_id, rec) in enumerate(rx_matchers):
        for lp, ci, stream, gate in rec["rx"]:
            seqs.append((rxi, lp, ci, stream, gate))
    rx_seq_slot_buckets = bucket_ragged(
        [s[4] for s in seqs], max(len(seqs), 1)
    )
    rx_seq_always = np.array(
        [not s[4] for s in seqs] or [False], dtype=bool
    )
    NSEQ = max(len(seqs), 1)
    rx_max_m = max((s[1].m for s in seqs), default=1)
    rx_lanes = (rx_max_m + 31) // 32  # uint32 state lanes
    rx_seq_matcher = np.zeros((NSEQ,), dtype=np.int32)
    rx_seq_stream = np.zeros((NSEQ,), dtype=np.int32)
    rx_seq_ci = np.zeros((NSEQ,), dtype=bool)
    rx_classes = np.zeros((NSEQ, rx_max_m, 8), dtype=np.uint32)
    rx_m_count = np.ones((NSEQ,), dtype=np.int32)
    rx_seed = np.zeros((NSEQ, rx_lanes), dtype=np.uint32)
    rx_skip = np.zeros((NSEQ, rx_lanes), dtype=np.uint32)
    rx_accept = np.zeros((NSEQ, rx_lanes), dtype=np.uint32)
    rx_self = np.zeros((NSEQ, rx_lanes), dtype=np.uint32)
    rx_anchored = np.zeros((NSEQ,), dtype=bool)
    rx_end_mode = np.zeros((NSEQ,), dtype=np.int32)
    rx_start_wb = np.zeros((NSEQ,), dtype=bool)
    rx_end_wb = np.zeros((NSEQ,), dtype=bool)
    rx_max_skip_run = 0
    for si, (rxi, lp, ci, stream, _gate) in enumerate(seqs):
        rx_seq_matcher[si] = rxi
        rx_seq_stream[si] = STREAMS.index(stream)
        rx_seq_ci[si] = ci
        rx_classes[si, : lp.m] = lp.classes
        rx_m_count[si] = lp.m
        rx_anchored[si] = lp.anchored
        rx_end_mode[si] = lp.end_mode
        rx_start_wb[si] = lp.start_wb
        rx_end_wb[si] = lp.end_wb
        seed, skip, accept, sl = regexlin.derived_masks(lp)
        for j, v in enumerate((seed, skip, accept, sl)):
            arr = (rx_seed, rx_skip, rx_accept, rx_self)[j]
            for ln in range(rx_lanes):
                arr[si, ln] = (v >> (32 * ln)) & 0xFFFFFFFF
        rx_max_skip_run = max(rx_max_skip_run, lp.max_skip_run)
    # byte → position-bits lookup (the kernel's per-byte B[c] masks):
    # transpose of rx_classes into state lanes.
    rx_bytemap = np.zeros((NSEQ, 256, rx_lanes), dtype=np.uint32)
    if seqs:
        for c in range(256):
            bits = (rx_classes[:, :, c >> 5] >> np.uint32(c & 31)) & 1
            for i in range(rx_max_m):
                rx_bytemap[:, c, i // 32] |= bits[:, i].astype(
                    np.uint32
                ) << np.uint32(i % 32)

    # --- operation / template arrays ---
    NOP = max(len(ops), 1)
    op_cond_and = np.zeros((NOP,), dtype=bool)
    op_prefilter = np.zeros((NOP,), dtype=bool)
    for i, o in enumerate(ops):
        op_cond_and[i] = o["cond_and"]
        op_prefilter[i] = o["prefilter"]
    op_m_buckets = bucket_ragged([o["matchers"] for o in ops], NOP)
    t_op_buckets = bucket_ragged(t_ops, max(len(t_ops), 1))

    t_prefilter = np.array(t_prefilter_flags or [False], dtype=bool)

    # provenance for the engine's sparse host-confirmation: device
    # matcher/op id → source (template, operation[, matcher]) indices
    m_src = np.zeros((NM, 3), dtype=np.int32)
    for i, rec in enumerate(matchers):
        m_src[i] = rec["src"]
    # per-pattern extraction provenance: matcher id -> (extractor_local,
    # pattern_idx) for synthesized extraction prefilters, (-1, -1)
    # otherwise (incl. the fire-always degrade, which confirms whole-op)
    m_ext_src = np.full((NM, 2), -1, dtype=np.int32)
    for i, rec in enumerate(matchers):
        pe = rec.get("pseudo_ext")
        if isinstance(pe, tuple):
            m_ext_src[i] = pe
    op_src = np.zeros((NOP, 2), dtype=np.int32)
    for i, o in enumerate(ops):
        op_src[i] = o["src"]
    op_matchers = [list(o["matchers"]) for o in ops]

    stats = {
        "templates_in": len(templates),
        "templates_device": len(kept_templates),
        "templates_prefilter": int(sum(t_prefilter_flags)),
        "ops_prefilter": int(op_prefilter.sum()),
        "templates_host_always": len(host_always),
        "matchers": len(matchers),
        "rx_matchers": len(rx_matchers),
        "rx_sequences": len(seqs),
        "word_slots": NW,
        "tiny_slots": NTINY,
        "tables": {
            f"{t.stream}/{'ci' if t.lowered else 'cs'}/q{t.q}": int(
                t.entry_h2.shape[0]
            )
            for t in tables
        },
    }

    if delta_stats is not None:
        delta_stats["tables_total"] = len(tables)
        delta_stats["tables_reused"] = tables_reused
        delta_stats["tables_rebuilt"] = len(tables) - tables_reused
    out_db = CompiledDB(
        slot_bytes=slot_bytes,
        slot_len=slot_len,
        slot_long=slot_long,
        tables=tables,
        tiny_bytes=tiny_bytes,
        tiny_len=tiny_len,
        tiny_slot=tiny_slot,
        tiny_stream=tiny_stream,
        tiny_lowered=tiny_lowered,
        m_kind=m_kind,
        m_negative=m_negative,
        m_cond_and=m_cond_and,
        m_slot_buckets=m_slot_buckets,
        m_negslot_buckets=m_negslot_buckets,
        m_scalar=m_scalar,
        m_residue=m_residue,
        m_md5=m_md5,
        m_md5_check=m_md5_check,
        rx_m_ids=rx_m_ids,
        rx_seq_slot_buckets=rx_seq_slot_buckets,
        rx_seq_always=rx_seq_always,
        rx_seq_matcher=rx_seq_matcher,
        rx_seq_stream=rx_seq_stream,
        rx_seq_ci=rx_seq_ci,
        rx_classes=rx_classes,
        rx_bytemap=rx_bytemap,
        rx_m_count=rx_m_count,
        rx_seed=rx_seed,
        rx_skip=rx_skip,
        rx_accept=rx_accept,
        rx_self=rx_self,
        rx_anchored=rx_anchored,
        rx_end_mode=rx_end_mode,
        rx_start_wb=rx_start_wb,
        rx_end_wb=rx_end_wb,
        rx_max_skip_run=rx_max_skip_run,
        m_status=m_status,
        m_size=m_size,
        m_size_stream=m_size_stream,
        op_cond_and=op_cond_and,
        op_prefilter=op_prefilter,
        op_m_buckets=op_m_buckets,
        t_op_buckets=t_op_buckets,
        t_prefilter=t_prefilter,
        m_src=m_src,
        m_ext_src=m_ext_src,
        op_src=op_src,
        op_matchers=op_matchers,
        t_ops=[list(o) for o in t_ops],
        template_ids=[t.id for t in kept_templates],
        host_always=host_always,
        templates=kept_templates,
        stats=stats,
    )
    # the delta-reuse registry (rides dbcache pickles: plain tuples,
    # a few ints per entry) — absent on pre-delta pickles, which then
    # simply take the full-rebuild path
    out_db._table_keys = table_keys
    # workflow DAGs lower against the finished device id spaces (the
    # delta path rebuilds the plan too — gate tables are tiny)
    out_db.wf = lower_workflows(list(templates), out_db)
    stats["workflows"] = out_db.wf.stats
    return out_db
