"""Template corpus → dense tensor database for the device match kernels.

Lowering strategy (designed for TPU/XLA, not a port — the reference
shells out to nuclei/nmap for this entire layer):

- Every *word-like* payload (word matchers, binary matchers, dsl
  ``contains`` conjuncts, regex required-literals) becomes a **word
  slot**: a (bytes, stream, case) triple. Slots of length ≥ 4 register a
  q-gram (8-gram, or 4-gram for short words) in per-(stream, case, q)
  hash tables — sorted unique h1 groups + entry arrays + a Bloom bitmap
  probed by the kernel. Tiny slots (1–3 bytes) take a dense shifted
  compare (exact). The kernel verifies q-gram hits via 128 hash bits
  (entry h1/h2 + suffix-gram h1/h2) — every q-gram hit is marked
  *uncertain* and host-confirmed (hits are sparse in scanning), so no
  byte gathers run on device. ``slot_bytes``/``slot_len`` are retained
  for the planned fused-Pallas byte-exact verify, which will clear the
  uncertain bit on device.
- Matchers lower to records over those bits plus scalar features
  (status, part lengths): word/binary → slot-bucket reductions,
  status/size → scalar compares, simple dsl → conjunctive scalar
  programs (len/status/content_length) with optional residues (md5 → a
  digest check the host or the device md5 kernel confirms), regex → a
  prefilter slot whose hits are uncertain-by-construction.
- Matchers that cannot be soundly approximated (kval/json/xpath,
  literal-less regex, exotic dsl) force their template onto the
  **host-always** list — evaluated by the exact CPU oracle so overall
  parity stays 100%; the compiler reports how much of the corpus that
  tail is.
- Out-of-band parts (``interactsh_*``) are constant-False on both
  engines (no interaction server in either framework's scope).

Uncertainty contract (the parity invariant): a matcher's device bit is
exact unless its ``uncertain`` bit is set, and uncertain bits can only
be set when the underlying superset signal *fired* — absence of a hit is
always exact. Host confirmation therefore only runs on (row, template)
pairs whose verdict actually fired an uncertain matcher.
"""

from __future__ import annotations

import binascii
import dataclasses
import re
from typing import Optional

import numpy as np

from swarm_tpu.fingerprints import dslc
from swarm_tpu.fingerprints.model import Matcher, Template
from swarm_tpu.ops import hashing
from swarm_tpu.ops.encoding import (
    HOST_ONLY_PARTS,
    STREAMS,
    lower_bytes_np,
    stream_for_part,
)

# ---------------------------------------------------------------------------
# Constants / enums (shared with ops.match / ops.verdict)
# ---------------------------------------------------------------------------

VERIFY_WIDTH = 64  # byte-exact verify cap; longer slots are prefix+host

# Matcher kinds
MK_CONST_FALSE = 0
MK_WORDS = 1  # word/binary/contains — slots under this matcher's condition
MK_STATUS = 2
MK_SIZE = 3
MK_SCALAR_DSL = 4  # conjunctive scalar program (+ optional residue)
MK_REGEX_PREFILTER = 5  # slot bit is a superset; hit ⇒ uncertain

# Scalar-program variable ids
SV_STATUS = 0
SV_LEN_BODY = 1
SV_LEN_HEADER = 2
SV_LEN_ALL = 3
SV_CONTENT_LENGTH = 4
SCALAR_VARS = 5

# Scalar-program comparison ops
SOP_EQ, SOP_NE, SOP_LT, SOP_GT, SOP_LE, SOP_GE, SOP_TRUE = range(7)

MAX_SCALAR_CONJUNCTS = 6
MAX_GROUP = 8  # max word slots sharing one (table, h1) group

# Rough byte-commonness weights for picking the rarest q-gram of a word.
_COMMON = np.zeros(256, dtype=np.float32)
for _c in b"etaoinshrdlucmfwygpb ":
    _COMMON[_c] = 1.0
for _c in b"ETAOINSHRDLU<>/\"'=.-_:;()0123456789":
    _COMMON[_c] = 0.7
for _c in b"\r\n\t&?%+,![]{}":
    _COMMON[_c] = 0.5


def _gram_offsets_by_rarity(data: bytes, q: int) -> list[int]:
    """Candidate gram offsets, rarest window first."""
    if len(data) <= q:
        return [0]
    weights = _COMMON[np.frombuffer(data, dtype=np.uint8)]
    window_scores = np.convolve(weights, np.ones(q), mode="valid")
    return list(np.argsort(window_scores, kind="stable").astype(int))


# ---------------------------------------------------------------------------
# Regex required-literal extraction (prefilter factory)
# ---------------------------------------------------------------------------


def required_literal(pattern: str, min_len: int = 4) -> Optional[bytes]:
    """Longest byte literal that must occur in any match of ``pattern``.

    Conservative walk of the sre parse tree: only literals on required,
    non-alternating paths count. Returns None when nothing ≥ min_len is
    guaranteed — those regexes make their template host-always.
    """
    try:
        import re._parser as sre_parse  # py3.11+
    except ImportError:  # pragma: no cover
        import sre_parse  # type: ignore
    try:
        tree = sre_parse.parse(pattern)
    except re.error:
        return None

    global_ci = bool(tree.state.flags & re.IGNORECASE)

    # best required literal; a run collected under case-insensitivity
    # (global or scoped (?i:...)) is unusable if it has non-ASCII bytes —
    # Python folds Unicode over the latin-1 decode, device lowering is
    # ASCII-only, so the lowered probe would not be a superset.
    best: list[bytes] = [b""]

    def consider(run: bytes, ci: bool) -> None:
        if ci and any(b >= 0x80 for b in run):
            return
        if len(run) > len(best[0]):
            best[0] = bytes(run)

    def walk(seq, ci: bool) -> None:
        run = bytearray()

        def flush():
            nonlocal run
            consider(bytes(run), ci)
            run = bytearray()

        for op, arg in seq:
            opname = str(op)
            if opname == "LITERAL" and 0 <= arg < 256:
                run.append(arg)
            elif opname == "MAX_REPEAT" or opname == "MIN_REPEAT":
                lo, _hi, child = arg
                flush()
                if lo >= 1:
                    walk(child, ci)
            elif opname == "SUBPATTERN":
                # arg = (group, add_flags, del_flags, seq): scoped flags
                flush()
                child_ci = (ci or bool(arg[1] & re.IGNORECASE)) and not bool(
                    arg[2] & re.IGNORECASE
                )
                walk(arg[3], child_ci)
            elif opname == "AT":
                # zero-width assertion: consumes nothing, so bytes on either
                # side are still adjacent in any match — run continues.
                continue
            else:
                # IN, BRANCH, ANY, CATEGORY, GROUPREF… — not a required literal
                flush()
        flush()

    walk(tree, global_ci)
    lit = best[0]
    if len(lit) < min_len:
        return None
    # Always ASCII-lowercase: the prefilter probes the *lowered* stream,
    # a sound superset for case-sensitive regexes (non-A-Z bytes are
    # untouched in both literal and stream) and for (?i) regexes with
    # ASCII literals.
    return bytes(lower_bytes_np(np.frombuffer(lit, np.uint8)).tobytes())


# ---------------------------------------------------------------------------
# DSL lowering: conjunctive scalar programs + contains/md5 residues
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ScalarProgram:
    conjuncts: list[tuple[int, int, float]]  # (var, op, value)
    contains: list[tuple[bytes, str, bool]]  # (needle, stream, case_insensitive)
    residue: bool = False  # md5/sha residue → hit needs host confirm
    never: bool = False  # statically unsatisfiable (e.g. "AbC" in tolower(x))


_CMP_OPS = {"==": SOP_EQ, "!=": SOP_NE, "<": SOP_LT, ">": SOP_GT, "<=": SOP_LE, ">=": SOP_GE}
_SWAP = {SOP_LT: SOP_GT, SOP_GT: SOP_LT, SOP_LE: SOP_GE, SOP_GE: SOP_LE}


def _scalar_var(node) -> Optional[int]:
    if node[0] == "var" and node[1] == "status_code":
        return SV_STATUS
    if node[0] == "var" and node[1] == "content_length":
        return SV_CONTENT_LENGTH
    if node[0] == "call" and node[1] == "len" and len(node[2]) == 1:
        inner = node[2][0]
        if inner[0] == "var":
            return {
                "body": SV_LEN_BODY,
                "header": SV_LEN_HEADER,
                "all_headers": SV_LEN_HEADER,
                "raw": SV_LEN_ALL,
            }.get(inner[1])
    return None


def _part_stream_of_var(node) -> Optional[tuple[str, Optional[str]]]:
    """(stream, case_wrap) for body/header vars; case_wrap ∈ {None,
    'lower', 'upper'} from a tolower()/toupper() wrapper."""
    wrap: Optional[str] = None
    while node[0] == "call" and node[1] in ("tolower", "toupper") and len(node[2]) == 1:
        wrap = "lower" if node[1] == "tolower" else "upper"
        node = node[2][0]
    if node[0] == "var":
        stream = {"body": "body", "header": "header", "all_headers": "header", "raw": "all"}.get(node[1])
        if stream:
            return stream, wrap
    return None


_HASH_FNS = ("md5", "sha1", "sha256", "mmh3")


def lower_dsl(ast) -> Optional[ScalarProgram]:
    """Lower one dsl expression to a scalar program, or None if it
    doesn't fit the supported shape (top-level conjunction of scalar
    compares / contains / hash-equality residues)."""
    prog = ScalarProgram(conjuncts=[], contains=[])

    def handle(node) -> bool:
        if node[0] == "bin" and node[1] == "&&":
            return handle(node[2]) and handle(node[3])
        if node[0] == "bin" and node[1] in _CMP_OPS:
            op = _CMP_OPS[node[1]]
            lhs, rhs = node[2], node[3]
            for a, b, swapped in ((lhs, rhs, False), (rhs, lhs, True)):
                var = _scalar_var(a)
                if var is not None and b[0] == "lit" and isinstance(b[1], (int, float)):
                    real_op = _SWAP.get(op, op) if swapped else op
                    prog.conjuncts.append((var, real_op, float(b[1])))
                    return True
            # hash-equality residue:  md5(body) == "…"  (either side)
            for a, b in ((lhs, rhs), (rhs, lhs)):
                if (
                    op == SOP_EQ
                    and a[0] == "call"
                    and a[1] in _HASH_FNS
                    and b[0] == "lit"
                    and isinstance(b[1], str)
                ):
                    prog.residue = True
                    return True
            return False
        if node[0] == "call" and node[1] == "contains" and len(node[2]) == 2:
            hay, needle = node[2]
            loc = _part_stream_of_var(hay)
            if loc and needle[0] == "lit" and isinstance(needle[1], str):
                stream, wrap = loc
                data = needle[1].encode()
                if len(data) == 0:
                    return False
                if wrap is None:
                    prog.contains.append((data, stream, False))
                elif wrap == "lower":
                    if data != data.lower():
                        # an uppercase needle can never occur in a
                        # lowercased haystack — statically false
                        prog.never = True
                    else:
                        prog.contains.append((data, stream, True))
                else:  # upper
                    if data != data.upper():
                        prog.never = True
                    else:
                        prog.contains.append((data.lower(), stream, True))
                return True
        return False

    if not handle(ast):
        return None
    if len(prog.conjuncts) > MAX_SCALAR_CONJUNCTS:
        return None
    return prog


# ---------------------------------------------------------------------------
# The compiled database
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class WordTable:
    """One (stream, case, gram-size) hash table.

    A window hit must match the entry's (h1, h2) *and* the word's
    suffix-gram hashes at position ``pos + suf_delta`` — 128 hash bits
    total, computed entirely from the rolling-hash arrays the kernel
    already has (no byte gathers). Hits are still marked *uncertain*
    and host-confirmed, so a hash collision can never corrupt a verdict;
    the hashes exist to make candidate traffic ≈ true-hit traffic.
    """

    stream: str
    lowered: bool
    q: int
    group_h1: np.ndarray  # uint32 [G] sorted unique
    entry_start: np.ndarray  # int32 [G]
    entry_count: np.ndarray  # int32 [G]
    entry_h2: np.ndarray  # uint32 [E]
    entry_slot: np.ndarray  # int32 [E]
    entry_off: np.ndarray  # int32 [E] gram offset within the slot bytes
    entry_len: np.ndarray  # int32 [E] true word length
    entry_suf_delta: np.ndarray  # int32 [E] = (len - q) - off  (suffix pos - window pos)
    entry_suf_h1: np.ndarray  # uint32 [E]
    entry_suf_h2: np.ndarray  # uint32 [E]
    bloom: np.ndarray  # uint32 [BLOOM_WORDS]
    max_group: int = 1

    @property
    def num_groups(self) -> int:
        return int(self.group_h1.shape[0])


@dataclasses.dataclass
class IndexBucket:
    """One width-class of a ragged index table.

    ``rows[i]`` owns ``idx[i, :width]``; rows with fewer real entries are
    padded by repeating their first entry (neutral for both AND and OR
    reductions).
    """

    width: int
    rows: np.ndarray  # int32 [NB] — owner ids (matcher / op / template)
    idx: np.ndarray  # int32 [NB, width]


def bucket_ragged(ragged: list[list[int]], owner_count: int) -> list[IndexBucket]:
    """Ragged owner→members lists → power-of-two width buckets.

    Total gather volume stays Σ|members| × (≤2) instead of
    owners × max(|members|).
    """
    by_width: dict[int, list[tuple[int, list[int]]]] = {}
    for owner, members in enumerate(ragged):
        if not members:
            continue
        width = 1
        while width < len(members):
            width *= 2
        by_width.setdefault(width, []).append((owner, members))
    buckets = []
    for width in sorted(by_width):
        rows = np.array([o for o, _ in by_width[width]], dtype=np.int32)
        idx = np.zeros((len(rows), width), dtype=np.int32)
        for i, (_o, members) in enumerate(by_width[width]):
            for j in range(width):
                idx[i, j] = members[j] if j < len(members) else members[0]
        buckets.append(IndexBucket(width=width, rows=rows, idx=idx))
    return buckets


@dataclasses.dataclass
class CompiledDB:
    # --- word slots ---
    slot_bytes: np.ndarray  # uint8 [NW, VERIFY_WIDTH] (lowered for ci slots)
    slot_len: np.ndarray  # int32 [NW] true length (may exceed VERIFY_WIDTH)
    slot_long: np.ndarray  # bool [NW] — len > VERIFY_WIDTH ⇒ hit is uncertain
    tables: list[WordTable]
    # tiny slots, dense path: per (stream, lowered) padded byte matrix
    tiny_bytes: np.ndarray  # uint8 [NTINY, TINY_MAX]
    tiny_len: np.ndarray  # int32 [NTINY]
    tiny_slot: np.ndarray  # int32 [NTINY]
    tiny_stream: np.ndarray  # int32 [NTINY] index into STREAMS
    tiny_lowered: np.ndarray  # bool [NTINY]

    # --- matchers ---
    m_kind: np.ndarray  # int32 [NM]
    m_negative: np.ndarray  # bool [NM]
    m_cond_and: np.ndarray  # bool [NM]
    m_slot_buckets: list  # list[IndexBucket] matcher → word-slot ids
    m_scalar: np.ndarray  # float32 [NM, MAX_SCALAR_CONJUNCTS, 3] (var, op, val)
    m_residue: np.ndarray  # bool [NM] — scalar pass still needs host confirm
    m_status: np.ndarray  # int32 [NM, MAX_STATUS] (pad = -1)
    m_size: np.ndarray  # int32 [NM, MAX_STATUS] (pad = -1)
    m_size_stream: np.ndarray  # int32 [NM] stream index for size matchers

    # --- operations & templates ---
    op_cond_and: np.ndarray  # bool [NOP]
    op_m_buckets: list  # list[IndexBucket] op → matcher ids
    t_op_buckets: list  # list[IndexBucket] template → op ids

    template_ids: list  # str [NT] — device-evaluated templates
    host_always: list  # list[Template] — exact-CPU-only tail
    templates: list  # the NT Template objects (for host confirmation)
    stats: dict

    @property
    def num_slots(self) -> int:
        return int(self.slot_bytes.shape[0])

    @property
    def num_templates(self) -> int:
        return len(self.template_ids)


# ---------------------------------------------------------------------------
# Compiler
# ---------------------------------------------------------------------------


class _SlotSpace:
    """Dedup (bytes, stream, lowered) → slot id."""

    def __init__(self) -> None:
        self.index: dict[tuple[bytes, str, bool], int] = {}
        self.entries: list[tuple[bytes, str, bool]] = []

    def get(self, data: bytes, stream: str, lowered: bool) -> int:
        if lowered:
            data = bytes(lower_bytes_np(np.frombuffer(data, np.uint8)).tobytes()) if data else data
        key = (data, stream, lowered)
        slot = self.index.get(key)
        if slot is None:
            slot = len(self.entries)
            self.index[key] = slot
            self.entries.append(key)
        return slot


def _word_payloads(matcher: Matcher) -> Optional[list[bytes]]:
    if matcher.type == "word":
        return [w.encode("utf-8", "surrogateescape") for w in matcher.words]
    if matcher.type == "binary":
        out = []
        for hexstr in matcher.binary:
            try:
                out.append(binascii.unhexlify(re.sub(r"\s", "", hexstr)))
            except (binascii.Error, ValueError):
                return None
        return out
    return None


def compile_corpus(
    templates: list[Template],
    verify_width: int = VERIFY_WIDTH,
) -> CompiledDB:
    slots = _SlotSpace()
    matchers: list[dict] = []
    ops: list[dict] = []
    t_ops: list[list[int]] = []
    kept_templates: list[Template] = []
    host_always: list[Template] = []

    def lower_matcher(m: Matcher) -> Optional[dict]:
        """→ matcher record dict, or None if not device-loweable."""
        rec = {
            "kind": MK_CONST_FALSE,
            "negative": m.negative,
            "cond_and": m.condition == "and",
            "slots": [],
            "scalar": [],
            "residue": False,
            "status": [],
            "size": [],
            "size_stream": 0,
        }

        def const(value: bool) -> dict:
            # constant matcher: encode as MK_CONST_FALSE with the
            # negation flag folded in (negative ^ value ≡ value after
            # the kernel's generic `value ^= negative` step)
            rec["kind"] = MK_CONST_FALSE
            rec["negative"] = bool(m.negative) ^ bool(value)
            return rec
        if m.type in ("word", "binary"):
            payloads = _word_payloads(m)
            if payloads is None or not payloads:
                return None
            if m.part in HOST_ONLY_PARTS:
                return None  # oracle has real bytes here; not device-loweable
            stream = stream_for_part(m.part)
            if stream is None:
                return rec  # unknown/OOB part: constant False on both engines
            if any(len(p) == 0 for p in payloads):
                return None
            # cpu_ref (like nuclei) ignores case-insensitive for binary
            # payloads — keep the device identical.
            lowered = m.case_insensitive and m.type == "word"
            rec["kind"] = MK_WORDS
            rec["slots"] = [slots.get(p, stream, lowered) for p in payloads]
            return rec
        if m.type == "status":
            if not m.status:
                return None
            rec["kind"] = MK_STATUS
            rec["status"] = list(m.status)
            return rec
        if m.type == "size":
            stream = stream_for_part(m.part)
            if not m.size:
                return None
            if stream is None:
                # oracle sees b"" for this part: len==0 is a compile-time
                # constant (size [0] matches the empty part!)
                return const(0 in m.size)
            rec["kind"] = MK_SIZE
            rec["size"] = list(m.size)
            rec["size_stream"] = STREAMS.index(stream)
            return rec
        if m.type == "regex":
            stream = stream_for_part(m.part)
            if stream is None:
                # oracle runs the regex over the empty string — also a
                # compile-time constant (e.g. `.*` matches empty)
                results = []
                for pattern in m.regex:
                    try:
                        results.append(re.search(pattern, "") is not None)
                    except re.error:
                        return None
                if not results:
                    return None
                value = all(results) if m.condition == "and" else any(results)
                return const(value)
            # every regex in the list needs its own required literal; the
            # matcher bit is the OR/AND of per-regex prefilter bits.
            slot_ids = []
            for pattern in m.regex:
                lit = required_literal(pattern)
                if lit is None:
                    return None
                # prefilter literals always probe the lowered stream (sound
                # superset regardless of the regex's case flags)
                slot_ids.append(slots.get(lit, stream, True))
            if not slot_ids:
                return None
            rec["kind"] = MK_REGEX_PREFILTER
            rec["slots"] = slot_ids
            return rec
        if m.type == "dsl":
            progs = []
            for expr in m.dsl:
                ast = dslc.try_parse(expr)
                if ast is None:
                    return None
                prog = lower_dsl(ast)
                if prog is None:
                    return None
                progs.append(prog)
            if len(progs) != 1:
                # multi-expression dsl matchers are rare; host them for now
                return None
            prog = progs[0]
            if prog.never:
                return rec  # statically unsatisfiable: constant False
            rec["kind"] = MK_SCALAR_DSL
            rec["scalar"] = prog.conjuncts
            rec["residue"] = prog.residue
            rec["cond_and"] = True  # conjuncts and contains() are all AND'd
            rec["slots"] = [
                slots.get(needle, stream, lowered)
                for needle, stream, lowered in prog.contains
            ]
            return rec
        return None  # kval / json / xpath

    for template in templates:
        if template.protocol == "workflow" or not template.operations:
            continue
        lowered_ops: list[dict] = []
        ok = True
        for op in template.operations:
            recs = []
            for m in op.matchers:
                rec = lower_matcher(m)
                if rec is None:
                    ok = False
                    break
                recs.append(rec)
            if not ok:
                break
            lowered_ops.append(
                {"cond_and": op.matchers_condition == "and", "matchers": recs}
            )
        if not ok:
            host_always.append(template)
            continue
        op_ids = []
        for lop in lowered_ops:
            if not lop["matchers"]:
                continue
            m_ids = []
            for rec in lop["matchers"]:
                m_ids.append(len(matchers))
                matchers.append(rec)
            ops.append({"cond_and": lop["cond_and"], "matchers": m_ids})
            op_ids.append(len(ops) - 1)
        if not op_ids:
            # no matchers anywhere: never matches (same as oracle)
            continue
        t_ops.append(op_ids)
        kept_templates.append(template)

    # --- build slot arrays ---
    NW = len(slots.entries)
    slot_bytes = np.zeros((max(NW, 1), verify_width), dtype=np.uint8)
    slot_len = np.zeros((max(NW, 1),), dtype=np.int32)
    for i, (data, _stream, _lowered) in enumerate(slots.entries):
        view = data[:verify_width]
        slot_bytes[i, : len(view)] = np.frombuffer(view, dtype=np.uint8)
        slot_len[i] = len(data)
    slot_long = slot_len > verify_width

    # --- build q-gram tables + tiny path ---
    # Each slot picks its rarest gram; oversized (table, h1) groups then
    # shed members to their next-rarest gram so the kernel's per-group
    # loop bound stays small.
    table_members: dict[tuple[str, bool, int], list[tuple[int, int, int, int]]] = {}
    tiny: list[int] = []
    placements: dict[int, tuple[tuple, int, int, int]] = {}  # slot -> (tkey, h1, h2, off)
    candidates: dict[int, list[int]] = {}
    group_sizes: dict[tuple, int] = {}  # (tkey, h1) -> count

    def _hash_at(data: bytes, off: int, q: int) -> tuple[int, int]:
        return hashing.gram_hash_np(data[off : off + q], q)

    for slot_id, (data, stream, lowered) in enumerate(slots.entries):
        if len(data) < hashing.GRAM_SHORT:
            tiny.append(slot_id)
            continue
        q = hashing.GRAM_LONG if len(data) >= hashing.GRAM_LONG else hashing.GRAM_SHORT
        tkey = (stream, lowered, q)
        offs = _gram_offsets_by_rarity(data, q)
        candidates[slot_id] = offs
        off = offs[0]
        h1, h2 = _hash_at(data, off, q)
        placements[slot_id] = (tkey, h1, h2, off)
        group_sizes[(tkey, h1)] = group_sizes.get((tkey, h1), 0) + 1

    for _round in range(12):
        oversized = {k for k, n in group_sizes.items() if n > MAX_GROUP}
        if not oversized:
            break
        moved = False
        for slot_id, (tkey, h1, h2, off) in list(placements.items()):
            if (tkey, h1) not in oversized or group_sizes[(tkey, h1)] <= MAX_GROUP:
                continue
            data = slots.entries[slot_id][0]
            q = tkey[2]
            for alt in candidates[slot_id]:
                if alt == off:
                    continue
                ah1, ah2 = _hash_at(data, alt, q)
                if group_sizes.get((tkey, ah1), 0) < MAX_GROUP:
                    group_sizes[(tkey, h1)] -= 1
                    group_sizes[(tkey, ah1)] = group_sizes.get((tkey, ah1), 0) + 1
                    placements[slot_id] = (tkey, ah1, ah2, alt)
                    moved = True
                    break
        if not moved:
            break

    for slot_id, (tkey, h1, h2, off) in placements.items():
        table_members.setdefault(tkey, []).append((h1, h2, slot_id, off))

    tables: list[WordTable] = []
    for (stream, lowered, q), members in sorted(table_members.items()):
        members.sort()
        group_h1: list[int] = []
        entry_start: list[int] = []
        entry_count: list[int] = []
        e_h2: list[int] = []
        e_slot: list[int] = []
        e_off: list[int] = []
        e_len: list[int] = []
        e_sufd: list[int] = []
        e_sufh1: list[int] = []
        e_sufh2: list[int] = []
        for h1, h2, slot_id, off in members:
            if not group_h1 or group_h1[-1] != h1:
                group_h1.append(h1)
                entry_start.append(len(e_h2))
                entry_count.append(0)
            entry_count[-1] += 1
            data = slots.entries[slot_id][0]
            suf_off = len(data) - q  # suffix gram start within the word
            sh1, sh2 = _hash_at(data, suf_off, q)
            e_h2.append(h2)
            e_slot.append(slot_id)
            e_off.append(off)
            e_len.append(len(data))
            e_sufd.append(suf_off - off)
            e_sufh1.append(sh1)
            e_sufh2.append(sh2)
        max_group = max(entry_count)
        if max_group > MAX_GROUP:
            raise ValueError(
                f"word-table group overflow ({max_group} > {MAX_GROUP}); "
                "raise MAX_GROUP or diversify gram offsets"
            )
        # Bloom carries every entry's (h1, h2) pair so a probe can only
        # pass where some entry's gram might start.
        tables.append(
            WordTable(
                stream=stream,
                lowered=lowered,
                q=q,
                group_h1=np.array(group_h1, dtype=np.uint32),
                entry_start=np.array(entry_start, dtype=np.int32),
                entry_count=np.array(entry_count, dtype=np.int32),
                entry_h2=np.array(e_h2, dtype=np.uint32),
                entry_slot=np.array(e_slot, dtype=np.int32),
                entry_off=np.array(e_off, dtype=np.int32),
                entry_len=np.array(e_len, dtype=np.int32),
                entry_suf_delta=np.array(e_sufd, dtype=np.int32),
                entry_suf_h1=np.array(e_sufh1, dtype=np.uint32),
                entry_suf_h2=np.array(e_sufh2, dtype=np.uint32),
                bloom=hashing.build_bloom_np(
                    np.repeat(
                        np.array(group_h1, dtype=np.uint32),
                        np.array(entry_count, dtype=np.int64),
                    ),
                    np.array(e_h2, dtype=np.uint32),
                ),
                max_group=max_group,
            )
        )

    NTINY = len(tiny)
    tiny_bytes = np.zeros((max(NTINY, 1), hashing.TINY_MAX), dtype=np.uint8)
    tiny_len = np.zeros((max(NTINY, 1),), dtype=np.int32)
    tiny_slot = np.zeros((max(NTINY, 1),), dtype=np.int32)
    tiny_stream = np.zeros((max(NTINY, 1),), dtype=np.int32)
    tiny_lowered = np.zeros((max(NTINY, 1),), dtype=bool)
    for i, slot_id in enumerate(tiny):
        data, stream, lowered = slots.entries[slot_id]
        tiny_bytes[i, : len(data)] = np.frombuffer(data, dtype=np.uint8)
        tiny_len[i] = len(data)
        tiny_slot[i] = slot_id
        tiny_stream[i] = STREAMS.index(stream)
        tiny_lowered[i] = lowered

    # --- matcher arrays ---
    NM = max(len(matchers), 1)
    max_status = max(
        (max(len(r["status"]), len(r["size"])) for r in matchers), default=1
    ) or 1
    m_kind = np.zeros((NM,), dtype=np.int32)
    m_negative = np.zeros((NM,), dtype=bool)
    m_cond_and = np.zeros((NM,), dtype=bool)
    m_scalar = np.zeros((NM, MAX_SCALAR_CONJUNCTS, 3), dtype=np.float32)
    m_scalar[:, :, 1] = SOP_TRUE
    m_residue = np.zeros((NM,), dtype=bool)
    m_status = np.full((NM, max_status), -1, dtype=np.int32)
    m_size = np.full((NM, max_status), -1, dtype=np.int32)
    m_size_stream = np.zeros((NM,), dtype=np.int32)
    for i, rec in enumerate(matchers):
        m_kind[i] = rec["kind"]
        m_negative[i] = rec["negative"]
        m_cond_and[i] = rec["cond_and"]
        for j, (var, op, val) in enumerate(rec["scalar"][:MAX_SCALAR_CONJUNCTS]):
            m_scalar[i, j] = (var, op, val)
        m_residue[i] = rec["residue"]
        for j, s in enumerate(rec["status"]):
            m_status[i, j] = s
        for j, s in enumerate(rec["size"]):
            m_size[i, j] = s
        m_size_stream[i] = rec["size_stream"]
    m_slot_buckets = bucket_ragged([r["slots"] for r in matchers], NM)

    # --- operation / template arrays ---
    NOP = max(len(ops), 1)
    op_cond_and = np.zeros((NOP,), dtype=bool)
    for i, o in enumerate(ops):
        op_cond_and[i] = o["cond_and"]
    op_m_buckets = bucket_ragged([o["matchers"] for o in ops], NOP)
    t_op_buckets = bucket_ragged(t_ops, max(len(t_ops), 1))

    stats = {
        "templates_in": len(templates),
        "templates_device": len(kept_templates),
        "templates_host_always": len(host_always),
        "matchers": len(matchers),
        "word_slots": NW,
        "tiny_slots": NTINY,
        "tables": {
            f"{t.stream}/{'ci' if t.lowered else 'cs'}/q{t.q}": int(
                t.entry_h2.shape[0]
            )
            for t in tables
        },
    }

    return CompiledDB(
        slot_bytes=slot_bytes,
        slot_len=slot_len,
        slot_long=slot_long,
        tables=tables,
        tiny_bytes=tiny_bytes,
        tiny_len=tiny_len,
        tiny_slot=tiny_slot,
        tiny_stream=tiny_stream,
        tiny_lowered=tiny_lowered,
        m_kind=m_kind,
        m_negative=m_negative,
        m_cond_and=m_cond_and,
        m_slot_buckets=m_slot_buckets,
        m_scalar=m_scalar,
        m_residue=m_residue,
        m_status=m_status,
        m_size=m_size,
        m_size_stream=m_size_stream,
        op_cond_and=op_cond_and,
        op_m_buckets=op_m_buckets,
        t_op_buckets=t_op_buckets,
        template_ids=[t.id for t in kept_templates],
        host_always=host_always,
        templates=kept_templates,
        stats=stats,
    )
