"""nuclei matcher-DSL compiler: parse → AST → evaluate (host) / lower (device).

The corpus's 766 ``dsl`` matchers are govaluate-style expressions such as
``len(body)==2336 && status_code==200 && md5(body)=="…"``
(``technologies/favicon-detection.yaml:23-27`` in the reference corpus).
This module parses them once into a small AST that both the exact host
evaluator (here) and the device lowering (``fingerprints/compile.py``,
``lower_dsl``) consume.

AST node forms (plain tuples, trivially traversable):
  ("lit", value) · ("var", name) · ("call", fname, [args])
  ("bin", op, lhs, rhs) · ("un", op, expr)
"""

from __future__ import annotations

import base64 as _b64
import hashlib
import re
import time as _time

from swarm_tpu.fingerprints.regexlin import quiet_warnings
from typing import Any, Callable, Optional


class DslError(ValueError):
    pass


# Backslashes that do NOT start a recognized escape sequence stay
# literal ("\d" in a dsl regex string). unicode_escape currently warns
# on them and will eventually raise — pre-doubling the invalid ones
# pins today's pass-through semantics, warning-free and future-proof.
# One pass, consuming each escape atomically (a "\\-" must not have its
# second backslash re-examined as the start of an invalid "\-").
_ESC_SCAN = re.compile(
    r"\\(?:(\n|[\\'\"abfnrtv]|[0-7]{1,3}|x[0-9a-fA-F]{2}"
    r"|u[0-9a-fA-F]{4}|U[0-9a-fA-F]{8}|N\{[^}]+\})|(.)|$)",
    re.DOTALL,
)


def _unescape_literal(body: str) -> str:
    def fix(m: "re.Match[str]") -> str:
        if m.group(1) is not None:
            return m.group(0)  # recognized escape — decode below
        if m.group(2) is not None:
            return "\\\\" + m.group(2)  # invalid — backslash is literal
        return "\\\\"  # lone trailing backslash
    return _ESC_SCAN.sub(fix, body).encode().decode("unicode_escape")


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<num>\d+\.\d+|\d+)
      | (?P<str>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
      | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
      | (?P<op>\|\||&&|==|!=|<=|>=|=~|!~|<<|>>|[-+*/%()!,<>])
    )""",
    re.VERBOSE | re.DOTALL,
)


def _tokenize(text: str) -> list[tuple[str, str]]:
    tokens: list[tuple[str, str]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m or m.end() == pos:
            if text[pos:].strip() == "":
                break
            raise DslError(f"bad token at {text[pos:pos+20]!r}")
        pos = m.end()
        for kind in ("num", "str", "name", "op"):
            val = m.group(kind)
            if val is not None:
                tokens.append((kind, val))
                break
    tokens.append(("eof", ""))
    return tokens


# ---------------------------------------------------------------------------
# Pratt parser
# ---------------------------------------------------------------------------

_BINARY_PRECEDENCE = {
    "||": 1,
    "&&": 2,
    "==": 3, "!=": 3, "=~": 3, "!~": 3,
    "<": 4, ">": 4, "<=": 4, ">=": 4,
    "+": 5, "-": 5,
    "*": 6, "/": 6, "%": 6,
}


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.tokens = tokens
        self.i = 0

    def peek(self) -> tuple[str, str]:
        return self.tokens[self.i]

    def next(self) -> tuple[str, str]:
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def expect(self, value: str) -> None:
        kind, val = self.next()
        if val != value:
            raise DslError(f"expected {value!r}, got {val!r}")

    def parse_expression(self, min_prec: int = 0) -> tuple:
        left = self.parse_unary()
        while True:
            kind, val = self.peek()
            prec = _BINARY_PRECEDENCE.get(val)
            if kind != "op" or prec is None or prec < min_prec:
                return left
            self.next()
            right = self.parse_expression(prec + 1)
            left = ("bin", val, left, right)

    def parse_unary(self) -> tuple:
        kind, val = self.peek()
        if kind == "op" and val == "!":
            self.next()
            return ("un", "!", self.parse_unary())
        if kind == "op" and val == "-":
            self.next()
            return ("un", "-", self.parse_unary())
        return self.parse_primary()

    def parse_primary(self) -> tuple:
        kind, val = self.next()
        if kind == "num":
            return ("lit", float(val) if "." in val else int(val))
        if kind == "str":
            body = val[1:-1]
            if "\\" in body:
                body = _unescape_literal(body)
            return ("lit", body)
        if kind == "name":
            if val in ("true", "false"):
                return ("lit", val == "true")
            nkind, nval = self.peek()
            if nkind == "op" and nval == "(":
                self.next()
                args = []
                if self.peek() != ("op", ")"):
                    args.append(self.parse_expression())
                    while self.peek() == ("op", ","):
                        self.next()
                        args.append(self.parse_expression())
                self.expect(")")
                return ("call", val, args)
            return ("var", val)
        if kind == "op" and val == "(":
            expr = self.parse_expression()
            self.expect(")")
            return expr
        raise DslError(f"unexpected token {val!r}")


def parse_dsl(text: str) -> tuple:
    parser = _Parser(_tokenize(text))
    ast = parser.parse_expression()
    if parser.peek()[0] != "eof":
        raise DslError(f"trailing input after expression: {text!r}")
    return ast


# ---------------------------------------------------------------------------
# Host evaluator (the exact/oracle semantics)
# ---------------------------------------------------------------------------


def _to_bytes(v: Any) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode("utf-8", "surrogateescape")
    return str(v).encode()


def _text(v: Any) -> str:
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return str(v)


_REGEX_CACHE: dict[str, "re.Pattern[str]"] = {}


def compile_cached(pattern: str) -> "re.Pattern[str]":
    """Unbounded pattern→compiled cache shared by the DSL evaluator and
    the CPU oracle (the corpus outgrows re's 512-entry internal cache).

    The nested-set FutureWarning family ("possible nested set" —
    corpus patterns with literal '[[') is suppressed through
    regexlin.quiet_warnings, the lock-serialized guard (compiles also
    run from worker thread pools, where bare catch_warnings races on
    the process-global filter list)."""
    compiled = _REGEX_CACHE.get(pattern)
    if compiled is None:
        with quiet_warnings():
            compiled = _REGEX_CACHE[pattern] = re.compile(pattern)
    return compiled


def _search(pattern: str, text: str):
    return compile_cached(pattern).search(text)


_FUNCTIONS: dict[str, Callable] = {
    "len": lambda v: len(_to_bytes(v)) if isinstance(v, (bytes, str)) else len(v),
    "md5": lambda v: hashlib.md5(_to_bytes(v)).hexdigest(),
    "sha1": lambda v: hashlib.sha1(_to_bytes(v)).hexdigest(),
    "sha256": lambda v: hashlib.sha256(_to_bytes(v)).hexdigest(),
    "contains": lambda hay, needle: _to_bytes(needle) in _to_bytes(hay),
    "tolower": lambda v: _to_bytes(v).lower(),
    "toupper": lambda v: _to_bytes(v).upper(),
    "trim_space": lambda v: _to_bytes(v).strip(),
    "base64": lambda v: _b64.b64encode(_to_bytes(v)).decode(),
    "base64_decode": lambda v: _b64.b64decode(_to_bytes(v)),
    "hex_encode": lambda v: _to_bytes(v).hex(),
    "regex": lambda pattern, v: _search(_text(pattern), _text(v)) is not None,
    "mmh3": None,  # installed below (needs helper)
    # wall-clock seconds; corpus use: ssl/expired-ssl.yaml
    # ``unixtime() > not_after`` (evaluated host-side by the ssl scanner)
    "unixtime": lambda: int(_time.time()),
}


def _mmh3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3 x86 32-bit — the hash nuclei's favicon dsl uses.

    Pure-python reference; the device version lives in ops/hashes.py.
    Returns the *signed* 32-bit value (Shodan/nuclei convention).
    """
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    n = len(data)
    rounded = n - (n % 4)
    for i in range(0, rounded, 4):
        k = int.from_bytes(data[i : i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = data[rounded:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h - (1 << 32) if h >= (1 << 31) else h


_FUNCTIONS["mmh3"] = lambda v: str(_mmh3_32(_to_bytes(v)))


def _cmp_coerce(a: Any, b: Any) -> tuple[Any, Any]:
    """Make ==/</> tolerant of bytes-vs-str and str-vs-number mixes."""
    if isinstance(a, bool) or isinstance(b, bool):
        return a, b
    if isinstance(a, (bytes, str)) and isinstance(b, (bytes, str)):
        return _to_bytes(a), _to_bytes(b)
    if isinstance(a, (int, float)) and isinstance(b, (bytes, str)):
        try:
            return a, float(_text(b))
        except ValueError:
            return str(a), _text(b)
    if isinstance(b, (int, float)) and isinstance(a, (bytes, str)):
        b2, a2 = _cmp_coerce(b, a)
        return a2, b2
    return a, b


def evaluate(ast: tuple, env: dict[str, Any]) -> Any:
    kind = ast[0]
    if kind == "lit":
        return ast[1]
    if kind == "var":
        name = ast[1]
        if name not in env:
            raise DslError(f"unknown variable {name!r}")
        return env[name]
    if kind == "un":
        v = evaluate(ast[2], env)
        return (not v) if ast[1] == "!" else -v
    if kind == "call":
        fn = _FUNCTIONS.get(ast[1])
        if fn is None:
            raise DslError(f"unknown function {ast[1]!r}")
        return fn(*(evaluate(a, env) for a in ast[2]))
    if kind == "bin":
        op = ast[1]
        if op == "&&":
            return bool(evaluate(ast[2], env)) and bool(evaluate(ast[3], env))
        if op == "||":
            return bool(evaluate(ast[2], env)) or bool(evaluate(ast[3], env))
        a, b = evaluate(ast[2], env), evaluate(ast[3], env)
        if op in ("==", "!=", "<", ">", "<=", ">="):
            a, b = _cmp_coerce(a, b)
            try:
                result = {
                    "==": a == b, "!=": a != b,
                    "<": a < b, ">": a > b, "<=": a <= b, ">=": a >= b,
                }[op]
            except TypeError:
                result = False if op != "!=" else True
            return result
        if op == "=~":
            return _search(_text(b), _text(a)) is not None
        if op == "!~":
            return _search(_text(b), _text(a)) is None
        if op == "+":
            if isinstance(a, (bytes, str)) or isinstance(b, (bytes, str)):
                return _to_bytes(a) + _to_bytes(b)
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return a / b
        if op == "%":
            return a % b
    raise DslError(f"bad AST node {ast!r}")


def build_env(response) -> dict[str, Any]:
    """DSL variable environment for one :class:`Response`."""
    body = response.part("body")
    header = response.part("header")
    return {
        "body": body,
        "header": header,
        "all_headers": header,
        "raw": response.part("raw"),
        "status_code": response.status,
        "content_length": response.content_length,
        "host": response.host,
        "port": response.port,
        "duration": response.duration_s,
        # OOB interaction vars: filled by the worker's callback
        # listener (worker/oob.py); empty without one — the matchers
        # then evaluate False, same as nuclei with OOB disabled
        "interactsh_protocol": " ".join(response.oob_protocols),
        "interactsh_request": response.oob_requests,
    }


_PARSE_CACHE: dict[str, Optional[tuple]] = {}


def try_parse(text: str) -> Optional[tuple]:
    """Parse-or-None, memoized: the corpus has a few thousand distinct
    expressions but the engine's sparse confirmation path re-evaluates
    the hot ones per fired row — parsing must not dominate that."""
    try:
        return _PARSE_CACHE[text]
    except KeyError:
        try:
            ast = parse_dsl(text)
        except DslError:
            ast = None
        if len(_PARSE_CACHE) < 65536:
            _PARSE_CACHE[text] = ast
        return ast


#: Names ``build_env`` defines — the oracle's complete variable surface.
ENV_VARS = frozenset(
    {
        "body", "header", "all_headers", "raw", "status_code",
        "content_length", "host", "port", "duration",
        "interactsh_protocol", "interactsh_request",
    }
)


def always_errors(ast: tuple) -> bool:
    """True if evaluating ``ast`` raises for *every* environment —
    i.e. an unknown variable/function sits on an unconditionally
    evaluated path (&&/|| short-circuit only protects the RIGHT
    operand; comparisons/arithmetic/calls evaluate both sides).

    The oracle maps an evaluation error to "matcher unsupported" →
    verdict False with negation NOT applied (cpu_ref.match_matcher),
    so an always-erroring expression makes its whole matcher a
    compile-time constant False — the multi-step template tail
    (status_code_2, body_1, set_cookie…) lowers exactly this way.
    """
    kind = ast[0]
    if kind == "lit":
        return False
    if kind == "var":
        return ast[1] not in ENV_VARS
    if kind == "un":
        return always_errors(ast[2])
    if kind == "call":
        if ast[1] not in _FUNCTIONS:
            return True
        return any(always_errors(a) for a in ast[2])
    if kind == "bin":
        if ast[1] in ("&&", "||"):
            return always_errors(ast[2])
        return always_errors(ast[2]) or always_errors(ast[3])
    return False


def effectively_false(ast: tuple) -> bool:
    """True if every evaluation either errors or yields falsy — both of
    which make a single-expression (or AND-listed) dsl matcher False:
    an error marks the whole matcher unsupported → False, and a falsy
    value is False outright. The canonical corpus shape is
    ``status_code==200 && "…" == mmh3(base64_py(body))`` — the unknown
    function only errors when the guard passes, so ``always_errors``
    alone can't fold it, but False-or-error still holds row-wise.
    """
    if always_errors(ast):
        return True
    kind = ast[0]
    if kind == "bin":
        if ast[1] == "&&":
            return effectively_false(ast[2]) or effectively_false(ast[3])
        if ast[1] == "||":
            return effectively_false(ast[2]) and effectively_false(ast[3])
    return False
