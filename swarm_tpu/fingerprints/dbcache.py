"""Corpus-compile cache: parsed templates + CompiledDB on disk.

Compiling the full reference corpus (3,989 YAML templates → device
tensors) costs ~8-10 s of pure Python per process. Together with the
persistent XLA cache (utils/xlacache.py) this makes a warm worker's
engine construction near-instant: both halves of startup — corpus
lowering and kernel compilation — are paid once per (corpus, compiler
version) and reused across restarts and fleet clones.

The cache key covers the corpus contents (every template file's path,
size, mtime) AND the compiler's own source bytes, so editing either the
templates or the lowering code invalidates cleanly. Entries are pickles
written atomically under ``~/.cache/swarm_tpu/db`` (override:
``SWARM_DB_CACHE_DIR``; empty disables). Only this framework writes the
cache dir — entries are trusted local artifacts, same trust level as
the XLA cache next to it.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
from pathlib import Path
from typing import Optional

DEFAULT_CACHE_DIR = "~/.cache/swarm_tpu/db"
_FORMAT_VERSION = 1

# compiler source files whose bytes salt the key: a lowering change must
# never serve stale compiled DBs. compile.py bakes tables from
# ops/hashing.py (gram hashes, blooms) and ops/encoding.py (stream
# layout) into the CompiledDB, so those salt the key too.
_CODE_FILES = (
    "compile.py",
    "nuclei.py",
    "model.py",
    "regexlin.py",
    "dslc.py",
    "../ops/hashing.py",
    "../ops/encoding.py",
    # the service classifier's template construction (_inline_flags,
    # Matcher wiring) feeds the svcdb entries — a lowering change there
    # must invalidate them too
    "../ops/service.py",
)


def _code_salt() -> bytes:
    h = hashlib.sha256()
    here = Path(__file__).resolve().parent
    for name in _CODE_FILES:
        try:
            h.update(name.encode())
            h.update((here / name).read_bytes())
        except OSError:
            h.update(b"?")
    return h.digest()


def _corpus_material(templates_dir: str | Path) -> bytes:
    """The corpus tree's identity bytes (path, size, mtime per file)."""
    root = Path(templates_dir)
    entries = sorted(
        p for p in root.rglob("*")
        if p.is_file() and p.suffix in (".yaml", ".yml", ".txt")
    )
    lines = []
    for p in entries:
        st = p.stat()
        lines.append(
            f"{p.relative_to(root)}|{st.st_size}|{st.st_mtime_ns}\n"
        )
    return "".join(lines).encode()


def _entry_key(key_material: bytes) -> str:
    h = hashlib.sha256()
    h.update(b"v%d|" % _FORMAT_VERSION)
    h.update(_code_salt())
    h.update(key_material)
    return h.hexdigest()


def corpus_key(templates_dir: str | Path) -> str:
    """Stable key over the corpus tree + compiler version."""
    return _entry_key(_corpus_material(templates_dir))


def _cache_dir() -> Optional[Path]:
    raw = os.environ.get("SWARM_DB_CACHE_DIR", DEFAULT_CACHE_DIR)
    if not raw:
        return None
    path = Path(raw).expanduser()
    try:
        path.mkdir(parents=True, exist_ok=True)
    except OSError:
        return None
    return path


def load_or_compile_keyed(tag: str, key_material: bytes, build):
    """Generic cached compile: ``tag`` groups entries (stale siblings
    under the SAME tag are evicted on publish — derive it from the
    artifact's identity, e.g. its path hash, so distinct DBs coexist),
    ``key_material`` + the compiler-source salt key them, ``build()``
    produces the picklable value. Used by load_or_compile and by the
    service classifier to bound the 12k-signature DB compile (~18 s
    cold) to one pickle load warm."""
    cache = _cache_dir()
    if cache is None:
        return build()
    key = _entry_key(key_material)
    entry = cache / f"{tag}-{key}.pkl"
    if entry.is_file():
        try:
            with open(entry, "rb") as fh:
                return pickle.load(fh)
        except Exception:
            try:
                entry.unlink()
            except OSError:
                pass
    value = build()
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(dir=cache, suffix=".tmp")
        with os.fdopen(fd, "wb") as fh:
            pickle.dump(value, fh, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, entry)
        tmp = None
        for stale in cache.glob(f"{tag}-*.pkl"):
            if stale.name != entry.name:
                stale.unlink(missing_ok=True)
    except Exception:
        pass
    finally:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return value


def path_tag(path: str | Path) -> str:
    """Entry-group tag from an artifact's resolved path: groups the
    cache entries per location so publishing a new key evicts the
    stale siblings (the mtime-sensitive key would otherwise mint an
    immortal multi-MB pickle per checkout/touch), while distinct
    locations coexist."""
    return hashlib.sha256(
        str(Path(path).resolve()).encode()
    ).hexdigest()[:16]


def load_or_compile(templates_dir: str | Path):
    """→ (templates, CompiledDB), served from the disk cache when the
    corpus+compiler key matches; compiled (and cached) otherwise."""
    from swarm_tpu.fingerprints import load_corpus
    from swarm_tpu.fingerprints.compile import compile_corpus

    def build():
        templates, _errors = load_corpus(templates_dir)
        return templates, compile_corpus(templates)

    return load_or_compile_keyed(
        path_tag(templates_dir), _corpus_material(templates_dir), build
    )
