"""Workflow templates + wappalyzer tech→tags mapping.

The reference corpus chains templates conditionally (``workflows/*``,
e.g. ``workflows/74cms-workflow.yaml:8-13`` in `/root/reference/worker/
artifacts/templates/`): run a fingerprint template, and when it (or one
of its *named matchers*) fires, run the subtemplates selected by tag or
path. ``wappalyzer-mapping.yml`` additionally maps detected technology
names to template tags for nuclei's automatic-scan mode.

TPU-first execution model: the whole corpus is matched in ONE batched
device pass (``ops/engine.MatchEngine``); workflows then become pure
post-processing — trigger hits gate which subtemplate hits are
*reported*. Match verdicts are identical to running subtemplates
conditionally; only the request-side effect differs (we matched an
already-captured response batch, so there is nothing to skip).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Optional, Sequence

from swarm_tpu.fingerprints.model import Template


@dataclasses.dataclass
class SubtemplateRef:
    """Selects templates by tag set OR by corpus-relative path."""

    tags: list[str] = dataclasses.field(default_factory=list)
    template: Optional[str] = None
    # nested chaining: these refs apply only when the parent fired
    matchers: list["MatcherGate"] = dataclasses.field(default_factory=list)
    subtemplates: list["SubtemplateRef"] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class MatcherGate:
    """Gate on a *named matcher* of the trigger template having fired."""

    name: str
    subtemplates: list[SubtemplateRef] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class WorkflowStep:
    template: Optional[str] = None  # corpus-relative path of the trigger
    tags: list[str] = dataclasses.field(default_factory=list)  # tag-triggered
    matchers: list[MatcherGate] = dataclasses.field(default_factory=list)
    subtemplates: list[SubtemplateRef] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Workflow:
    id: str
    steps: list[WorkflowStep] = dataclasses.field(default_factory=list)
    source_path: Optional[str] = None


def _parse_tags(raw) -> list[str]:
    if raw is None:
        return []
    if isinstance(raw, str):
        return [t.strip() for t in raw.split(",") if t.strip()]
    return [str(t).strip() for t in raw]


def _parse_ref(raw: dict) -> SubtemplateRef:
    return SubtemplateRef(
        tags=_parse_tags(raw.get("tags")),
        template=raw.get("template"),
        matchers=[_parse_gate(m) for m in raw.get("matchers") or []],
        subtemplates=[_parse_ref(s) for s in raw.get("subtemplates") or []],
    )


def _parse_gate(raw: dict) -> MatcherGate:
    return MatcherGate(
        name=str(raw.get("name", "")),
        subtemplates=[_parse_ref(s) for s in raw.get("subtemplates") or []],
    )


def parse_workflow(template: Template) -> Workflow:
    """Lift a protocol='workflow' Template's raw block into the model."""
    steps = []
    for raw in template.extra.get("workflows") or []:
        if not isinstance(raw, dict):
            continue
        steps.append(
            WorkflowStep(
                template=raw.get("template"),
                tags=_parse_tags(raw.get("tags")),
                matchers=[_parse_gate(m) for m in raw.get("matchers") or []],
                subtemplates=[_parse_ref(s) for s in raw.get("subtemplates") or []],
            )
        )
    return Workflow(id=template.id, steps=steps, source_path=template.source_path)


# ---------------------------------------------------------------------------
# wappalyzer-mapping.yml — tech name → template tags
# ---------------------------------------------------------------------------


def parse_wappalyzer_mapping(text: str) -> dict[str, list[str]]:
    """The mapping file is intentionally trivial YAML (``tech: tags``
    lines — `wappalyzer-mapping.yml:5-6` in the reference corpus); a
    hand parser avoids depending on comment-preserving YAML quirks."""
    out: dict[str, list[str]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, sep, value = line.partition(":")
        if not sep:
            continue
        tags = _parse_tags(value)
        if key.strip() and tags:
            out[key.strip().lower()] = tags
    return out


def load_wappalyzer_mapping(path: str | Path) -> dict[str, list[str]]:
    return parse_wappalyzer_mapping(Path(path).read_text())


# ---------------------------------------------------------------------------
# Template index for ref resolution
# ---------------------------------------------------------------------------


class TemplateIndex:
    """Resolve SubtemplateRefs against a loaded corpus: by tag, and by
    corpus-relative path suffix (workflow refs are written relative to
    the corpus root)."""

    def __init__(self, templates: Sequence[Template]):
        self.by_tag: dict[str, list[Template]] = {}
        self._paths: list[tuple[str, Template]] = []
        for t in templates:
            for tag in t.tags:
                self.by_tag.setdefault(tag.lower(), []).append(t)
            if t.source_path:
                self._paths.append((str(t.source_path).replace("\\", "/"), t))
        # refs are row-invariant: memoize so per-row workflow evaluation
        # never rescans the corpus path list
        self._by_path_cache: dict[str, Optional[Template]] = {}

    def by_path(self, ref: str) -> Optional[Template]:
        if ref in self._by_path_cache:
            return self._by_path_cache[ref]
        norm = ref.replace("\\", "/").lstrip("/")
        found = None
        for path, t in self._paths:
            if path.endswith("/" + norm) or path == norm:
                found = t
                break
        self._by_path_cache[ref] = found
        return found

    def resolve(self, ref: SubtemplateRef) -> list[Template]:
        out: list[Template] = []
        if ref.template:
            t = self.by_path(ref.template)
            if t:
                out.append(t)
        for tag in ref.tags:
            out.extend(self.by_tag.get(tag.lower(), []))
        return out
