"""nuclei-template YAML → :mod:`swarm_tpu.fingerprints.model`.

Covers the template surface measured in the reference corpus
(``/root/reference/worker/artifacts/templates``, SURVEY.md §2.3):
``requests`` (http), ``network``, ``dns``, ``file``, ``ssl``,
``headless`` blocks; word/regex/status/size/binary/dsl/kval/json/xpath
matchers with parts, and/or conditions, negation, case-insensitivity,
named matchers; regex/kval extractors; ``workflows`` templates are
loaded with their raw chain kept in ``Template.extra``.
"""

from __future__ import annotations

import binascii
from pathlib import Path
from typing import Any, Iterable, Optional

import yaml

from swarm_tpu.fingerprints.model import (
    Extractor,
    Matcher,
    Operation,
    Template,
)


class TemplateParseError(ValueError):
    pass


def _as_list(value: Any) -> list:
    if value is None:
        return []
    if isinstance(value, (list, tuple)):
        return list(value)
    return [value]


def _parse_matcher(raw: dict) -> Matcher:
    mtype = raw.get("type")
    if mtype not in (
        "word", "regex", "status", "size", "binary", "dsl", "kval", "json", "xpath",
    ):
        raise TemplateParseError(f"unknown matcher type: {mtype!r}")
    m = Matcher(
        type=mtype,
        part=str(raw.get("part", "body")),
        words=[str(w) for w in _as_list(raw.get("words"))],
        regex=[str(r) for r in _as_list(raw.get("regex"))],
        status=[int(s) for s in _as_list(raw.get("status"))],
        size=[int(s) for s in _as_list(raw.get("size"))],
        binary=[str(b) for b in _as_list(raw.get("binary"))],
        dsl=[str(d) for d in _as_list(raw.get("dsl"))],
        kval=[str(k) for k in _as_list(raw.get("kval"))],
        condition=str(raw.get("condition", "or")),
        negative=bool(raw.get("negative", False)),
        case_insensitive=bool(raw.get("case-insensitive", False)),
        name=raw.get("name"),
    )
    return m


def _parse_extractor(raw: dict) -> Extractor:
    return Extractor(
        type=str(raw.get("type", "regex")),
        part=str(raw.get("part", "body")),
        name=raw.get("name"),
        regex=[str(r) for r in _as_list(raw.get("regex"))],
        kval=[str(k) for k in _as_list(raw.get("kval"))],
        json=[str(j) for j in _as_list(raw.get("json"))],
        xpath=[str(x) for x in _as_list(raw.get("xpath"))],
        attribute=raw.get("attribute"),
        group=int(raw.get("group", 0)),
        internal=bool(raw.get("internal", False)),
    )


def _network_input_bytes(entry: dict) -> Optional[bytes]:
    data = entry.get("data")
    if data is None:
        return None
    text = str(data)
    if entry.get("type") == "hex":
        try:
            return binascii.unhexlify(text.strip())
        except (binascii.Error, ValueError):
            return text.encode("utf-8", "surrogateescape")
    return text.encode("utf-8", "surrogateescape")


def _parse_operation(raw: dict, protocol: str) -> Operation:
    op = Operation(
        matchers=[_parse_matcher(m) for m in _as_list(raw.get("matchers"))],
        matchers_condition=str(raw.get("matchers-condition", "or")),
        extractors=[_parse_extractor(e) for e in _as_list(raw.get("extractors"))],
        method=raw.get("method"),
        paths=[str(p) for p in _as_list(raw.get("path"))],
        raw=[str(r) for r in _as_list(raw.get("raw"))],
        headers=(
            [(str(k), str(v)) for k, v in raw["headers"].items()]
            if isinstance(raw.get("headers"), dict)
            else []
        ),
        body=str(raw.get("body") or ""),
        payloads=raw.get("payloads") or {},
        attack=str(raw.get("attack") or "batteringram"),
        hosts=[str(h) for h in _as_list(raw.get("host"))],
        redirects=bool(raw.get("redirects", False)),
        max_redirects=int(raw.get("max-redirects", 0)),
    )
    if protocol == "ssl":
        # (the corpus's ``address`` field is always the default
        # "{{Host}}:{{Port}}" — the scanner dials the input target)
        op.ssl_min_version = str(raw.get("min_version") or "").lower()
        op.ssl_max_version = str(raw.get("max_version") or "").lower()
    if protocol == "file":
        op.extensions = [
            str(e).lower().lstrip(".") for e in _as_list(raw.get("extensions"))
        ]
    if protocol == "dns":
        op.dns_type = str(raw.get("type") or "A").upper()
        op.dns_name = str(raw.get("name") or "{{FQDN}}")
    if protocol == "headless":
        op.steps = [s for s in _as_list(raw.get("steps")) if isinstance(s, dict)]
    if protocol == "network":
        for entry in _as_list(raw.get("inputs")):
            if isinstance(entry, dict):
                data = _network_input_bytes(entry)
                if data is not None:
                    op.inputs.append(data)
                if entry.get("read"):
                    op.read_size = int(entry["read"])
        if raw.get("read-size"):
            op.read_size = int(raw["read-size"])
    return op


_PROTOCOL_KEYS = (
    ("requests", "http"),
    ("http", "http"),
    ("network", "network"),
    ("tcp", "network"),
    ("dns", "dns"),
    ("file", "file"),
    ("ssl", "ssl"),
    ("headless", "headless"),
    ("workflows", "workflow"),
)


def parse_template(
    doc: dict, source_path: Optional[str] = None
) -> Template:
    if not isinstance(doc, dict) or "id" not in doc:
        raise TemplateParseError(f"not a template document: {source_path}")
    info = doc.get("info") or {}
    tags = info.get("tags", "")
    if isinstance(tags, str):
        tags = [t.strip() for t in tags.split(",") if t.strip()]

    protocol = None
    operations: list[Operation] = []
    extra: dict[str, Any] = {}
    for key, proto in _PROTOCOL_KEYS:
        block = doc.get(key)
        if not block:
            continue
        protocol = protocol or proto
        if proto == "workflow":
            extra["workflows"] = block
            continue
        for entry in _as_list(block):
            if isinstance(entry, dict):
                operations.append(_parse_operation(entry, proto))
    if protocol is None:
        raise TemplateParseError(f"template {doc.get('id')!r} has no protocol block")

    return Template(
        id=str(doc["id"]),
        protocol=protocol,
        severity=str(info.get("severity", "info")),
        name=info.get("name"),
        tags=tags,
        operations=operations,
        source_path=source_path,
        extra=extra,
    )


def load_template_file(path: str | Path) -> Template:
    p = Path(path)
    doc = yaml.safe_load(p.read_text(encoding="utf-8", errors="replace"))
    return parse_template(doc, source_path=str(p))


def load_corpus(
    root: str | Path,
    protocols: Optional[set[str]] = None,
    limit: Optional[int] = None,
    strict: bool = False,
) -> tuple[list[Template], list[tuple[str, str]]]:
    """Load every ``*.yaml`` template under ``root``.

    Returns ``(templates, errors)`` where errors is a list of
    ``(path, message)`` for files that failed to parse (the reference
    corpus has a handful of helper YAMLs that are not templates).
    """
    root = Path(root)
    templates: list[Template] = []
    errors: list[tuple[str, str]] = []
    paths: Iterable[Path] = sorted(root.rglob("*.yaml"))
    for p in paths:
        if limit is not None and len(templates) >= limit:
            break
        # Skip corpus helper data (wordlists/payloads), not templates.
        rel = p.relative_to(root).as_posix()
        if rel.startswith("helpers/"):
            continue
        try:
            t = load_template_file(p)
        except TemplateParseError as e:
            errors.append((str(p), str(e)))
            continue
        except yaml.YAMLError as e:
            errors.append((str(p), f"yaml: {e}"))
            continue
        except Exception as e:  # corrupt file in a 4k-file corpus: record, move on
            if strict:
                raise
            errors.append((str(p), f"{type(e).__name__}: {e}"))
            continue
        if protocols is None or t.protocol in protocols:
            templates.append(t)
    return templates, errors
