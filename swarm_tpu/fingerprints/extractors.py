"""Host-side extractor evaluation: kval, json (jq-lite), xpath.

The corpus uses four extractor types (measured: regex 581, kval 44,
json 16, xpath 7 — SURVEY.md §2.3); regex lives in ops/cpu_ref.py next
to the matcher loop, the structured three live here:

- ``kval``: response-header value by key, dashes normalized to
  underscores (same normalization the kval *matcher* uses).
- ``json``: jq-style dotted paths (``.a.b[0].c``) over the decoded
  body — the corpus only uses simple paths (``.baseUrl``,
  ``.gitVersion``), so this evaluates the dotted/indexed subset and
  emits scalars as text, composites as compact JSON.
- ``xpath``: absolute element paths with 1-based positional predicates
  (``/html/body/div[1]/form/input[2]``) against a lenient HTML parse;
  ``attribute:`` selects an attribute value, otherwise element text.
  All seven corpus uses are ``attribute: value`` form-input grabs.
"""

from __future__ import annotations

import json as jsonlib
import re
from html.parser import HTMLParser
from typing import Any, Optional
from xml.etree import ElementTree as ET

from swarm_tpu.fingerprints.model import Extractor, Response

# ---------------------------------------------------------------------------
# kval


def parse_header_blob(header_blob: bytes) -> dict[str, str]:
    """Header normalization shared by the kval matcher and extractor:
    keys lowered with dashes → underscores, last value wins."""
    headers: dict[str, str] = {}
    for line in header_blob.split(b"\r\n"):
        if b":" in line:
            k, _, v = line.partition(b":")
            key = k.strip().decode("latin-1").lower().replace("-", "_")
            headers[key] = v.strip().decode("latin-1")
    return headers


def headers_of(response: Response) -> dict[str, str]:
    return parse_header_blob(response.part("header"))


def extract_kval(ex: Extractor, response: Response) -> list[str]:
    headers = headers_of(response)
    out = []
    for key in ex.kval:
        norm = key.lower().replace("-", "_")
        if norm == "interactsh_ip":
            # OOB pseudo-kval: "print the remote interaction IP"
            # (vulnerabilities/other/*-log4j-rce.yaml extractors)
            out.extend(response.oob_ips)
            continue
        val = headers.get(norm)
        if val is not None:
            out.append(val)
    return out


# ---------------------------------------------------------------------------
# json (jq-lite)

_SEG_RE = re.compile(r"\.([A-Za-z0-9_\-$]+)|\[(\d+)?\]")


def jq_path(expr: str, doc: Any) -> Optional[Any]:
    """Evaluate a dotted/indexed jq path; None when it doesn't resolve."""
    expr = expr.strip()
    if not expr.startswith("."):
        return None
    pos = 0
    node = doc
    while pos < len(expr):
        m = _SEG_RE.match(expr, pos)
        if m is None:
            return None  # unsupported jq syntax (pipes, functions, …)
        pos = m.end()
        if m.group(1) is not None:
            if not isinstance(node, dict) or m.group(1) not in node:
                return None
            node = node[m.group(1)]
        elif m.group(2) is not None:
            idx = int(m.group(2))
            if not isinstance(node, list) or idx >= len(node):
                return None
            node = node[idx]
        else:
            # ``[]`` — jq iterate-all; supported in trailing position
            # (corpus use: ssl templates' ``.dns_names[]``). The list
            # itself is returned; extract_json flattens it per element.
            if not isinstance(node, list) or pos < len(expr):
                return None
    return node


def extract_json(ex: Extractor, response: Response) -> list[str]:
    try:
        doc = jsonlib.loads(response.part(ex.part).decode("utf-8", "replace"))
    except ValueError:
        return []
    out = []
    for expr in ex.json:
        val = jq_path(expr, doc)
        if val is None:
            continue
        if isinstance(val, list) and expr.rstrip().endswith("[]"):
            # iterate-all path: one output per element (jq streaming)
            out.extend(
                v if isinstance(v, str)
                else jsonlib.dumps(v, separators=(",", ":"))
                for v in val
            )
        elif isinstance(val, str):
            out.append(val)
        else:
            out.append(jsonlib.dumps(val, separators=(",", ":")))
    return out


# ---------------------------------------------------------------------------
# xpath over lenient HTML

_VOID = frozenset(
    "area base br col embed hr img input link meta param source track wbr".split()
)


class _TreeBuilder(HTMLParser):
    """Tolerant HTML → ElementTree: unclosed tags close at parent close."""

    def __init__(self) -> None:
        super().__init__(convert_charrefs=True)
        self.root = ET.Element("__doc__")
        self.stack = [self.root]

    def handle_starttag(self, tag: str, attrs) -> None:
        el = ET.SubElement(self.stack[-1], tag, {k: (v or "") for k, v in attrs})
        if tag not in _VOID:
            self.stack.append(el)

    def handle_startendtag(self, tag: str, attrs) -> None:
        ET.SubElement(self.stack[-1], tag, {k: (v or "") for k, v in attrs})

    def handle_endtag(self, tag: str) -> None:
        for i in range(len(self.stack) - 1, 0, -1):
            if self.stack[i].tag == tag:
                del self.stack[i:]
                return
        # stray close tag: ignore

    def handle_data(self, data: str) -> None:
        el = self.stack[-1]
        if len(el):
            last = el[-1]
            last.tail = (last.tail or "") + data
        else:
            el.text = (el.text or "") + data


def parse_html(text: str) -> Optional[ET.Element]:
    try:
        builder = _TreeBuilder()
        builder.feed(text)
        builder.close()
        return builder.root
    except Exception:
        return None


_XSEG_RE = re.compile(r"^([A-Za-z0-9_\-:*]+)(?:\[(\d+)\])?$")


def xpath_nodes(root: ET.Element, path: str) -> list[ET.Element]:
    """Absolute-path subset: /tag[i]/tag/... (1-based predicate)."""
    segs = [s for s in path.strip().split("/") if s]
    nodes = [root]
    for seg in segs:
        m = _XSEG_RE.match(seg)
        if m is None:
            return []
        tag, idx = m.group(1), m.group(2)
        nxt: list[ET.Element] = []
        for node in nodes:
            kids = [c for c in node if tag in ("*", c.tag)]
            if idx is not None:
                i = int(idx) - 1
                if 0 <= i < len(kids):
                    nxt.append(kids[i])
            else:
                nxt.extend(kids)
        nodes = nxt
        if not nodes:
            return []
    return nodes


def extract_xpath(ex: Extractor, response: Response) -> list[str]:
    root = parse_html(response.part(ex.part).decode("utf-8", "replace"))
    if root is None:
        return []
    out = []
    for path in ex.xpath:
        for node in xpath_nodes(root, path):
            if ex.attribute:
                val = node.get(ex.attribute)
                if val is not None:
                    out.append(val)
            else:
                out.append("".join(node.itertext()))
    return out


def extract_structured(ex: Extractor, response: Response) -> list[str]:
    """Dispatch for the non-regex extractor types ([] for unknown)."""
    if ex.type == "kval":
        return extract_kval(ex, response)
    if ex.type == "json":
        return extract_json(ex, response)
    if ex.type == "xpath":
        return extract_xpath(ex, response)
    return []
