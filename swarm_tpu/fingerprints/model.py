"""Parsed fingerprint-template model and the response record it matches.

This is the framework-neutral form between the YAML corpus and the two
match engines (exact CPU oracle in ``ops/cpu_ref.py``, tensor DB in
``fingerprints/compile.py``). The matcher DSL surface mirrors what the
reference corpus actually uses (SURVEY.md §2.3: word 6,895 / status
2,558 / regex 1,779 / dsl 766 / kval 44 / json 23 / xpath 7 / binary 6;
parts body/header/interactsh_protocol; and/or conditions; negative and
named matchers; regex/kval extractors).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

# Matcher types understood by the engines. kval/json/xpath (74 uses in
# the corpus) are parsed but marked unsupported-on-device; they evaluate
# on the host path only.
MATCHER_TYPES = (
    "word",
    "regex",
    "status",
    "size",
    "binary",
    "dsl",
    "kval",
    "json",
    "xpath",
)

# Response parts a matcher can address. "all" = header + body. For raw
# TCP (network templates) body/raw/all alias the banner bytes.
PARTS = ("body", "header", "all", "raw", "interactsh_protocol", "host")


@dataclasses.dataclass
class Matcher:
    type: str
    part: str = "body"
    words: list[str] = dataclasses.field(default_factory=list)
    regex: list[str] = dataclasses.field(default_factory=list)
    status: list[int] = dataclasses.field(default_factory=list)
    size: list[int] = dataclasses.field(default_factory=list)
    binary: list[str] = dataclasses.field(default_factory=list)  # hex strings
    dsl: list[str] = dataclasses.field(default_factory=list)
    kval: list[str] = dataclasses.field(default_factory=list)
    condition: str = "or"  # across this matcher's words/regexes/...
    negative: bool = False
    case_insensitive: bool = False
    name: Optional[str] = None

    def payload_count(self) -> int:
        return len(
            self.words or self.regex or self.status or self.size or self.binary
            or self.dsl or self.kval
        )


@dataclasses.dataclass
class Extractor:
    type: str  # regex | kval | json | xpath | dsl
    part: str = "body"
    name: Optional[str] = None
    regex: list[str] = dataclasses.field(default_factory=list)
    kval: list[str] = dataclasses.field(default_factory=list)
    json: list[str] = dataclasses.field(default_factory=list)  # jq-style paths
    xpath: list[str] = dataclasses.field(default_factory=list)
    attribute: Optional[str] = None  # xpath: extract this attr, else text
    group: int = 0
    internal: bool = False


@dataclasses.dataclass
class Operation:
    """One request/probe block inside a template.

    For http templates this is one ``requests`` entry (method + paths or
    raw requests); for network templates one ``network`` entry (inputs +
    hosts). The probe half is metadata consumed by the I/O front-end;
    the matcher half is what the match engines evaluate against the
    response.
    """

    matchers: list[Matcher] = dataclasses.field(default_factory=list)
    matchers_condition: str = "or"
    extractors: list[Extractor] = dataclasses.field(default_factory=list)
    # --- probe metadata ---
    method: Optional[str] = None
    paths: list[str] = dataclasses.field(default_factory=list)
    raw: list[str] = dataclasses.field(default_factory=list)
    headers: list[tuple[str, str]] = dataclasses.field(default_factory=list)
    body: str = ""
    payloads: dict = dataclasses.field(default_factory=dict)  # fuzz lists
    attack: str = "batteringram"  # payload combination mode
    inputs: list[bytes] = dataclasses.field(default_factory=list)  # network send
    hosts: list[str] = dataclasses.field(default_factory=list)
    read_size: Optional[int] = None
    redirects: bool = False
    max_redirects: int = 0
    # dns protocol: record type + query-name template ("{{FQDN}}")
    dns_type: str = ""
    dns_name: str = ""
    # file protocol: extension gate (lowercased, no dot; "all" = any).
    # Reference corpus: worker/artifacts/templates/file/**.yaml and the
    # standalone worker/artifacts/s3-bucket.yaml:7-10.
    extensions: list[str] = dataclasses.field(default_factory=list)
    # ssl protocol: handshake version pin (nuclei names: sslv3, tls10,
    # tls11, tls12, tls13; "" = negotiate freely). Reference corpus:
    # worker/artifacts/templates/ssl/deprecated-tls.yaml pins per entry.
    ssl_min_version: str = ""
    ssl_max_version: str = ""
    # headless protocol: the raw browser action list (dicts with
    # "action"/"args"/"name"), e.g. reference corpus
    # worker/artifacts/templates/headless/*.yaml. Executed by
    # worker/headless.py's browserless subset.
    steps: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Template:
    id: str
    protocol: str  # http | network | dns | file | headless | ssl | workflow
    severity: str = "info"
    name: Optional[str] = None
    tags: list[str] = dataclasses.field(default_factory=list)
    operations: list[Operation] = dataclasses.field(default_factory=list)
    source_path: Optional[str] = None
    # Raw parsed YAML for fields the model doesn't lift (workflows etc.)
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)

    def all_matchers(self) -> list[tuple[int, Matcher]]:
        out = []
        for op_idx, op in enumerate(self.operations):
            out.extend((op_idx, m) for m in op.matchers)
        return out


@dataclasses.dataclass
class Response:
    """One observed (host, port) response row — the unit the engines match.

    The TPU path batches these into fixed-shape padded arrays
    (``ops/encoding.py``); the CPU oracle consumes them directly.
    """

    host: str = ""
    port: int = 0
    status: int = 0
    body: bytes = b""
    header: bytes = b""
    duration_s: float = 0.0
    # For raw TCP banners, set banner and leave body/header empty.
    banner: Optional[bytes] = None
    # Whether the probe ran over TLS; None = unknown (port heuristic
    # applies when rendering URLs).
    tls: Optional[bool] = None
    # False = the probe never got a response (unresolvable/unreachable).
    # Dead rows are never matched — nuclei produces no output for failed
    # requests, and negative matchers must not fire on an empty phantom
    # response.
    alive: bool = True
    # Out-of-band interactions correlated to this row's request (filled
    # by worker/oob.py's callback listener after the poll window).
    # ``interactsh_protocol``/``interactsh_request`` matcher parts read
    # these; empty = no interaction observed (matchers stay False, the
    # no-OOB-configured behavior).
    oob_protocols: tuple = ()  # e.g. ("http",), ("dns", "http")
    oob_requests: bytes = b""  # raw callback requests, concatenated
    oob_ips: tuple = ()  # remote addresses (interactsh_ip extractor)

    def part(self, name: str) -> bytes:
        # Canonical part aliasing — MUST stay in lockstep with
        # encoding.PART_TO_STREAM (which is derived from this table) so the
        # oracle and the device agree on what every part name means.
        if self.banner is not None and name in (
            "body", "raw", "all", "data", "response", "body_1", "body_2",
        ):
            return self.banner
        if name in ("body", "data", "body_1", "body_2"):
            return self.body
        if name in ("header", "all_headers"):
            return self.header
        if name in ("all", "raw", "response"):
            return self.header + b"\r\n" + self.body if self.header else self.body
        if name == "host":
            return self.host.encode()
        if name == "interactsh_protocol":
            return " ".join(self.oob_protocols).encode()
        if name == "interactsh_request":
            return self.oob_requests
        return b""

    @property
    def content_length(self) -> int:
        return len(self.body if self.banner is None else self.banner)
