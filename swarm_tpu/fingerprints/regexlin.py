"""Regex → linear class-sequence programs for the device verify kernel.

The corpus's 1,180 distinct matcher regexes are overwhelmingly "version
sniffer" shaped: byte classes, fixed repeats, small alternations, an
occasional ``+``/``*``. Those compile to a **linear pattern**: a
sequence of ≤64 positions, each a 256-bit byte-class with a repeat kind
(one / optional / self-loop), executed by bit-parallel shift-and
(Baeza-Yates/Gonnet; extended per Navarro–Raffinot for classes and
gaps) — exactly the compiler-friendly, branch-free inner loop the TPU
wants. Alternations expand to several linear patterns (OR of results),
capped.

Semantics target: Python ``re.search`` over the latin-1 decode of the
stream — the oracle's exact semantics (ops/cpu_ref.py). Every compiled
pattern is therefore *exactly* verifiable on device; patterns that
don't fit (lookarounds, backrefs, >64 positions, huge expansions)
return None and keep the host-confirm path.

Execution recurrence, per byte c over state bits D (bit i = "some
match prefix ends at position i"):

    D = (((D << 1) | SEED) & B[c]) | (D & SL[c])
    repeat r times:  D |= (D << 1) & SKIP          (epsilon closure)
    matched |= (D & ACCEPT) != 0

with B[c] position-classes, SL[c] self-loop classes, SEED the start
epsilon-closure, SKIP the skippable positions, r the longest skippable
run, ACCEPT the accepting positions (final position plus any position
from which the tail is all-skippable).
"""

from __future__ import annotations

import dataclasses
import re
import threading
import warnings
from contextlib import contextmanager
from typing import Optional

import numpy as np

try:  # py3.11+
    import re._parser as sre_parse
    import re._constants as sre_c
except ImportError:  # pragma: no cover
    import sre_parse  # type: ignore
    import sre_constants as sre_c  # type: ignore


# swarmlint-exempt: _WARN_LOCK serializes the PROCESS-GLOBAL warnings
# filter save/mutate/restore window (see quiet_warnings below) — there
# is no module attribute to guard
_WARN_LOCK = threading.Lock()


@contextmanager
def quiet_warnings(category=FutureWarning):
    """Thread-correct narrow warning suppression.

    ``catch_warnings`` saves/restores the PROCESS-GLOBAL filter list;
    unsynchronized enter/exit from worker thread pools can interleave
    so a temporary ignore-filter is restored as the permanent state
    (or a concurrent compile warns nondeterministically). The shared
    lock serializes the save/mutate/restore window. A module-import
    ``filterwarnings`` is no alternative: pytest wraps every test in
    its own catch_warnings that resets to configured filters, which
    would resurface the noise the suite must stay free of."""
    with _WARN_LOCK:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", category)
            yield


def parse_quiet(pattern: str):
    """``sre_parse.parse`` with the nested-set FutureWarning silenced.

    Corpus patterns contain literal ``[[`` (e.g. ``[[:alpha:]]`` POSIX
    classes written for PCRE engines); their *current* Python-re
    semantics are exactly what every lowering here must reproduce, and
    the warning re-fires on each corpus compile otherwise. Shared by
    all sre-tree walks (regexlin, fastre, compile)."""
    with quiet_warnings():
        return sre_parse.parse(pattern)

MAX_POSITIONS = 96  # 3 uint32 state lanes
MAX_SEQUENCES = 48  # branch-expansion cap per pattern
MAX_SKIP_RUN = 8  # longest consecutive-optional run we unroll

K_ONE, K_OPT, K_LOOP, K_OPTLOOP = 0, 1, 2, 3  # X, X?, X+ (loop), X*

# end-of-match anchor modes
END_NONE, END_Z, END_DOLLAR = 0, 1, 2

_WORD_BYTES = np.array(
    [re.match(r"\w", chr(b)) is not None for b in range(256)], dtype=bool
)


@dataclasses.dataclass
class LinearPattern:
    """One branch-free alternative of a compiled regex."""

    classes: np.ndarray  # uint32 [m, 8] — bit b of word b>>5: byte in class
    kinds: np.ndarray  # int8 [m] — K_* repeat kind
    max_skip_run: int
    unbounded: bool  # any self-loop ⇒ match length unbounded
    anchored: bool = False  # \A/^ — match must start at byte 0
    end_mode: int = END_NONE  # \Z / $ — match must end at stream end
    start_wb: bool = False  # leading \b
    end_wb: bool = False  # trailing \b

    @property
    def m(self) -> int:
        return int(self.classes.shape[0])

    @property
    def max_len(self) -> Optional[int]:
        return None if self.unbounded else self.m


# --- byte-class construction -----------------------------------------------

_CATEGORY_BYTES: dict = {}


def _category_mask(cat) -> np.ndarray:
    """256-bool membership for an sre CATEGORY, via Python's own regex
    semantics over latin-1 code points (so \\w includes e.g. µ exactly
    when re does)."""
    got = _CATEGORY_BYTES.get(cat)
    if got is not None:
        return got
    name = str(cat)
    base = {
        "CATEGORY_DIGIT": r"\d",
        "CATEGORY_NOT_DIGIT": r"\D",
        "CATEGORY_WORD": r"\w",
        "CATEGORY_NOT_WORD": r"\W",
        "CATEGORY_SPACE": r"\s",
        "CATEGORY_NOT_SPACE": r"\S",
    }.get(name.split(".")[-1])
    if base is None:
        raise _Unsupported(f"category {name}")
    rex = re.compile(base)
    mask = np.array(
        [rex.match(chr(b)) is not None for b in range(256)], dtype=bool
    )
    _CATEGORY_BYTES[cat] = mask
    return mask


class _Unsupported(Exception):
    pass


def _case_fold(mask: np.ndarray) -> np.ndarray:
    """IGNORECASE closure: both cases of every member match.
    Multi-char case maps ('ß'.upper() == 'SS') don't fold to a single
    byte and are left alone — matching Python's simple casefold for
    single-char classes."""
    folded = mask.copy()
    for b in np.flatnonzero(mask):
        c = chr(int(b))
        for other in (c.lower(), c.upper()):
            if len(other) == 1 and ord(other) < 256:
                folded[ord(other)] = True
    return folded


def _class_mask(items, ci: bool) -> np.ndarray:
    """256-bool membership for an IN item list (or a single token).

    Under IGNORECASE the fold applies to the *positive* member set
    before negation ([^a] must reject both 'a' and 'A')."""
    mask = np.zeros(256, dtype=bool)
    negate = False
    for op, arg in items:
        name = str(op)
        if name == "NEGATE":
            negate = True
        elif name == "LITERAL":
            if arg > 255:
                continue  # can't occur in latin-1 text
            mask[arg] = True
        elif name == "RANGE":
            lo, hi = arg
            mask[max(0, lo) : min(255, hi) + 1] = True
        elif name == "CATEGORY":
            mask |= _category_mask(arg)
        else:
            raise _Unsupported(f"class item {name}")
    if ci:
        mask = _case_fold(mask)
    if negate:
        mask = ~mask
    return mask


def _lower_fold(mask: np.ndarray) -> np.ndarray:
    """Project a raw-byte mask onto the ASCII-lowered stream domain:
    observed byte x could be original x or (if x is a lowercase
    letter) its uppercase form."""
    out = mask.copy()
    for b in range(ord("a"), ord("z") + 1):
        out[b] = mask[b] or mask[b - 32]
    # uppercase letters never appear in a lowered stream
    out[ord("A") : ord("Z") + 1] = False
    return out


# --- parse-tree walk --------------------------------------------------------


def _expand(
    seq, ci: bool, dotall: bool = False
) -> list[list[tuple[np.ndarray, int]]]:
    """sre subpattern → list of alternatives, each a list of
    (byte-mask, kind). Raises _Unsupported to reject."""
    outs: list[list[tuple[np.ndarray, int]]] = [[]]

    def cross(alts: list[list[tuple[np.ndarray, int]]]) -> None:
        nonlocal outs
        nxt = [o + a for o in outs for a in alts]
        if len(nxt) > MAX_SEQUENCES:
            raise _Unsupported("alternation explosion")
        outs = nxt

    for op, arg in seq:
        name = str(op)
        if name == "LITERAL":
            if arg > 255:
                raise _Unsupported("non-latin literal")
            mask = np.zeros(256, dtype=bool)
            mask[arg] = True
            if ci:
                c = chr(arg)
                for other in (c.lower(), c.upper()):
                    if len(other) == 1 and ord(other) < 256:
                        mask[ord(other)] = True
            cross([[(mask, K_ONE)]])
        elif name == "NOT_LITERAL":
            mask = np.ones(256, dtype=bool)
            if arg <= 255:
                mask[arg] = False
                if ci:
                    c = chr(arg)
                    for other in (c.lower(), c.upper()):
                        if len(other) == 1 and ord(other) < 256:
                            mask[ord(other)] = False
            cross([[(mask, K_ONE)]])
        elif name == "ANY":
            mask = np.ones(256, dtype=bool)
            if not dotall:
                mask[ord("\n")] = False
            cross([[(mask, K_ONE)]])
        elif name == "IN":
            cross([[(_class_mask(arg, ci), K_ONE)]])
        elif name == "SUBPATTERN":
            _gid, add_flags, del_flags, sub = arg
            if add_flags & re.ASCII:
                # scoped (?a:) — same Unicode-vs-ASCII mask hazard as
                # the top-level guard in compile_linear
                raise _Unsupported("ascii-flag scope")
            sub_ci = (ci or bool(add_flags & re.IGNORECASE)) and not bool(
                del_flags & re.IGNORECASE
            )
            if sub_ci != ci:
                raise _Unsupported("mixed-case scopes")
            # scoped (?s:)/(?-s:) only changes ANY masks — no stream
            # choice involved, so mixing is fine
            sub_dotall = (
                dotall or bool(add_flags & re.DOTALL)
            ) and not bool(del_flags & re.DOTALL)
            cross(_expand(sub, sub_ci, sub_dotall))
        elif name == "BRANCH":
            alts: list[list[tuple[np.ndarray, int]]] = []
            for branch in arg[1]:
                alts.extend(_expand(branch, ci, dotall))
                if len(alts) > MAX_SEQUENCES:
                    raise _Unsupported("alternation explosion")
            cross(alts)
        elif name in ("MAX_REPEAT", "MIN_REPEAT"):
            lo, hi, sub = arg
            if hi == 0:
                continue  # X{0} / (X+){0} matches only the empty string
            sub_alts = _expand(sub, ci, dotall)
            single = (
                len(sub_alts) == 1 and len(sub_alts[0]) == 1
            )
            if single:
                mask, kind = sub_alts[0][0]
                # kind algebra for nested repeats of one position:
                # (X+)? = X*, (X?)*= X*, (X+){2,3} = X{2,}, …
                skippable = kind in (K_OPT, K_OPTLOOP)
                loopy = kind in (K_LOOP, K_OPTLOOP)
                eff_lo = 0 if skippable else lo
                unbounded = loopy or hi == sre_c.MAXREPEAT
                if unbounded:
                    if eff_lo > MAX_POSITIONS:
                        raise _Unsupported("huge repeat")
                    fixed = [(mask, K_ONE)] * max(eff_lo - 1, 0)
                    loop = [(mask, K_LOOP if eff_lo >= 1 else K_OPTLOOP)]
                    cross([fixed + loop])
                else:
                    if hi > MAX_POSITIONS:
                        raise _Unsupported("huge repeat")
                    cross(
                        [
                            [(mask, K_ONE)] * eff_lo
                            + [(mask, K_OPT)] * (hi - eff_lo)
                        ]
                    )
            else:
                # multi-position group: expand counts as alternatives
                if hi == sre_c.MAXREPEAT or hi > 4:
                    raise _Unsupported("unbounded group repeat")
                alts = []
                for n in range(lo, hi + 1):
                    reps: list[list[tuple[np.ndarray, int]]] = [[]]
                    for _ in range(n):
                        reps = [r + a for r in reps for a in sub_alts]
                        if len(reps) > MAX_SEQUENCES:
                            raise _Unsupported("group repeat explosion")
                    alts.extend(reps)
                if len(alts) > MAX_SEQUENCES:
                    raise _Unsupported("group repeat explosion")
                cross(alts)
        elif name == "AT":
            # anchors need absolute stream positions — host keeps them
            raise _Unsupported("anchor")
        else:
            raise _Unsupported(name)
    return outs


def compile_linear(pattern: str) -> Optional[tuple[list[LinearPattern], bool]]:
    """→ (alternatives, case_insensitive) or None.

    ``re.search(pattern, text)`` is True iff any alternative's
    shift-and run accepts — alternatives are an exact OR-decomposition.
    ci alternatives run on the ASCII-lowered stream (their masks are
    pre-folded to the lowered byte domain).

    Edge assertions are supported when they sit at the pattern's very
    ends: ``\\A``/``^`` (anchored start), ``\\Z``/``$`` (anchored
    end; ``$`` keeps its before-final-newline semantics), and ``\\b``
    (word boundary). Interior assertions reject.
    """
    try:
        tree = parse_quiet(pattern)
    except re.error:
        return None
    ci = bool(tree.state.flags & re.IGNORECASE)
    dotall = bool(tree.state.flags & re.DOTALL)
    if tree.state.flags & re.MULTILINE:
        return None  # ^/$ become per-line — out of scope
    if tree.state.flags & re.ASCII:
        # class/category masks below are computed under Unicode
        # semantics; (?a) flips \w/\s/[^...] membership for bytes
        # >= 0x80 — lowering would be a silent false negative on the
        # exact no-host-confirm device path. Keep the host path.
        return None
    toks = list(tree)
    anchored = start_wb = end_wb = False
    end_mode = END_NONE
    while toks and str(toks[0][0]) == "AT":
        at = str(toks[0][1]).rsplit(".", 1)[-1]
        if at in ("AT_BEGINNING", "AT_BEGINNING_STRING"):
            anchored = True
        elif at == "AT_BOUNDARY":
            start_wb = True
        else:
            return None
        toks.pop(0)
    while toks and str(toks[-1][0]) == "AT":
        at = str(toks[-1][1]).rsplit(".", 1)[-1]
        if at == "AT_END_STRING":
            end_mode = END_Z
        elif at == "AT_END":
            end_mode = END_DOLLAR
        elif at == "AT_BOUNDARY":
            end_wb = True
        else:
            return None
        toks.pop(-1)
    if end_wb and end_mode != END_NONE:
        return None  # unusual combo; keep the host path
    try:
        alts = _expand(toks, ci, dotall)
    except _Unsupported:
        return None
    out = []
    for seq in alts:
        if not seq or all(k in (K_OPT, K_OPTLOOP) for _msk, k in seq):
            # matches the empty string — search is always True; the
            # shift-and recurrence only accepts after consuming ≥1 byte
            return None
        if len(seq) > MAX_POSITIONS:
            return None
        m = len(seq)
        classes = np.zeros((m, 8), dtype=np.uint32)
        kinds = np.zeros((m,), dtype=np.int8)
        run = mx = 0
        for i, (mask, kind) in enumerate(seq):
            if ci:
                mask = _lower_fold(mask)
            bits = np.packbits(mask.astype(np.uint8), bitorder="little")
            classes[i] = bits.view("<u4")
            kinds[i] = kind
            if kind in (K_OPT, K_OPTLOOP):
                run += 1
                mx = max(mx, run)
            else:
                run = 0
        if mx > MAX_SKIP_RUN:
            return None
        out.append(
            LinearPattern(
                classes=classes,
                kinds=kinds,
                max_skip_run=mx,
                unbounded=bool(
                    np.isin(kinds, (K_LOOP, K_OPTLOOP)).any()
                ),
                anchored=anchored,
                end_mode=end_mode,
                start_wb=start_wb,
                end_wb=end_wb,
            )
        )
    return out, ci


# --- reference simulator (numpy; the device kernel mirrors this) -----------


def derived_masks(p: LinearPattern):
    """(seed, skip, accept, self_loop_mask) as python ints over m bits."""
    m = p.m
    skippable = np.isin(p.kinds, (K_OPT, K_OPTLOOP))
    self_loop = np.isin(p.kinds, (K_LOOP, K_OPTLOOP))
    seed = 0
    for i in range(m):
        seed |= 1 << i
        if not skippable[i]:
            break
    skip = 0
    accept = 1 << (m - 1)
    for i in range(m):
        if skippable[i]:
            skip |= 1 << i
    for i in range(m - 2, -1, -1):
        if skippable[i + 1:].all():
            accept |= 1 << i
    sl = 0
    for i in range(m):
        if self_loop[i]:
            sl |= 1 << i
    return seed, skip, accept, sl


def byte_in_class(p: LinearPattern, i: int, c: int) -> bool:
    return bool((p.classes[i, c >> 5] >> (c & 31)) & 1)


def search_ref(p: LinearPattern, data: bytes) -> bool:
    """Pure-python shift-and over ``data`` — the spec the device kernel
    and the fuzz tests both check against."""
    seed, skip, accept, sl = derived_masks(p)
    m = p.m
    D = 0
    pending = False  # accept awaiting the trailing-\b check
    pending_word = False  # wordness of that accept's final char
    n = len(data)
    for t, c in enumerate(data):
        w_c = bool(_WORD_BYTES[c])
        if pending and (pending_word != w_c):
            return True
        pending = False
        bc = 0
        for i in range(m):
            if byte_in_class(p, i, c):
                bc |= 1 << i
        s = seed
        if p.anchored and t > 0:
            s = 0
        if p.start_wb:
            w_prev = t > 0 and bool(_WORD_BYTES[data[t - 1]])
            if not (w_c != w_prev):
                s = 0
        D = (((D << 1) | s) & bc) | (D & sl & bc)
        for _ in range(p.max_skip_run):
            D |= (D << 1) & skip
        D &= (1 << m) - 1
        if D & accept:
            if p.end_wb:
                pending = True
                pending_word = w_c
            elif p.end_mode == END_NONE:
                return True
            elif p.end_mode == END_Z:
                if t == n - 1:
                    return True
            else:  # END_DOLLAR: end, or just before a final newline
                if t == n - 1 or (t == n - 2 and data[n - 1] == 0x0A):
                    return True
    # end of stream is a boundary exactly after a word char
    return pending and pending_word


def search_pattern(
    alts: list[LinearPattern], ci: bool, data: bytes
) -> bool:
    if ci:
        data = bytes(
            c + 32 if 65 <= c <= 90 else c for c in data
        )
    return any(search_ref(p, data) for p in alts)
