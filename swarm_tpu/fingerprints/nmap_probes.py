"""nmap-service-probes parser + service fingerprint model.

The reference's nmap module ran ``nmap -sV`` (service/version detection
— ``/root/reference/worker/modules/nmap.json``), whose brain is the
``nmap-service-probes`` database: probe payloads to send per port, and
per-probe ordered ``match``/``softmatch`` regex directives that name the
service and extract product/version fields.

This module parses that file format (the real system DB when present,
else the bundled mini DB at ``swarm_tpu/data/service-probes.txt``) into
a neutral model the TPU match path consumes: every match directive
lowers to a regex matcher over the banner stream (compiled through the
same word-table/required-literal infrastructure as the template corpus),
with host-side confirmation supplying the capture groups for version
template substitution (``$1``..``$9``).

Format reference (publicly documented by nmap):
  Probe <TCP|UDP> <name> q|<payload>|
  ports <spec>[,spec...]   sslports <spec>   rarity <n>
  totalwaitms <ms>         fallback <name>[,name...]
  match <service> m<delim><regex><delim>[flags] [p/…/ v/…/ i/…/ o/…/ h/…/ cpe:/…/]
  softmatch <service> m<delim><regex><delim>[flags]
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path

from swarm_tpu.fingerprints.regexlin import quiet_warnings
from typing import Optional

BUNDLED_DB = Path(__file__).resolve().parent.parent / "data" / "service-probes.txt"
SYSTEM_DB = Path("/usr/share/nmap/nmap-service-probes")


@dataclasses.dataclass
class ServiceMatch:
    service: str
    pattern: str                    # raw regex source (perl-ish)
    flags: str = ""                 # subset of "si"
    soft: bool = False
    product: Optional[str] = None   # version-info templates, $N backrefs
    version: Optional[str] = None
    info: Optional[str] = None
    ostype: Optional[str] = None
    hostname: Optional[str] = None
    cpe: list[str] = dataclasses.field(default_factory=list)
    line_no: int = 0

    def compile(self) -> Optional[re.Pattern]:
        """Python re over raw bytes; None when the pattern uses PCRE
        constructs re lacks (those matches are skipped, counted by the
        loader)."""
        f = re.DOTALL if "s" in self.flags else 0
        if "i" in self.flags:
            f |= re.IGNORECASE
        try:
            # nmap DB patterns with literal '[[' trip re's nested-set
            # FutureWarning; their current semantics are the contract
            with quiet_warnings():
                return re.compile(self.pattern.encode("latin-1"), f)
        except (re.error, UnicodeEncodeError):
            return None


@dataclasses.dataclass
class ServiceProbe:
    proto: str                      # TCP | UDP
    name: str
    payload: bytes = b""
    ports: list[tuple[int, int]] = dataclasses.field(default_factory=list)
    sslports: list[tuple[int, int]] = dataclasses.field(default_factory=list)
    rarity: int = 5
    totalwaitms: int = 6000
    fallback: list[str] = dataclasses.field(default_factory=list)
    matches: list[ServiceMatch] = dataclasses.field(default_factory=list)

    def covers_port(self, port: int) -> bool:
        return any(lo <= port <= hi for lo, hi in self.ports)


_ESCAPES = {
    b"0": b"\0", b"a": b"\a", b"b": b"\b", b"f": b"\f", b"n": b"\n",
    b"r": b"\r", b"t": b"\t", b"v": b"\v", b"\\": b"\\",
}


def unescape_payload(raw: str) -> bytes:
    """nmap q|...| payload escapes: C-style chars + \\xHH."""
    data = raw.encode("latin-1")
    out = bytearray()
    i = 0
    while i < len(data):
        ch = data[i : i + 1]
        if ch != b"\\" or i + 1 >= len(data):
            out += ch
            i += 1
            continue
        nxt = data[i + 1 : i + 2]
        if nxt == b"x" and i + 3 < len(data):
            out.append(int(data[i + 2 : i + 4], 16))
            i += 4
        elif nxt in _ESCAPES:
            out += _ESCAPES[nxt]
            i += 2
        else:
            out += nxt
            i += 2
    return bytes(out)


def parse_port_spec(spec: str) -> list[tuple[int, int]]:
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, _, hi = part.partition("-")
            out.append((int(lo), int(hi)))
        else:
            out.append((int(part), int(part)))
    return out


_VERSION_FIELD_RE = re.compile(r"(cpe:|[pvidoh])(.)")


def _parse_version_info(rest: str, m: ServiceMatch) -> None:
    """p/…/ v/…/ i/…/ d/…/ o/…/ h/…/ cpe:/…/[a] annotations after the
    regex. Fields are consumed strictly left-to-right at field
    boundaries — never scanned for inside a previous field's value
    (``d/switch/`` must not yield a phantom ``h/`` field)."""
    i = 0
    n = len(rest)
    while i < n:
        if rest[i].isspace():
            i += 1
            continue
        mo = _VERSION_FIELD_RE.match(rest, i)
        if not mo:
            return  # unrecognized token: stop rather than mis-slice
        key, delim = mo.group(1), mo.group(2)
        start = mo.end()
        end = rest.find(delim, start)
        if end < 0:
            return
        value = rest[start:end]
        i = end + 1
        # cpe may carry a trailing 'a' (applies-to-app) flag
        while i < n and not rest[i].isspace():
            i += 1
        if key == "p":
            m.product = value
        elif key == "v":
            m.version = value
        elif key == "i":
            m.info = value
        elif key == "o":
            m.ostype = value
        elif key == "h":
            m.hostname = value
        elif key == "d":
            pass  # devicetype: parsed (so later fields stay aligned), not lifted
        elif key == "cpe:":
            m.cpe.append(value)


def _parse_match(line: str, line_no: int, soft: bool) -> Optional[ServiceMatch]:
    # match <service> m<delim><regex><delim>[flags] [version info]
    body = line.split(None, 1)[1] if " " in line else ""
    parts = body.split(None, 1)
    if len(parts) < 2:
        return None
    service, rest = parts
    if not rest.startswith("m") or len(rest) < 3:
        return None
    delim = rest[1]
    end = rest.find(delim, 2)
    if end < 0:
        return None
    pattern = rest[2:end]
    tail = rest[end + 1 :]
    flags = ""
    while tail and tail[0] in "si":
        flags += tail[0]
        tail = tail[1:]
    m = ServiceMatch(
        service=service, pattern=pattern, flags=flags, soft=soft, line_no=line_no
    )
    _parse_version_info(tail.strip(), m)
    return m


def parse_probes(text: str) -> tuple[list[ServiceProbe], int]:
    """→ (probes, skipped_match_count). Directives before any Probe line
    (Exclude etc.) are ignored."""
    probes: list[ServiceProbe] = []
    current: Optional[ServiceProbe] = None
    skipped = 0
    for line_no, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        word = line.split(None, 1)[0]
        if word == "Probe":
            parts = line.split(None, 3)
            if len(parts) < 4:
                continue
            _, proto, name, rest = parts
            payload = b""
            if rest.startswith("q") and len(rest) >= 3:
                delim = rest[1]
                end = rest.find(delim, 2)
                if end >= 0:
                    payload = unescape_payload(rest[2:end])
            current = ServiceProbe(proto=proto.upper(), name=name, payload=payload)
            probes.append(current)
        elif current is None:
            continue
        elif word == "ports":
            current.ports = parse_port_spec(line.split(None, 1)[1])
        elif word == "sslports":
            current.sslports = parse_port_spec(line.split(None, 1)[1])
        elif word == "rarity":
            current.rarity = int(line.split(None, 1)[1])
        elif word == "totalwaitms":
            current.totalwaitms = int(line.split(None, 1)[1])
        elif word == "fallback":
            current.fallback = [f.strip() for f in line.split(None, 1)[1].split(",")]
        elif word in ("match", "softmatch"):
            m = _parse_match(line, line_no, soft=(word == "softmatch"))
            if m is None or m.compile() is None:
                skipped += 1
            else:
                current.matches.append(m)
    return probes, skipped


def load_probes(path: Optional[str | Path] = None) -> tuple[list[ServiceProbe], int]:
    """Load a probes DB: explicit path > system nmap DB > bundled mini DB."""
    p = Path(path) if path else (SYSTEM_DB if SYSTEM_DB.is_file() else BUNDLED_DB)
    return parse_probes(p.read_text(encoding="latin-1"))


_HELPER_RE = re.compile(
    r"\$P\((\d)\)"                                  # printable filter
    r"|\$SUBST\((\d),\"([^\"]*)\",\"([^\"]*)\"\)"   # substring replace
    r"|\$I\((\d),\"([<>])\"\)"                      # unsigned int from bytes
    r"|\$(\d)"                                      # plain backref
)


def substitute_version(template: Optional[str], mo: re.Match) -> Optional[str]:
    """Backref substitution in p/v/i templates: ``$1``..``$9`` plus the
    nmap helper functions ``$P(n)`` (strip non-printable bytes),
    ``$SUBST(n,"a","b")`` and ``$I(n,"<"|">")`` (endian-tagged unsigned
    int). Missing groups substitute empty."""
    if template is None:
        return None

    def group(idx: str) -> bytes:
        try:
            return mo.group(int(idx)) or b""
        except (IndexError, re.error):
            return b""

    def repl(m: re.Match) -> str:
        p, s_n, s_a, s_b, i_n, i_e, plain = m.groups()
        if p is not None:
            return bytes(b for b in group(p) if 32 <= b < 127).decode("ascii")
        if s_n is not None:
            return group(s_n).decode("latin-1", "replace").replace(s_a, s_b)
        if i_n is not None:
            return str(
                int.from_bytes(group(i_n), "little" if i_e == "<" else "big")
            )
        return group(plain).decode("latin-1", "replace")

    return _HELPER_RE.sub(repl, template).strip()
