"""Fingerprint corpus front-end: parsers and compilers.

Turns template corpora (nuclei-template YAML, nmap-service-probes) into
(a) an exact CPU-evaluable form (`model.Template`) and (b) a dense
tensor database (`compile.CompiledDB`) consumed by the device match
kernels in :mod:`swarm_tpu.ops`.
"""

from swarm_tpu.fingerprints.model import (  # noqa: F401
    Extractor,
    Matcher,
    Operation,
    Response,
    Template,
)
from swarm_tpu.fingerprints.nuclei import load_corpus, parse_template  # noqa: F401
