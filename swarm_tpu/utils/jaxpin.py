"""Make an operator-set JAX_PLATFORMS env var actually stick.

Platform plugins registered by site hooks (the image's sitecustomize
registers the accelerator backend at interpreter start) can override
the env var alone, so a process told ``JAX_PLATFORMS=cpu`` would still
dial the accelerator — and hang forever when its tunnel is wedged.
``jax.config.update`` wins over both; every entry point that honors the
env var pins through here so the semantics cannot diverge (worker
runtime, bench, the graft entry). backendprobe.py's child program
inlines the same idiom as a self-contained string — keep it in
lock-step with this helper.
"""

from __future__ import annotations

from typing import Optional


def pin_platform_from_env() -> Optional[str]:
    """Pin the env-selected platform through jax.config; returns the
    pinned value, or None when the env leaves platform selection to
    JAX's default (registered-plugin priority)."""
    import os

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:  # comma-separated priority lists are valid config values
        import jax

        jax.config.update("jax_platforms", plat)
    return plat or None
