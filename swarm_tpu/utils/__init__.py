"""Cross-cutting utilities: tracing/profiling, phase timing."""
