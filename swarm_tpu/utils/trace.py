"""Tracing and profiling — the observability the reference never had.

The reference's only observability is ``print()`` plus job timestamps
(SURVEY.md §5 "Tracing/profiling: None"). Here:

- :class:`PhaseTimer` — wall-clock per pipeline phase
  (download/execute/upload), reported to the server inside the job's
  ``perf`` field on completion and aggregated into the per-scan rollup
  (``rows_per_second`` etc. in ``/get-statuses``).
- :func:`maybe_device_profile` — wraps a block in a JAX profiler trace
  (TensorBoard-loadable) when ``SWARM_PROFILE_DIR`` is set; free when
  it is not. Device-level visibility into the match kernels without any
  code change at the call sites.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Iterator, Optional

PROFILE_ENV = "SWARM_PROFILE_DIR"


class PhaseTimer:
    """Accumulates named wall-clock phases → a flat perf dict.

    Thread-safe: worker sessions tick phases from the streaming thread
    while the telemetry scraper snapshots mid-job, so every mutation
    holds the lock and :meth:`snapshot` hands out copies.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()  # guards: seconds (reads), counters (reads)
        self.seconds: dict[str, float] = {}
        self.counters: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.seconds[name] = self.seconds.get(name, 0.0) + dt

    def count(self, name: str, value: float) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0.0) + value

    def snapshot(self) -> tuple[dict[str, float], dict[str, float]]:
        """Point-in-time ``(seconds, counters)`` copies — never mutates,
        safe to call from any thread mid-job."""
        with self._lock:
            return dict(self.seconds), dict(self.counters)

    def perf(self) -> dict:
        seconds, counters = self.snapshot()
        out: dict = {f"{k}_s": round(v, 6) for k, v in seconds.items()}
        for k, v in counters.items():
            out[k] = int(v) if float(v).is_integer() else v
        return out


@contextlib.contextmanager
def maybe_device_profile(tag: str, profile_dir: Optional[str] = None) -> Iterator[bool]:
    """JAX profiler trace around the block when profiling is enabled.

    ``profile_dir`` defaults to ``$SWARM_PROFILE_DIR``; yields whether a
    trace was actually recorded. Traces land in
    ``<dir>/<tag>/plugins/profile/...`` for TensorBoard.
    """
    root = profile_dir if profile_dir is not None else os.environ.get(PROFILE_ENV)
    if not root:
        yield False
        return
    import jax

    target = os.path.join(root, tag)
    os.makedirs(target, exist_ok=True)
    with jax.profiler.trace(target):
        yield True
