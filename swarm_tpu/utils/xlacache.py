"""Persistent XLA compilation cache.

The full-corpus match kernel takes tens of seconds to compile (device
word tables + q-gram prefilter + verify + regex lanes in one jit). The
reference worker had no analogous cost — its engines were prebuilt
binaries — so worker startup parity argues for caching: with JAX's
persistent compilation cache enabled, every worker restart (and every
fleet scale-up clone, server/fleet.py) after the first reuses the
serialized executable instead of recompiling.
"""

from __future__ import annotations

import os
import sys
from pathlib import Path
from typing import Optional

DEFAULT_CACHE_DIR = "~/.cache/swarm_tpu/xla"
_active_dir: Optional[str] = None


def enable_compilation_cache(cache_dir: Optional[str] = None) -> str:
    """Idempotently point JAX's persistent compilation cache at
    ``cache_dir`` (default ``~/.cache/swarm_tpu/xla``, overridable via
    ``SWARM_XLA_CACHE_DIR``; empty string disables). Returns the dir
    actually in effect ('' when disabled) — once bound, later calls
    with a different dir return the original binding. A cache dir that
    cannot be created degrades to no-cache rather than failing startup
    (the worker must run with a read-only HOME)."""
    global _active_dir
    if _active_dir is not None:
        return _active_dir
    raw = (
        cache_dir
        if cache_dir is not None
        else os.environ.get("SWARM_XLA_CACHE_DIR", DEFAULT_CACHE_DIR)
    )
    if not raw:
        return ""
    path = Path(raw).expanduser()
    try:
        path.mkdir(parents=True, exist_ok=True)
    except OSError as e:
        # stderr: bench.py's stdout is a JSON-only metric stream
        print(f"xla cache disabled ({path}: {e})", file=sys.stderr)
        return ""
    import jax

    jax.config.update("jax_compilation_cache_dir", str(path))
    # cache everything that took real compile time; tiny kernels
    # aren't worth the disk round-trip
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _active_dir = str(path)
    return _active_dir
