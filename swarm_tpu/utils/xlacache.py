"""Persistent XLA compilation cache.

The full-corpus match kernel takes tens of seconds to compile (device
word tables + q-gram prefilter + verify + regex lanes in one jit). The
reference worker had no analogous cost — its engines were prebuilt
binaries — so worker startup parity argues for caching: with JAX's
persistent compilation cache enabled, every worker restart (and every
fleet scale-up clone, server/fleet.py) after the first reuses the
serialized executable instead of recompiling.
"""

from __future__ import annotations

import os
import sys
from pathlib import Path
from typing import Optional

DEFAULT_CACHE_DIR = "~/.cache/swarm_tpu/xla"
_active_dir: Optional[str] = None
_metrics_installed = False

#: jax.monitoring event names the persistent cache emits (jax/_src/
#: compiler.py + compilation_cache.py) — one listener maps them onto
#: the swarm counters.
_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _cache_counters():
    from swarm_tpu.telemetry import REGISTRY

    hit = REGISTRY.counter(
        "swarm_xla_cache_hit_total",
        "Persistent XLA compilation cache hits (executable deserialized "
        "instead of recompiled)",
    )
    miss = REGISTRY.counter(
        "swarm_xla_cache_miss_total",
        "Persistent XLA compilation cache misses (fresh compile written "
        "back to the cache)",
    )
    return hit, miss


def _cache_event_listener(event: str, **_kw) -> None:
    """jax.monitoring → telemetry bridge (module-level so tests can
    drive it with synthetic events)."""
    hit, miss = _cache_counters()
    if event == _HIT_EVENT:
        hit.inc()
    elif event == _MISS_EVENT:
        miss.inc()


def install_cache_metrics() -> bool:
    """Idempotently register the swarm_xla_cache_{hit,miss}_total
    counters on JAX's monitoring stream. Separate from
    :func:`enable_compilation_cache` so fleet code can re-assert the
    wiring; returns whether the listener is installed. Without these,
    a fleet restart can't tell whether the persistent cache actually
    served (the whole point of shipping it)."""
    global _metrics_installed
    if _metrics_installed:
        return True
    try:
        from jax import monitoring
    except ImportError:  # pragma: no cover - jax always present here
        return False
    _cache_counters()  # register the families even before any event
    monitoring.register_event_listener(_cache_event_listener)
    _metrics_installed = True
    return True


def enable_compilation_cache(cache_dir: Optional[str] = None) -> str:
    """Idempotently point JAX's persistent compilation cache at
    ``cache_dir`` (default ``~/.cache/swarm_tpu/xla``, overridable via
    ``SWARM_XLA_CACHE_DIR``; empty string disables). Returns the dir
    actually in effect ('' when disabled) — once bound, later calls
    with a different dir return the original binding. A cache dir that
    cannot be created degrades to no-cache rather than failing startup
    (the worker must run with a read-only HOME)."""
    global _active_dir
    if _active_dir is not None:
        return _active_dir
    raw = (
        cache_dir
        if cache_dir is not None
        else os.environ.get("SWARM_XLA_CACHE_DIR", DEFAULT_CACHE_DIR)
    )
    if not raw:
        return ""
    path = Path(raw).expanduser()
    try:
        path.mkdir(parents=True, exist_ok=True)
    except OSError as e:
        # stderr: bench.py's stdout is a JSON-only metric stream
        print(f"xla cache disabled ({path}: {e})", file=sys.stderr)
        return ""
    import jax

    jax.config.update("jax_compilation_cache_dir", str(path))
    # cache everything that took real compile time; tiny kernels
    # aren't worth the disk round-trip
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    install_cache_metrics()  # hit/miss counters ride every enable
    _active_dir = str(path)
    return _active_dir
