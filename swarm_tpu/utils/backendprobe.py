"""Disposable-subprocess backend probe.

The configured accelerator backend can wedge *inside* init — the
tunnel hangs in a C call that signals cannot interrupt, so an
in-process ``jax.devices()`` (and even a SIGALRM guard around it) hangs
forever. The only safe probe from a jax-uninitialized process is a
disposable subprocess with a hard timeout. Both bench.py and
``__graft_entry__.dryrun_multichip`` route through here so the
wedge-handling logic cannot diverge.

Do NOT call this after the current process initialized a backend: the
child would contend with this process's exclusive accelerator.
"""

from __future__ import annotations

import subprocess
import sys


def probe_backend(timeout: float = 150.0) -> tuple[bool, str, int]:
    """→ (ok, platform, device_count) of the environment-configured JAX
    backend, probed in a subprocess. ``ok`` False = the probe hung or
    failed — treat the backend as unusable and force CPU.

    The probe runs a real (tiny) computation, not just device
    enumeration: the tunnel has been observed in a half-dead state
    where ``jax.devices()`` answers but any dispatched program blocks
    forever, and enumeration alone would wave that state through.
    """
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                # pin an env-selected platform through jax.config: site
                # hooks can register plugin backends that override the
                # env var alone, and the probe must exercise the same
                # backend its caller will get. Inlined (self-contained
                # stdlib+jax child) — keep in lock-step with
                # utils/jaxpin.pin_platform_from_env, the idiom's home
                # for in-process users.
                "import os, jax, jax.numpy as jnp;"
                " p = os.environ.get('JAX_PLATFORMS');"
                " p and jax.config.update('jax_platforms', p);"
                " d = jax.devices();"
                " (jnp.ones((8, 8)) + 1).block_until_ready();"
                " print(d[0].platform, len(d))",
            ],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False, "", 0
    out = proc.stdout.strip().split()
    if proc.returncode != 0 or len(out) != 2:
        return False, "", 0
    try:
        return True, out[0], int(out[1])
    except ValueError:
        return False, "", 0
