"""Disposable-subprocess backend probe.

The configured accelerator backend can wedge *inside* init — the
tunnel hangs in a C call that signals cannot interrupt, so an
in-process ``jax.devices()`` (and even a SIGALRM guard around it) hangs
forever. The only safe probe from a jax-uninitialized process is a
disposable subprocess with a hard timeout. Both bench.py and
``__graft_entry__.dryrun_multichip`` route through here so the
wedge-handling logic cannot diverge.

Do NOT call this after the current process initialized a backend: the
child would contend with this process's exclusive accelerator.
"""

from __future__ import annotations

import subprocess
import sys
import time


def probe_backend(timeout: float = 150.0) -> tuple[bool, str, int]:
    """→ (ok, platform, device_count) of the environment-configured JAX
    backend, probed in a subprocess. ``ok`` False = the probe hung or
    failed — treat the backend as unusable and force CPU.

    The probe runs a real (tiny) computation, not just device
    enumeration: the tunnel has been observed in a half-dead state
    where ``jax.devices()`` answers but any dispatched program blocks
    forever, and enumeration alone would wave that state through.
    """
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                # pin an env-selected platform through jax.config: site
                # hooks can register plugin backends that override the
                # env var alone, and the probe must exercise the same
                # backend its caller will get. Inlined (self-contained
                # stdlib+jax child) — keep in lock-step with
                # utils/jaxpin.pin_platform_from_env, the idiom's home
                # for in-process users.
                "import os, jax, jax.numpy as jnp;"
                " p = os.environ.get('JAX_PLATFORMS');"
                " p and jax.config.update('jax_platforms', p);"
                " d = jax.devices();"
                " (jnp.ones((8, 8)) + 1).block_until_ready();"
                " print(d[0].platform, len(d))",
            ],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False, "", 0
    out = proc.stdout.strip().split()
    if proc.returncode != 0 or len(out) != 2:
        return False, "", 0
    try:
        return True, out[0], int(out[1])
    except ValueError:
        return False, "", 0


def probe_backend_retry(
    attempt_timeout: float = 150.0,
    deadline: float = 1800.0,
    wait: float = 60.0,
    log=None,
) -> tuple[bool, str, int]:
    """``probe_backend`` in a retry loop: re-probe until success or
    ``deadline`` seconds have elapsed, sleeping ``wait`` seconds between
    attempts (a hung attempt already burns ``attempt_timeout``, so the
    effective cadence is 1–3.5 min). A transient tunnel outage at probe
    time must not erase a whole benchmark run — the round-4 record was
    wiped by exactly one failed 150 s probe committing every phase to
    CPU. Each attempt is reported through ``log`` so the run's record
    shows what was tried, not just the final verdict.

    ``deadline <= attempt_timeout`` degrades to a single attempt.
    """
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        t0 = time.monotonic()
        ok, platform, count = probe_backend(timeout=attempt_timeout)
        took = time.monotonic() - t0
        elapsed = time.monotonic() - start
        if log is not None:
            log(
                f"backend probe attempt {attempt}: "
                f"{'ok platform=' + platform if ok else 'FAILED'} "
                f"(attempt {took:.0f}s, total {elapsed:.0f}s, "
                f"deadline {deadline:.0f}s)"
            )
        if ok:
            return ok, platform, count
        if deadline <= attempt_timeout:  # single-attempt configuration
            return False, "", 0
        remaining = deadline - (time.monotonic() - start)
        # budget the sleep AND the next attempt (sized by how long the
        # last one actually took: fast-fail probes keep retrying to the
        # wire, hanging ones stop early enough not to overshoot)
        if remaining <= wait + took:
            return False, "", 0
        time.sleep(wait)
