"""Disposable-subprocess backend probe.

The configured accelerator backend can wedge *inside* init — the
tunnel hangs in a C call that signals cannot interrupt, so an
in-process ``jax.devices()`` (and even a SIGALRM guard around it) hangs
forever. The only safe probe from a jax-uninitialized process is a
disposable subprocess with a hard timeout. Both bench.py and
``__graft_entry__.dryrun_multichip`` route through here so the
wedge-handling logic cannot diverge.

Do NOT call this after the current process initialized a backend: the
child would contend with this process's exclusive accelerator.
"""

from __future__ import annotations

import subprocess
import sys


def probe_backend(timeout: float = 150.0) -> tuple[bool, str, int]:
    """→ (ok, platform, device_count) of the environment-configured JAX
    backend, probed in a subprocess. ``ok`` False = the probe hung or
    failed — treat the backend as unusable and force CPU."""
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; d = jax.devices(); print(d[0].platform, len(d))",
            ],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False, "", 0
    out = proc.stdout.strip().split()
    if proc.returncode != 0 or len(out) != 2:
        return False, "", 0
    try:
        return True, out[0], int(out[1])
    except ValueError:
        return False, "", 0
