"""Control plane: REST C2 server, job queue, fleet orchestration."""
