"""Elastic worker-fleet orchestration behind a provider interface.

The reference hardcodes DigitalOcean droplet create/delete with a
250-req/min thread limiter and idle auto-teardown
(``server/server.py:47-162, 506-546``). Here the same capabilities sit
behind ``FleetProvider``:

- ``NullProvider`` — no-op (TPU pods are typically statically
  provisioned; elastic scale means releasing queued shards, not
  hardware).
- ``ProcessProvider`` — spawns/kills local worker *processes*; the
  embedded single-host analog of a droplet fleet and what tests use.
- ``DigitalOceanProvider`` — wire-equivalent of the reference: same
  API endpoints, name-prefix selection, cloud-init user_data boot.

All providers share the token-bucket rate limiter and run create/delete
in background threads like the reference's ``/spin-up`` handler.
"""

from __future__ import annotations

import math
import os
import random
import shlex
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from swarm_tpu.telemetry.fleet_export import (
    FLEET_COLDSTART,
    FLEET_FORECAST,
    FLEET_NODES,
    FLEET_PREEMPTIONS,
    FLEET_SCALE_EVENTS,
    FLEET_TARGET,
)


class RateLimiter:
    """Token bucket: at most ``per_minute`` acquisitions per rolling minute."""

    def __init__(self, per_minute: int):
        self.per_minute = max(1, per_minute)
        self._lock = threading.Lock()  # guards: _stamps (reads)
        self._stamps: list[float] = []

    def acquire(self) -> None:
        while True:
            with self._lock:
                now = time.time()
                self._stamps = [s for s in self._stamps if now - s < 60.0]
                if len(self._stamps) < self.per_minute:
                    self._stamps.append(now)
                    return
                sleep_for = 60.0 - (now - self._stamps[0])
            time.sleep(max(0.05, sleep_for))


def generate_node_names(prefix: str, nodes: int) -> list[str]:
    """``prefix1..prefixN`` (reference server.py:76-77)."""
    return [f"{prefix}{i}" for i in range(1, nodes + 1)]


class FleetProvider:
    def spin_up(self, prefix: str, nodes: int) -> None:
        raise NotImplementedError

    def spin_down(self, prefix: str) -> None:
        raise NotImplementedError

    def list_nodes(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def teardown_async(self, prefix: str) -> None:
        t = threading.Thread(target=self.spin_down, args=(prefix,), daemon=True)
        t.start()


class NullProvider(FleetProvider):
    def spin_up(self, prefix, nodes):
        pass

    def spin_down(self, prefix):
        pass

    def list_nodes(self, prefix):
        return []


class ProcessProvider(FleetProvider):
    """Local worker processes as fleet nodes (embedded / test provider)."""

    def __init__(self, cfg, extra_args: Optional[list[str]] = None):
        self.cfg = cfg
        self.extra_args = extra_args or []
        self._lock = threading.Lock()  # guards: _procs (reads)
        self._procs: dict[str, subprocess.Popen] = {}

    def spin_up(self, prefix, nodes):
        for name in generate_node_names(prefix, nodes):
            with self._lock:
                if name in self._procs and self._procs[name].poll() is None:
                    continue
                cmd = [
                    sys.executable,
                    "-m",
                    "swarm_tpu.worker",
                    "--server-url",
                    self.cfg.resolve_url(),
                    "--api-key",
                    self.cfg.api_key,
                    "--worker-id",
                    name,
                ] + self.extra_args
                self._procs[name] = subprocess.Popen(
                    cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
                )

    def spin_down(self, prefix):
        with self._lock:
            for name, proc in list(self._procs.items()):
                if name.startswith(prefix) and proc.poll() is None:
                    proc.terminate()
                    self._procs.pop(name, None)

    def list_nodes(self, prefix):
        with self._lock:
            return [
                n
                for n, p in self._procs.items()
                if n.startswith(prefix) and p.poll() is None
            ]

    def shutdown(self):
        self.spin_down("")


class DigitalOceanProvider(FleetProvider):
    """Reference-equivalent cloud provider (requires network egress)."""

    API = "https://api.digitalocean.com/v2"

    def __init__(self, cfg, worker_image: str = "pry0cc/axiom-worker"):
        import requests  # stdlib-adjacent; baked in

        self._requests = requests
        self.cfg = cfg
        self.worker_image = worker_image
        self.limiter = RateLimiter(cfg.fleet_rate_limit_per_min)

    def _headers(self) -> dict:
        return {"Authorization": f"Bearer {self.cfg.fleet_api_token}"}

    def _user_data(self, name: str) -> str:
        env = (
            f"-e SERVER_URL={self.cfg.resolve_url()} -e API_KEY={self.cfg.api_key} "
            f"-e WORKER_ID={name}"
        )
        return f"#cloud-config\nruncmd:\n  - \"docker run -d {env} {self.worker_image}\"\n"

    def _create_one(self, name: str) -> None:
        self.limiter.acquire()
        self._requests.post(
            f"{self.API}/droplets",
            headers=self._headers(),
            json={
                "name": name,
                "region": self.cfg.fleet_region,
                "size": self.cfg.fleet_size,
                "image": self.cfg.fleet_image,
                "user_data": self._user_data(name),
            },
            timeout=30,
        )

    def _delete_one(self, droplet_id: int) -> None:
        self.limiter.acquire()
        self._requests.delete(
            f"{self.API}/droplets/{droplet_id}", headers=self._headers(), timeout=30
        )

    def _droplets(self, prefix: str) -> list[dict]:
        resp = self._requests.get(
            f"{self.API}/droplets?per_page=200", headers=self._headers(), timeout=30
        )
        if resp.status_code != 200:
            return []
        return [
            d
            for d in resp.json().get("droplets", [])
            if d.get("name", "").startswith(prefix)
        ]

    def spin_up(self, prefix, nodes):
        # ensure-up like ProcessProvider: DO allows duplicate droplet
        # names, so re-creating a live name would double-bill and
        # corrupt list_nodes/scale-down arithmetic — skip names that
        # already exist (one listing call per spin_up)
        live = set(self.list_nodes(prefix))
        threads = [
            threading.Thread(target=self._create_one, args=(n,), daemon=True)
            for n in generate_node_names(prefix, nodes)
            if n not in live
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def spin_down(self, prefix):
        droplets = self._droplets(prefix)
        threads = [
            threading.Thread(target=self._delete_one, args=(d["id"],), daemon=True)
            for d in droplets
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def list_nodes(self, prefix):
        return [d["name"] for d in self._droplets(prefix)]


class InflowForecaster:
    """EWMA job-inflow forecaster over the per-tenant admission history.

    The gateway reports every admitted submission's chunk count
    (:meth:`record`); the forecaster folds them into fixed windows and
    keeps one EWMA jobs/second rate per tenant. :meth:`rate` folds any
    elapsed empty windows first, so a tenant that went quiet decays
    toward zero instead of pinning its last spike forever — that decay
    is what lets scale-to-zero park an idle fleet. Deterministic under
    an injected clock (tests/bench pass ``now`` explicitly).
    """

    def __init__(
        self,
        alpha: float = 0.3,
        window_s: float = 1.0,
        clock=time.monotonic,
    ):
        self.alpha = min(1.0, max(0.01, float(alpha)))
        self.window_s = max(0.05, float(window_s))
        self._clock = clock
        self._lock = threading.Lock()  # guards: _rates, _buckets (reads)
        #: tenant -> EWMA jobs/s
        self._rates: dict[str, float] = {}
        #: tenant -> [window_start, jobs_in_window]
        self._buckets: dict[str, list] = {}

    # requires-lock: _lock (record/rate fold under the forecaster lock)
    def _fold_locked(self, tenant: str, now: float) -> None:
        bucket = self._buckets.get(tenant)
        if bucket is None:
            return
        start, count = bucket
        elapsed = now - start
        if elapsed < self.window_s:
            return
        rate = self._rates.get(tenant, 0.0)
        # the closed window's observed rate, then one zero-window blend
        # per fully elapsed empty window since — bounded so a long
        # quiet gap costs O(1), not O(gap)
        rate = rate + self.alpha * (count / self.window_s - rate)
        idle_windows = min(64, int(elapsed / self.window_s) - 1)
        for _ in range(idle_windows):
            rate += self.alpha * (0.0 - rate)
        if rate < 1e-6:
            rate = 0.0
        self._rates[tenant] = rate
        self._buckets[tenant] = [now, 0]

    def record(self, jobs: int, tenant: str = "default", now=None) -> None:
        """Fold ``jobs`` admitted chunks into the tenant's window."""
        now = self._clock() if now is None else now
        with self._lock:
            self._fold_locked(tenant, now)
            bucket = self._buckets.setdefault(tenant, [now, 0])
            bucket[1] += int(jobs)

    def rate(self, tenant: Optional[str] = None, now=None) -> float:
        """EWMA jobs/s — one tenant, or summed across all tenants."""
        now = self._clock() if now is None else now
        with self._lock:
            tenants = [tenant] if tenant else list(
                set(self._rates) | set(self._buckets)
            )
            total = 0.0
            for t in tenants:
                self._fold_locked(t, now)
                total += self._rates.get(t, 0.0)
        return total

    def tenant_rates(self, now=None) -> dict:
        now = self._clock() if now is None else now
        with self._lock:
            for t in list(set(self._rates) | set(self._buckets)):
                self._fold_locked(t, now)
            return {t: r for t, r in self._rates.items() if r > 0.0}


class SimulatedProvider(FleetProvider):
    """Deterministic preemptible-instance provider for tests and bench.

    Models the spot-capacity lifecycle real clouds impose (docs/
    RESILIENCE.md §Preemption): a spun-up node pays a cold-start
    latency before it is servable (drawn from the measured AOT
    bring-up numbers — 4.2 s cold compile vs 0.23 s AOT-warm fetch,
    docs/AOT.md), a preemption arrives as a *notice* first, and the
    node is force-killed ``preempt_grace_s`` after the notice if it
    has not gone away on its own. All transitions advance through
    :meth:`poll` against an injectable clock — no background threads —
    so a seeded run replays bit-identically.

    ``node_factory(name)`` (optional) attaches a real worker to each
    node once its cold-start elapses; the returned handle's ``stop()``
    is called on graceful spin-down and ``kill()`` (fallback
    ``stop()``) on a post-grace preemption kill. ``on_preempt_notice``
    is how the control plane learns a node must drain.
    """

    def __init__(
        self,
        cfg=None,
        seed: int = 0,
        preempt_grace_s: float = 5.0,
        coldstart_cold_s: float = 4.2,
        coldstart_warm_s: float = 0.23,
        aot_warm: bool = True,
        auto_preempt_p: float = 0.0,
        clock=time.monotonic,
        node_factory: Optional[Callable] = None,
        on_preempt_notice: Optional[Callable] = None,
        on_kill: Optional[Callable] = None,
    ):
        if cfg is not None:
            seed = getattr(cfg, "fleet_sim_seed", seed)
            preempt_grace_s = getattr(
                cfg, "fleet_sim_preempt_grace_s", preempt_grace_s
            )
            coldstart_cold_s = getattr(
                cfg, "fleet_sim_coldstart_cold_s", coldstart_cold_s
            )
            coldstart_warm_s = getattr(
                cfg, "fleet_sim_coldstart_warm_s", coldstart_warm_s
            )
            aot_warm = getattr(cfg, "fleet_sim_aot_warm", aot_warm)
        self.preempt_grace_s = float(preempt_grace_s)
        self.coldstart_s = (
            float(coldstart_warm_s) if aot_warm else float(coldstart_cold_s)
        )
        self.auto_preempt_p = float(auto_preempt_p)
        self._rng = random.Random(seed)  # guarded-by: _lock (reads)
        self._clock = clock
        self.node_factory = node_factory
        self.on_preempt_notice = on_preempt_notice
        self.on_kill = on_kill
        self._lock = threading.RLock()  # guards: _nodes, events (reads)
        #: name -> {"state": booting|ready|draining, "ready_at": float,
        #:          "spun_at": float, "kill_at": float|None, "handle": obj}
        self._nodes: dict[str, dict] = {}
        #: audit trail of (t, event, name) — bench/tests assert on it
        self.events: list[tuple] = []

    # -- lifecycle -----------------------------------------------------
    def spin_up(self, prefix, nodes):
        now = self._clock()
        notices = []
        with self._lock:
            for name in generate_node_names(prefix, nodes):
                # ensure-up: live names are skipped — INCLUDING
                # draining ones. A preemption-doomed node dies at
                # kill_at no matter what; re-provisioning its name
                # early would cancel the pending kill while the old
                # (possibly wedged) worker still owns the name's drain
                # state, poisoning the replacement. Capacity returns
                # once the kill lands and deregisters the name.
                if name in self._nodes:
                    continue
                self._nodes[name] = {
                    "state": "booting",
                    "spun_at": now,
                    "ready_at": now + self.coldstart_s,
                    "kill_at": None,
                    "handle": None,
                }
                self.events.append((now, "spin_up", name))
                if (
                    self.auto_preempt_p > 0.0
                    and self._rng.random() < self.auto_preempt_p
                ):
                    notices.append(name)
            self._export_states_locked()
        for name in notices:
            self.preempt(name, now=now)
        self.poll(now)

    def spin_down(self, prefix):
        handles = []
        with self._lock:
            for name, node in list(self._nodes.items()):
                if name.startswith(prefix):
                    if node["handle"] is not None:
                        handles.append(node["handle"])
                    self._nodes.pop(name)
                    self.events.append((self._clock(), "spin_down", name))
            self._export_states_locked()
        for h in handles:
            stop = getattr(h, "stop", None)
            if stop:
                stop()

    def list_nodes(self, prefix):
        with self._lock:
            return [n for n in self._nodes if n.startswith(prefix)]

    def ready_nodes(self, prefix: str = "") -> list[str]:
        self.poll()
        with self._lock:
            return [
                n
                for n, node in self._nodes.items()
                if n.startswith(prefix) and node["state"] != "booting"
            ]

    def shutdown(self):
        self.spin_down("")

    # -- preemption ----------------------------------------------------
    def preempt(self, name: str, now=None) -> bool:
        """Issue a preemption notice; the node is force-killed
        ``preempt_grace_s`` later unless it spun down first. (The
        ``fleet.preempt`` fault point lives on the server's dispatch
        path, where an armed chaos plan *injects* preemptions — see
        ``JobQueueService.next_job``.)"""
        now = self._clock() if now is None else now
        with self._lock:
            node = self._nodes.get(name)
            if node is None or node["state"] == "draining":
                return False
            node["state"] = "draining"
            node["kill_at"] = now + self.preempt_grace_s
            self.events.append((now, "preempt_notice", name))
            self._export_states_locked()
        FLEET_PREEMPTIONS.labels().inc()
        if self.on_preempt_notice is not None:
            try:
                self.on_preempt_notice(name)
            except Exception:
                pass
        return True

    # -- clock advance -------------------------------------------------
    def poll(self, now=None) -> None:
        """Apply due transitions: boots complete, post-grace kills."""
        now = self._clock() if now is None else now
        started, killed = [], []
        with self._lock:
            for name, node in list(self._nodes.items()):
                if node["state"] == "booting" and now >= node["ready_at"]:
                    node["state"] = "ready"
                    FLEET_COLDSTART.labels().observe(
                        node["ready_at"] - node["spun_at"]
                    )
                    self.events.append((now, "ready", name))
                    started.append((name, node))
                if (
                    node["kill_at"] is not None
                    and now >= node["kill_at"]
                ):
                    killed.append((name, node))
                    self._nodes.pop(name)
                    self.events.append((now, "killed", name))
            self._export_states_locked()
        for name, node in started:
            if self.node_factory is not None and node["handle"] is None:
                node["handle"] = self.node_factory(name)
        for name, node in killed:
            h = node["handle"]
            if h is not None:
                kill = getattr(h, "kill", None) or getattr(h, "stop", None)
                if kill:
                    kill()
            # the post-grace kill is the control plane's authoritative
            # "this node is dead NOW": the wired callback (app.py →
            # deregister_worker) hands its leases back immediately and
            # clears the name's drain state, so a wedged worker that
            # never saw its notice cannot poison the name — its
            # eventual stale upload is fenced off by the requeue
            if self.on_kill is not None:
                try:
                    self.on_kill(name)
                except Exception:
                    pass

    # -- telemetry -----------------------------------------------------
    def _export_states_locked(self) -> None:
        # requires-lock: _lock
        counts = {"booting": 0, "ready": 0, "draining": 0}
        for node in self._nodes.values():
            counts[node["state"]] = counts.get(node["state"], 0) + 1
        for state, n in counts.items():
            FLEET_NODES.labels(state=state).set(n)


class AutoscaleAdvisor:
    """Forecast-driven worker autoscaling (docs/GATEWAY.md,
    docs/RESILIENCE.md §Preemption).

    PR 10's advisor was depth-reactive; this one closes the loop and
    scales *ahead* of the spike: the sizing demand is current depth
    plus ``forecast_horizon_s`` seconds of EWMA-forecasted inflow (the
    :class:`InflowForecaster` fed from the admission path), divided by
    the jobs-per-node ratio, clamped to ``[min_nodes, max_nodes]``.
    Scale-up is immediate; scale-down waits out
    ``scaledown_hysteresis`` consecutive below-current recommendations
    so a between-waves trough doesn't thrash the fleet. With
    ``scale_to_zero_after_s`` set, a fleet whose tenants have shown
    zero depth AND zero forecasted inflow for that long parks to zero
    nodes regardless of ``min_nodes`` — the AOT-warm cold-start path
    (docs/AOT.md) re-warms it within the SLO when traffic returns.

    DRY-RUN BY DEFAULT — ``recommend()``/``status()`` only read;
    ``apply()`` touches the provider exclusively when the operator set
    ``gateway_autoscale_apply`` (scale-down tears down the
    highest-numbered nodes by name, matching ``generate_node_names``'s
    ``prefix1..prefixN`` scheme)."""

    def __init__(
        self,
        queue,
        provider: FleetProvider,
        jobs_per_node: int = 4,
        min_nodes: int = 0,
        max_nodes: int = 8,
        apply_enabled: bool = False,
        forecaster: Optional[InflowForecaster] = None,
        forecast_horizon_s: float = 30.0,
        scaledown_hysteresis: int = 3,
        scale_to_zero_after_s: float = 0.0,
        clock=time.monotonic,
    ):
        self.queue = queue
        self.provider = provider
        self.jobs_per_node = max(1, int(jobs_per_node))
        self.min_nodes = max(0, int(min_nodes))
        self.max_nodes = max(self.min_nodes, int(max_nodes))
        self.apply_enabled = bool(apply_enabled)
        self.forecaster = forecaster
        self.forecast_horizon_s = max(0.0, float(forecast_horizon_s))
        self.scaledown_hysteresis = max(0, int(scaledown_hysteresis))
        self.scale_to_zero_after_s = max(0.0, float(scale_to_zero_after_s))
        self._clock = clock
        self._lock = threading.Lock()  # guards: _below_streak, _idle_since, last_recommendation (reads)
        self._below_streak = 0
        self._idle_since: Optional[float] = None
        #: most recent recommend()/apply() output — /healthz's
        #: target-vs-actual readout without re-running the control law
        self.last_recommendation: Optional[dict] = None

    @classmethod
    def from_config(cls, queue, provider, cfg) -> "AutoscaleAdvisor":
        return cls(
            queue,
            provider,
            jobs_per_node=getattr(cfg, "gateway_autoscale_jobs_per_node", 4),
            min_nodes=getattr(cfg, "gateway_autoscale_min_nodes", 0),
            max_nodes=getattr(cfg, "gateway_autoscale_max_nodes", 8),
            apply_enabled=getattr(cfg, "gateway_autoscale_apply", False),
            forecaster=InflowForecaster(
                alpha=getattr(cfg, "fleet_forecast_alpha", 0.3)
            ),
            forecast_horizon_s=getattr(cfg, "fleet_forecast_horizon_s", 30.0),
            scaledown_hysteresis=getattr(
                cfg, "fleet_scaledown_hysteresis", 3
            ),
            scale_to_zero_after_s=getattr(
                cfg, "fleet_scale_to_zero_after_s", 0.0
            ),
        )

    def recommend(self, prefix: str = "node") -> dict:
        """One control-law step against the live queue gauges.

        Reads the world and advances the hysteresis/idle trackers; it
        never touches the provider. Use :meth:`status` for a readout
        that doesn't advance the trackers."""
        now = self._clock()
        depth = self.queue.queue_depth()
        current = len(self.provider.list_nodes(prefix))
        forecast_rate = (
            self.forecaster.rate(now=now) if self.forecaster else 0.0
        )
        forecast_jobs = forecast_rate * self.forecast_horizon_s
        demand = depth + forecast_jobs
        target = min(
            max(math.ceil(demand / self.jobs_per_node), self.min_nodes),
            self.max_nodes,
        )
        scale_to_zero = False
        with self._lock:
            if self.scale_to_zero_after_s > 0.0:
                if depth == 0 and forecast_rate <= 0.0:
                    if self._idle_since is None:
                        self._idle_since = now
                    elif now - self._idle_since >= self.scale_to_zero_after_s:
                        target = 0
                        scale_to_zero = current > 0
                else:
                    self._idle_since = None
            if target < current:
                self._below_streak += 1
                held_down = (
                    not scale_to_zero
                    and self._below_streak < self.scaledown_hysteresis
                )
            else:
                self._below_streak = 0
                held_down = False
        if target > current:
            action = "spin-up"
        elif target < current:
            action = "hold" if held_down else "spin-down"
        else:
            action = "hold"
        rec = {
            "prefix": prefix,
            "queue_depth": depth,
            "forecast_rate": round(forecast_rate, 4),
            "forecast_jobs": round(forecast_jobs, 2),
            "current_nodes": current,
            "target_nodes": target,
            "action": action,
            "scale_to_zero": scale_to_zero,
            "dry_run": not self.apply_enabled,
        }
        FLEET_TARGET.labels().set(target)
        FLEET_FORECAST.labels().set(forecast_rate)
        with self._lock:
            self.last_recommendation = rec
        return rec

    def status(self, prefix: str = "node") -> dict:
        """Target-vs-actual readout for /healthz and `swarm workers`:
        the last recommendation (if any) refreshed with the live node
        count — no control-law state is advanced."""
        current = len(self.provider.list_nodes(prefix))
        with self._lock:
            rec = dict(self.last_recommendation or {})
        rec.setdefault("prefix", prefix)
        rec.setdefault("target_nodes", None)
        rec["current_nodes"] = current
        rec.setdefault("dry_run", not self.apply_enabled)
        return rec

    def apply(self, prefix: str = "node") -> dict:
        """Execute the recommendation (no-op while dry-run).

        Scale-up passes the TARGET, not the delta: ``spin_up(prefix,
        N)`` generates the fixed names ``prefix1..prefixN`` (reference
        naming scheme), so providers ensure-up to N — already-live
        names are skipped, never duplicated. Passing a delta would
        regenerate ``prefix1..prefixΔ`` and collide with the live
        nodes instead of adding new ones."""
        rec = self.recommend(prefix)
        if not self.apply_enabled or rec["action"] == "hold":
            return rec
        if rec["action"] == "spin-up":
            self.provider.spin_up(prefix, rec["target_nodes"])
            FLEET_SCALE_EVENTS.labels(action="spin_up").inc()
        else:
            for i in range(rec["target_nodes"] + 1, rec["current_nodes"] + 1):
                self.provider.teardown_async(f"{prefix}{i}")
            FLEET_SCALE_EVENTS.labels(
                action="scale_to_zero" if rec["scale_to_zero"] else "spin_down"
            ).inc()
        rec["applied"] = True
        with self._lock:
            self.last_recommendation = rec
        return rec


def build_provider(cfg) -> FleetProvider:
    if cfg.fleet_provider == "digitalocean":
        return DigitalOceanProvider(cfg)
    if cfg.fleet_provider == "process":
        return ProcessProvider(cfg)
    if cfg.fleet_provider in ("sim", "simulated"):
        return SimulatedProvider(cfg)
    return NullProvider()
