"""Elastic worker-fleet orchestration behind a provider interface.

The reference hardcodes DigitalOcean droplet create/delete with a
250-req/min thread limiter and idle auto-teardown
(``server/server.py:47-162, 506-546``). Here the same capabilities sit
behind ``FleetProvider``:

- ``NullProvider`` — no-op (TPU pods are typically statically
  provisioned; elastic scale means releasing queued shards, not
  hardware).
- ``ProcessProvider`` — spawns/kills local worker *processes*; the
  embedded single-host analog of a droplet fleet and what tests use.
- ``DigitalOceanProvider`` — wire-equivalent of the reference: same
  API endpoints, name-prefix selection, cloud-init user_data boot.

All providers share the token-bucket rate limiter and run create/delete
in background threads like the reference's ``/spin-up`` handler.
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import sys
import threading
import time
from typing import Optional


class RateLimiter:
    """Token bucket: at most ``per_minute`` acquisitions per rolling minute."""

    def __init__(self, per_minute: int):
        self.per_minute = max(1, per_minute)
        self._lock = threading.Lock()  # guards: _stamps (reads)
        self._stamps: list[float] = []

    def acquire(self) -> None:
        while True:
            with self._lock:
                now = time.time()
                self._stamps = [s for s in self._stamps if now - s < 60.0]
                if len(self._stamps) < self.per_minute:
                    self._stamps.append(now)
                    return
                sleep_for = 60.0 - (now - self._stamps[0])
            time.sleep(max(0.05, sleep_for))


def generate_node_names(prefix: str, nodes: int) -> list[str]:
    """``prefix1..prefixN`` (reference server.py:76-77)."""
    return [f"{prefix}{i}" for i in range(1, nodes + 1)]


class FleetProvider:
    def spin_up(self, prefix: str, nodes: int) -> None:
        raise NotImplementedError

    def spin_down(self, prefix: str) -> None:
        raise NotImplementedError

    def list_nodes(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def teardown_async(self, prefix: str) -> None:
        t = threading.Thread(target=self.spin_down, args=(prefix,), daemon=True)
        t.start()


class NullProvider(FleetProvider):
    def spin_up(self, prefix, nodes):
        pass

    def spin_down(self, prefix):
        pass

    def list_nodes(self, prefix):
        return []


class ProcessProvider(FleetProvider):
    """Local worker processes as fleet nodes (embedded / test provider)."""

    def __init__(self, cfg, extra_args: Optional[list[str]] = None):
        self.cfg = cfg
        self.extra_args = extra_args or []
        self._lock = threading.Lock()  # guards: _procs (reads)
        self._procs: dict[str, subprocess.Popen] = {}

    def spin_up(self, prefix, nodes):
        for name in generate_node_names(prefix, nodes):
            with self._lock:
                if name in self._procs and self._procs[name].poll() is None:
                    continue
                cmd = [
                    sys.executable,
                    "-m",
                    "swarm_tpu.worker",
                    "--server-url",
                    self.cfg.resolve_url(),
                    "--api-key",
                    self.cfg.api_key,
                    "--worker-id",
                    name,
                ] + self.extra_args
                self._procs[name] = subprocess.Popen(
                    cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
                )

    def spin_down(self, prefix):
        with self._lock:
            for name, proc in list(self._procs.items()):
                if name.startswith(prefix) and proc.poll() is None:
                    proc.terminate()
                    self._procs.pop(name, None)

    def list_nodes(self, prefix):
        with self._lock:
            return [
                n
                for n, p in self._procs.items()
                if n.startswith(prefix) and p.poll() is None
            ]

    def shutdown(self):
        self.spin_down("")


class DigitalOceanProvider(FleetProvider):
    """Reference-equivalent cloud provider (requires network egress)."""

    API = "https://api.digitalocean.com/v2"

    def __init__(self, cfg, worker_image: str = "pry0cc/axiom-worker"):
        import requests  # stdlib-adjacent; baked in

        self._requests = requests
        self.cfg = cfg
        self.worker_image = worker_image
        self.limiter = RateLimiter(cfg.fleet_rate_limit_per_min)

    def _headers(self) -> dict:
        return {"Authorization": f"Bearer {self.cfg.fleet_api_token}"}

    def _user_data(self, name: str) -> str:
        env = (
            f"-e SERVER_URL={self.cfg.resolve_url()} -e API_KEY={self.cfg.api_key} "
            f"-e WORKER_ID={name}"
        )
        return f"#cloud-config\nruncmd:\n  - \"docker run -d {env} {self.worker_image}\"\n"

    def _create_one(self, name: str) -> None:
        self.limiter.acquire()
        self._requests.post(
            f"{self.API}/droplets",
            headers=self._headers(),
            json={
                "name": name,
                "region": self.cfg.fleet_region,
                "size": self.cfg.fleet_size,
                "image": self.cfg.fleet_image,
                "user_data": self._user_data(name),
            },
            timeout=30,
        )

    def _delete_one(self, droplet_id: int) -> None:
        self.limiter.acquire()
        self._requests.delete(
            f"{self.API}/droplets/{droplet_id}", headers=self._headers(), timeout=30
        )

    def _droplets(self, prefix: str) -> list[dict]:
        resp = self._requests.get(
            f"{self.API}/droplets?per_page=200", headers=self._headers(), timeout=30
        )
        if resp.status_code != 200:
            return []
        return [
            d
            for d in resp.json().get("droplets", [])
            if d.get("name", "").startswith(prefix)
        ]

    def spin_up(self, prefix, nodes):
        # ensure-up like ProcessProvider: DO allows duplicate droplet
        # names, so re-creating a live name would double-bill and
        # corrupt list_nodes/scale-down arithmetic — skip names that
        # already exist (one listing call per spin_up)
        live = set(self.list_nodes(prefix))
        threads = [
            threading.Thread(target=self._create_one, args=(n,), daemon=True)
            for n in generate_node_names(prefix, nodes)
            if n not in live
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def spin_down(self, prefix):
        droplets = self._droplets(prefix)
        threads = [
            threading.Thread(target=self._delete_one, args=(d["id"],), daemon=True)
            for d in droplets
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def list_nodes(self, prefix):
        return [d["name"] for d in self._droplets(prefix)]


class AutoscaleAdvisor:
    """Queue-depth-driven worker autoscaling (docs/GATEWAY.md).

    Closes the control loop the PR 1 gauges opened: the recommendation
    is a pure function of queue depth (``swarm_queue_depth``'s source)
    against a target waiting-jobs-per-node ratio, clamped to
    ``[min_nodes, max_nodes]``. DRY-RUN BY DEFAULT — ``recommend()``
    only reads; ``apply()`` touches the provider exclusively when the
    operator set ``gateway_autoscale_apply`` (scale-down tears down the
    highest-numbered nodes by name, matching ``generate_node_names``'s
    ``prefix1..prefixN`` scheme)."""

    def __init__(
        self,
        queue,
        provider: FleetProvider,
        jobs_per_node: int = 4,
        min_nodes: int = 0,
        max_nodes: int = 8,
        apply_enabled: bool = False,
    ):
        self.queue = queue
        self.provider = provider
        self.jobs_per_node = max(1, int(jobs_per_node))
        self.min_nodes = max(0, int(min_nodes))
        self.max_nodes = max(self.min_nodes, int(max_nodes))
        self.apply_enabled = bool(apply_enabled)

    @classmethod
    def from_config(cls, queue, provider, cfg) -> "AutoscaleAdvisor":
        return cls(
            queue,
            provider,
            jobs_per_node=getattr(cfg, "gateway_autoscale_jobs_per_node", 4),
            min_nodes=getattr(cfg, "gateway_autoscale_min_nodes", 0),
            max_nodes=getattr(cfg, "gateway_autoscale_max_nodes", 8),
            apply_enabled=getattr(cfg, "gateway_autoscale_apply", False),
        )

    def recommend(self, prefix: str = "node") -> dict:
        """Read-only recommendation against the live queue gauges."""
        import math

        depth = self.queue.queue_depth()
        current = len(self.provider.list_nodes(prefix))
        target = min(
            max(math.ceil(depth / self.jobs_per_node), self.min_nodes),
            self.max_nodes,
        )
        if target > current:
            action = "spin-up"
        elif target < current:
            action = "spin-down"
        else:
            action = "hold"
        return {
            "prefix": prefix,
            "queue_depth": depth,
            "current_nodes": current,
            "target_nodes": target,
            "action": action,
            "dry_run": not self.apply_enabled,
        }

    def apply(self, prefix: str = "node") -> dict:
        """Execute the recommendation (no-op while dry-run).

        Scale-up passes the TARGET, not the delta: ``spin_up(prefix,
        N)`` generates the fixed names ``prefix1..prefixN`` (reference
        naming scheme), so providers ensure-up to N — already-live
        names are skipped, never duplicated. Passing a delta would
        regenerate ``prefix1..prefixΔ`` and collide with the live
        nodes instead of adding new ones."""
        rec = self.recommend(prefix)
        if not self.apply_enabled or rec["action"] == "hold":
            return rec
        if rec["action"] == "spin-up":
            self.provider.spin_up(prefix, rec["target_nodes"])
        else:
            for i in range(rec["target_nodes"] + 1, rec["current_nodes"] + 1):
                self.provider.teardown_async(f"{prefix}{i}")
        rec["applied"] = True
        return rec


def build_provider(cfg) -> FleetProvider:
    if cfg.fleet_provider == "digitalocean":
        return DigitalOceanProvider(cfg)
    if cfg.fleet_provider == "process":
        return ProcessProvider(cfg)
    return NullProvider()
