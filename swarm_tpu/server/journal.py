"""Durable queue journal: write-ahead log + snapshots in the blob store.

The control plane's job table, tenant dispatch lists, attempt counts,
dead-letter history and RR cursor live in a ``MemoryStateStore`` by
default — a ``kill -9`` on the server used to orphan every queued and
in-flight job while workers kept scanning into the void (the reference
lost state the same way; PR 4 only made the *worker* side durable).
This module is the server-side fix (docs/DURABILITY.md):

- **Append-only WAL segments**: every queue mutation is serialized as
  one JSON record and written — *before* the state store is touched,
  and therefore before the client's 200 — as a segment blob
  ``_journal/seg/<seq>.jsonl``. Blob puts are crash-atomic
  (``LocalBlobStore`` writes temp + rename), so a segment either
  exists whole or not at all; an admitted job is never unjournaled.
- **Snapshots**: a checkpoint folds the full queue state into
  ``_journal/snap/<seq>.json`` and prunes the segments it covers.
  Replay = latest snapshot + segments with a later sequence number.
  A crash between the snapshot write and the prune leaves stale
  segments behind; the sequence filter skips them, so compaction is
  crash-safe at every step.
- **Generation**: ``_journal/generation`` holds a monotonic counter
  bumped once per journal-enabled boot. It rides the
  ``X-Swarm-Generation`` header so workers can tell "the server I'm
  talking to forgot nothing" from "the control plane restarted and
  recovered" (worker re-registration, docs/DURABILITY.md).

The journal deliberately uses the *existing* store roles: on the
embedded deployment it lands next to the chunk blobs on disk; on S3 it
is just more keys in the bucket. One writer at a time is assumed — the
single C2 server process — which is the same assumption the dispatch
lock already makes.
"""

from __future__ import annotations

import json
import threading
from typing import Iterator, Optional

from swarm_tpu.resilience.faults import fault_point
from swarm_tpu.stores import BlobStore
from swarm_tpu.telemetry.journal_export import (
    JOURNAL_APPENDS,
    JOURNAL_COMPACTIONS,
    JOURNAL_CORRUPT,
    JOURNAL_SEGMENTS,
)

#: zero-padded sequence width: blob listings sort lexically, so the
#: numeric replay order must survive string sorting
_SEQ_DIGITS = 12


class JournalError(RuntimeError):
    """A journal append/replay/compact failure. On the append path the
    caller (queue service) lets it propagate: the route 500s and the
    client retries — an unjournaled mutation is never acked."""


class QueueJournal:
    """Write-ahead journal over a :class:`BlobStore`.

    Thread-safe: sequence allocation and checkpoint bookkeeping run
    under one lock; the blob writes themselves happen outside it
    (distinct keys — replay order is the *sequence* order, which is
    assigned under the lock, and per-job mutation order is already
    serialized by the queue's dispatch lock).
    """

    PREFIX = "_journal"

    def __init__(
        self,
        blobs: BlobStore,
        prefix: str = PREFIX,
        compact_segments: int = 512,
    ):
        self.blobs = blobs
        self.prefix = prefix.rstrip("/")
        self.compact_segments = max(2, int(compact_segments))
        self._lock = threading.Lock()  # guards: _next_seq, _snap_seq, _segments
        # boot-time discovery: resume the sequence after the highest
        # existing segment/snapshot so a restarted writer never reuses
        # (and silently shadows) a predecessor's sequence number
        snap_seq = self._latest_snapshot_seq()
        seg_seqs = self._segment_seqs()
        self._snap_seq = snap_seq  # guarded-by: _lock
        self._segments = len([s for s in seg_seqs if s > (snap_seq or -1)])  # guarded-by: _lock
        self._next_seq = max([snap_seq or 0] + seg_seqs + [0]) + 1  # guarded-by: _lock
        JOURNAL_SEGMENTS.set(self._segments)

    # ------------------------------------------------------------------
    # Key layout
    # ------------------------------------------------------------------
    def _seg_key(self, seq: int) -> str:
        return f"{self.prefix}/seg/{seq:0{_SEQ_DIGITS}d}.jsonl"

    def _snap_key(self, seq: int) -> str:
        return f"{self.prefix}/snap/{seq:0{_SEQ_DIGITS}d}.json"

    @property
    def _gen_key(self) -> str:
        return f"{self.prefix}/generation"

    @staticmethod
    def _seq_of(key: str) -> Optional[int]:
        stem = key.rsplit("/", 1)[-1].split(".", 1)[0]
        try:
            return int(stem)
        except ValueError:
            return None

    def _segment_seqs(self) -> list[int]:
        return sorted(
            s
            for s in (
                self._seq_of(k) for k in self.blobs.list(f"{self.prefix}/seg/")
            )
            if s is not None
        )

    def _latest_snapshot_seq(self) -> Optional[int]:
        seqs = [
            s
            for s in (
                self._seq_of(k) for k in self.blobs.list(f"{self.prefix}/snap/")
            )
            if s is not None
        ]
        return max(seqs) if seqs else None

    # ------------------------------------------------------------------
    # Write path
    # ------------------------------------------------------------------
    def append(self, record: dict) -> None:
        self.append_many([record])

    def append_many(self, records: list[dict]) -> None:
        """Persist one WAL segment holding ``records`` (in order).

        Ordering invariant (append-before-ack): callers invoke this
        BEFORE mutating the state store, so the journal is always a
        superset of the store and a crash at any point leaves either
        "mutation journaled" or "mutation never happened" — never a
        stored-but-unjournaled job. A failure raises (wrapped as
        :class:`JournalError` unless it already is one) and the caller
        must NOT apply the mutation.
        """
        if not records:
            return
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
        data = b"".join(
            json.dumps(r, separators=(",", ":")).encode() + b"\n"
            for r in records
        )
        try:
            # chaos lever (docs/RESILIENCE.md): a failing append must
            # surface as a 500 from the mutating route, never as a
            # silently-acked-but-unjournaled mutation
            fault_point("journal.append", detail=records[0].get("op"))
            self.blobs.put(self._seg_key(seq), data)
        except Exception as e:
            raise JournalError(f"journal append failed: {e}") from e
        with self._lock:
            self._segments += 1
            segments = self._segments
        for r in records:
            JOURNAL_APPENDS.labels(op=str(r.get("op") or "job")).inc()
        JOURNAL_SEGMENTS.set(segments)

    # ------------------------------------------------------------------
    # Replay path
    # ------------------------------------------------------------------
    def has_state(self) -> bool:
        """True when a snapshot or any WAL segment exists."""
        return (
            self._latest_snapshot_seq() is not None
            or bool(self._segment_seqs())
        )

    def replay(self) -> tuple[Optional[dict], Iterator[dict]]:
        """Return ``(snapshot, records)``: the latest snapshot payload
        (or None) and an iterator over every WAL record with a sequence
        number past it, in append order. Unparseable records are
        counted (``swarm_journal_corrupt_records_total``) and skipped —
        see the corrupt-journal runbook in docs/DURABILITY.md."""
        fault_point("journal.replay")
        snap_seq = self._latest_snapshot_seq()
        snapshot: Optional[dict] = None
        if snap_seq is not None:
            try:
                snapshot = json.loads(self.blobs.get(self._snap_key(snap_seq)))
            except (ValueError, KeyError, FileNotFoundError, OSError):
                # damaged snapshot: fall back to full-WAL replay of
                # whatever segments survive (runbook case)
                JOURNAL_CORRUPT.inc()
                snapshot = None
                snap_seq = None

        def _records() -> Iterator[dict]:
            for seq in self._segment_seqs():
                if snap_seq is not None and seq <= snap_seq:
                    continue  # compaction crashed before the prune
                try:
                    raw = self.blobs.get(self._seg_key(seq))
                except (KeyError, FileNotFoundError, OSError):
                    continue
                for line in raw.splitlines():
                    if not line.strip():
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        JOURNAL_CORRUPT.inc()
                        continue
                    if isinstance(rec, dict):
                        yield rec
                    else:
                        JOURNAL_CORRUPT.inc()

        return snapshot, _records()

    # ------------------------------------------------------------------
    # Checkpoint / compaction
    # ------------------------------------------------------------------
    @property
    def segments_pending(self) -> int:
        with self._lock:
            return self._segments

    # orders: blobs.put < blobs.delete (snapshot durably lands before the segments it covers are pruned)
    def checkpoint(self, state: dict) -> int:
        """Fold ``state`` (the full queue state, journal-format) into a
        snapshot and prune the WAL segments it covers. Crash-safe:
        snapshot first, prune after — leftovers are skipped by replay's
        sequence filter. Returns the snapshot's sequence number."""
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
        try:
            fault_point("journal.compact")
            self.blobs.put(
                self._snap_key(seq),
                json.dumps(state, separators=(",", ":")).encode(),
            )
        except Exception as e:
            raise JournalError(f"journal checkpoint failed: {e}") from e
        JOURNAL_APPENDS.labels(op="checkpoint").inc()
        # prune: segments covered by the new snapshot, then superseded
        # snapshots (best-effort — a failure here only leaves garbage
        # that the next successful checkpoint removes)
        for s in self._segment_seqs():
            if s < seq:
                self.blobs.delete(self._seg_key(s))
        for key in self.blobs.list(f"{self.prefix}/snap/"):
            s = self._seq_of(key)
            if s is not None and s < seq:
                self.blobs.delete(self._snap_key(s))
        with self._lock:
            self._snap_seq = seq
            self._segments = 0
        JOURNAL_COMPACTIONS.inc()
        JOURNAL_SEGMENTS.set(0)
        return seq

    # ------------------------------------------------------------------
    # Generation
    # ------------------------------------------------------------------
    def generation(self) -> int:
        try:
            return int(self.blobs.get(self._gen_key).decode().strip())
        except (KeyError, FileNotFoundError, OSError, ValueError):
            return 0

    def bump_generation(self) -> int:
        """Advance the monotonic server generation (once per boot).
        Single-writer by assumption: exactly one C2 server owns a
        journal prefix at a time."""
        gen = self.generation() + 1
        self.blobs.put(self._gen_key, str(gen).encode())
        return gen

    # ------------------------------------------------------------------
    def clear(self) -> None:
        """Drop every segment and snapshot (``/reset``). The generation
        counter survives — resets are operational events, not new
        server identities."""
        for key in self.blobs.list(f"{self.prefix}/seg/") + self.blobs.list(
            f"{self.prefix}/snap/"
        ):
            self.blobs.delete(key)
        with self._lock:
            self._snap_seq = None
            self._segments = 0
        JOURNAL_SEGMENTS.set(0)
