from swarm_tpu.server.app import main

main()
