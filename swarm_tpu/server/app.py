"""HTTP REST C2 server — wire-compatible with the reference API.

Same 11 routes, methods, payload shapes, status codes and bearer-token
auth as reference ``server/server.py`` (so the reference client/worker
work unchanged), built on the stdlib threading HTTP server instead of
Flask (not in this image). Additive routes let workers move chunk data
over HTTP instead of needing direct S3 credentials:

    GET  /get-input-chunk/<scan>/<chunk>     (reference worker hits S3)
    POST /put-output-chunk/<scan>/<chunk>
    GET  /healthz                            (unauthenticated liveness:
                                              uptime, queue depth,
                                              jobs by state)
    GET  /metrics                            (unauthenticated Prometheus
                                              text exposition)
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional
from urllib.parse import parse_qs, urlparse

from swarm_tpu.config import Config
from swarm_tpu.datamodel import (
    SCAN_ID_RE,
    JobStatus,
    chunk_generator,
    chunk_output_key,
)
from swarm_tpu.gateway.admission import (
    DEFAULT_TENANT,
    AdmissionController,
    PressureSnapshot,
)
from swarm_tpu.gateway.qos import QOS_HEADER, QOS_INTERACTIVE, parse_qos
from swarm_tpu.gateway.qoscache import build_gateway_cache
from swarm_tpu.gateway.streaming import stream_scan
from swarm_tpu.monitor.feed import feed_prefix, stream_feed
from swarm_tpu.monitor.service import MonitorService
from swarm_tpu.monitor.spec import MONITOR_ID_RE, MonitorSpec
from swarm_tpu.server.fleet import AutoscaleAdvisor, build_provider
from swarm_tpu.server.queue import JobQueueService
from swarm_tpu.stores import build_stores
from swarm_tpu.telemetry import REGISTRY
from swarm_tpu.telemetry import tracing
from swarm_tpu.telemetry.events import emit_event, header_trace_id, new_trace_id
from swarm_tpu.telemetry.gateway_export import (
    GATEWAY_LATENCY,
    GATEWAY_QUEUED,
    GATEWAY_SHORT_CIRCUIT,
)
from swarm_tpu.telemetry.metrics import CONTENT_TYPE as _METRICS_CTYPE

_HTTP_REQUESTS = REGISTRY.counter(
    "swarm_http_requests_total",
    "HTTP requests handled by the C2 server",
    ("route", "method", "code"),
)
_HTTP_LATENCY = REGISTRY.histogram(
    "swarm_http_request_seconds",
    "C2 server request handling latency",
    ("route",),
)
_QUEUE_DEPTH = REGISTRY.gauge(
    "swarm_queue_depth", "Jobs waiting in the dispatch queue"
)
_JOBS_BY_STATE = REGISTRY.gauge(
    "swarm_jobs_by_state", "Job records by current status", ("status",)
)
_UPTIME = REGISTRY.gauge(
    "swarm_server_uptime_seconds", "Seconds since the C2 server started"
)


class SwarmServer:
    """Route table + dispatch. Handlers return (status, body, content_type)."""

    def __init__(self, cfg: Config, queue: Optional[JobQueueService] = None, fleet=None):
        self.cfg = cfg
        self.started_at = time.time()
        from swarm_tpu.resilience.faults import active_plan, install_plan

        if cfg.fault_plan:
            install_plan(cfg.fault_plan)  # deterministic chaos (tests/soak)
        else:
            active_plan()  # registers the armed-state gauge for /metrics
        # span tracing (docs/OBSERVABILITY.md §Tracing): config can arm
        # it process-wide but never forces it OFF — an operator's
        # SWARM_TRACE=1 env wins over an unset config field
        if cfg.trace_enabled:
            tracing.set_enabled(True)
        # see _advertise_url: captured before any bind mutates it. A URL
        # a PRIOR server instance derived (cfg.server_url_derived) still
        # counts as defaulted — a supervisor reusing one Config across
        # restarts must get a fresh alignment, not the dead previous
        # port advertised as operator-explicit.
        self._url_was_default = (
            cfg.server_url == Config.server_url or cfg.server_url_derived
        )
        if queue is None:
            state, blobs, docs = build_stores(cfg)
            # flight-recorder persistence (docs/OBSERVABILITY.md
            # §Tracing): the sink must attach BEFORE the queue is
            # constructed — journal recovery fires its flight dump
            # from inside JobQueueService.__init__, and a sink attached
            # after would miss exactly the dump that motivates
            # persisting the ring
            self._flight_unsub = tracing.FLIGHT.add_sink(
                tracing.blob_flight_sink(blobs)
            )
            fleet = fleet if fleet is not None else build_provider(cfg)
            queue = JobQueueService(cfg, state, blobs, docs, fleet=fleet)
        else:
            self._flight_unsub = tracing.FLIGHT.add_sink(
                tracing.blob_flight_sink(queue.blobs)
            )
        self.queue = queue
        self.fleet = fleet if fleet is not None else queue.fleet
        # multi-tenant front door (docs/GATEWAY.md): admission control
        # + the queue-depth-driven autoscale advisor (dry-run default)
        self.gateway = AdmissionController.from_config(cfg)
        self.autoscaler = AutoscaleAdvisor.from_config(
            self.queue, self.fleet, cfg
        )
        # preemption notices close the loop (docs/RESILIENCE.md
        # §Preemption): a provider that issues them (SimulatedProvider)
        # drains the doomed worker server-side, so dispatch stops
        # offering it jobs the moment the notice lands — the worker
        # itself learns via the X-Swarm-Drain header on its next poll
        if (
            hasattr(self.fleet, "on_preempt_notice")
            and self.fleet.on_preempt_notice is None
        ):
            queue_ref = self.queue
            self.fleet.on_preempt_notice = (
                lambda name: queue_ref.drain_worker(name, reason="preempted")
            )
        # the post-grace force-kill deregisters the name authoritatively:
        # leases requeue NOW (not at lease expiry) and the drain entry
        # clears, so a replacement node reusing the name starts clean
        # even when the killed worker was too wedged to drain itself
        if (
            hasattr(self.fleet, "on_kill")
            and self.fleet.on_kill is None
        ):
            queue_ref = self.queue
            self.fleet.on_kill = (
                lambda name: queue_ref.deregister_worker(name)
            )
        # gateway-tier result cache (docs/GATEWAY.md §QoS): interactive
        # submissions whose chunks are fleet-known complete HERE with
        # zero worker dispatch. None (the default: cache_backend=off)
        # keeps the submit path byte-identical; a backend that can't be
        # built must not kill the server — the cache is an accelerator,
        # never a dependency.
        self.qos_cache = None
        try:
            self.qos_cache = build_gateway_cache(cfg)
        except Exception as e:
            print(f"gateway scan cache unavailable ({e}); pass-through")
        # continuous monitoring (docs/MONITORING.md): the ticker thread
        # is server-lifecycle-owned; the DURABLE spec registry lives in
        # the queue (journaled). The verdict-plane store shares the
        # gateway cache's tier instance so both views of the shared
        # tier agree within this process; with no tier it degrades to
        # rebuilding planes from the change feed.
        self.monitor: Optional[MonitorService] = None
        if getattr(cfg, "monitor_enabled", True):
            tier = (
                self.qos_cache._tier if self.qos_cache is not None else None
            )
            if tier is None:
                try:
                    from swarm_tpu.cache.tier import build_tier

                    tier = build_tier(cfg)
                except Exception:
                    tier = None
            self.monitor = MonitorService(
                self.queue, cfg, submit=self._submit_monitor_epoch, tier=tier
            )
            self.monitor.start()
        self._routes: list[tuple[str, re.Pattern, Callable, str]] = []
        self._register_routes()
        self._httpd: Optional[ThreadingHTTPServer] = None
        # scrape-time queue gauges: depth + jobs-by-state read from the
        # state store only when /metrics (or snapshot()) renders, never
        # on the dispatch hot path. Weakref'd so servers a test drops
        # without shutdown() don't stay scrapable forever; removed
        # explicitly on shutdown.
        self._seen_states: set[str] = set()
        self._seen_tenants: set[str] = set()
        import weakref

        ref = weakref.ref(self)

        def _collector() -> None:
            srv = ref()
            if srv is not None:
                srv._collect_queue_gauges()

        self._collector = _collector
        REGISTRY.add_collector(self._collector)

    def _collect_queue_gauges(self) -> None:
        _UPTIME.set(time.time() - self.started_at)
        _QUEUE_DEPTH.set(self.queue.queue_depth())
        counts = self.queue.jobs_by_state()
        for status in self._seen_states - set(counts):
            _JOBS_BY_STATE.labels(status=status).set(0)
        for status, n in counts.items():
            _JOBS_BY_STATE.labels(status=status).set(n)
        self._seen_states |= set(counts)
        depths = self.queue.tenant_depths()
        for tenant in self._seen_tenants - set(depths):
            GATEWAY_QUEUED.labels(tenant=tenant).set(0)
        for tenant, n in depths.items():
            GATEWAY_QUEUED.labels(tenant=tenant).set(n)
        self._seen_tenants |= set(depths)

    # ------------------------------------------------------------------
    def _register_routes(self) -> None:
        def r(method, pattern, handler, name):
            self._routes.append((method, re.compile(pattern), handler, name))

        r("GET", r"^/healthz$", self._healthz, "/healthz")
        r("GET", r"^/metrics$", self._metrics, "/metrics")
        r("GET", r"^/get-statuses$", self._get_statuses, "/get-statuses")
        r("POST", r"^/update-job/(?P<job_id>[^/]+)$", self._update_job, "/update-job")
        r("POST", r"^/renew-lease/(?P<job_id>[^/]+)$", self._renew_lease, "/renew-lease")
        r("GET", r"^/dead-letter$", self._dead_letter, "/dead-letter")
        r("POST", r"^/requeue-job/(?P<job_id>[^/]+)$", self._requeue_job, "/requeue-job")
        r("GET", r"^/get-chunk/(?P<scan_id>[^/]+)/(?P<chunk_id>[^/]+)$", self._get_chunk, "/get-chunk")
        r("GET", r"^/get-latest-chunk$", self._get_latest_chunk, "/get-latest-chunk")
        r("GET", r"^/parse_job/(?P<job_id>[^/]+)$", self._parse_job, "/parse_job")
        r("GET", r"^/raw/(?P<scan_id>[^/]+)$", self._raw, "/raw")
        r("POST", r"^/queue$", self._queue_job, "/queue")
        r("GET", r"^/get-job$", self._get_job, "/get-job")
        r("POST", r"^/spans$", self._post_spans, "/spans")
        r("GET", r"^/trace/(?P<scan_id>[^/]+)$", self._get_trace, "/trace")
        r("GET", r"^/stream/(?P<scan_id>[^/]+)$", self._stream, "/stream")
        r("POST", r"^/monitor$", self._monitor_post, "/monitor")
        r("GET", r"^/monitor$", self._monitor_list, "/monitor")
        r("POST", r"^/monitor/(?P<monitor_id>[^/]+)$", self._monitor_update, "/monitor-update")
        r("GET", r"^/monitor-feed/(?P<monitor_id>[^/]+)$", self._monitor_feed, "/monitor-feed")
        r("GET", r"^/tenants$", self._tenants, "/tenants")
        r("GET", r"^/autoscale$", self._autoscale_recommend, "/autoscale")
        r("POST", r"^/autoscale$", self._autoscale_apply, "/autoscale")
        r("POST", r"^/spin-up$", self._spin_up, "/spin-up")
        r("POST", r"^/spin-down$", self._spin_down, "/spin-down")
        r("POST", r"^/drain/(?P<worker_id>[^/]+)$", self._drain_worker, "/drain")
        r("POST", r"^/deregister$", self._deregister, "/deregister")
        r("POST", r"^/reset$", self._reset, "/reset")
        r("GET", r"^/get-input-chunk/(?P<scan_id>[^/]+)/(?P<chunk_id>[^/]+)$", self._get_input_chunk, "/get-input-chunk")
        r("POST", r"^/put-output-chunk/(?P<scan_id>[^/]+)/(?P<chunk_id>[^/]+)$", self._put_output_chunk, "/put-output-chunk")

    # ------------------------------------------------------------------
    # Handlers — signatures:
    #   (match, query, body_bytes, headers) -> (code, body, ctype)
    # ------------------------------------------------------------------
    @staticmethod
    def _json(code: int, payload: Any) -> tuple[int, bytes, str]:
        return code, json.dumps(payload).encode(), "application/json"

    @staticmethod
    def _text(code: int, text: str) -> tuple[int, bytes, str]:
        return code, text.encode(), "text/html; charset=utf-8"

    def _healthz(self, m, q, body, h):
        # real liveness, not a static ok: load balancers and tests can
        # assert the queue is actually reachable behind this process.
        # Resilience surface (docs/RESILIENCE.md): dead-letter count and
        # in-process breaker states show degradation without Prometheus.
        from swarm_tpu.resilience.breaker import breaker_states
        from swarm_tpu.resilience.faults import active_plan

        by_state = self.queue.jobs_by_state()
        plan = active_plan()
        snap = self._pressure_snapshot()
        return self._json(
            200,
            {
                "status": "ok",
                "uptime_seconds": round(time.time() - self.started_at, 3),
                "queue_depth": self.queue.queue_depth(),
                "jobs_by_state": by_state,
                "dead_letter_jobs": by_state.get(JobStatus.DEAD_LETTER, 0),
                "breakers": breaker_states(),
                "fault_plan": plan.spec if plan is not None else "",
                # gateway surface (docs/GATEWAY.md): load shed starts
                # at pressure >= gateway_shed_pressure. COUNT only —
                # tenant ids are client data and this endpoint is
                # unauthenticated; the id list lives on authenticated
                # GET /tenants
                "pressure": round(self.gateway.pressure(snap), 4),
                "tenant_count": len(self.queue.tenants()),
                # durability surface (docs/DURABILITY.md): the
                # monotonic control-plane generation (0 = journal off)
                # and what boot-time recovery materialized, so "did the
                # restart lose anything" is one curl away
                "generation": self.queue.generation,
                "recovery": self.queue.recovery_summary,
                # elastic-fleet surface (docs/RESILIENCE.md
                # §Preemption): the advisor's last target vs the
                # provider's actual node count, plus which workers are
                # mid-drain — COUNTS and the advisor dict only, no
                # tenant ids (unauthenticated endpoint)
                "autoscale": self.autoscaler.status(),
                "draining_workers": len(self.queue.draining_workers()),
            },
        )

    def _renew_lease(self, m, q, body, h):
        try:
            data = json.loads(body or b"{}")
        except ValueError:
            return self._json(400, {"message": "Invalid JSON"})
        # heartbeats double as the saturation feed: a worker whose
        # scheduler is stalling on a full in-flight window says so here,
        # and admission pressure rises BEFORE the queue does
        if data.get("worker_id") and "saturation" in data:
            self.gateway.note_saturation(
                data["worker_id"], data.get("saturation")
            )
        expiry = self.queue.renew_lease(m["job_id"], data.get("worker_id"))
        if expiry is None:
            # the lease is not this worker's to renew (requeued,
            # re-leased, terminal, or unknown job)
            return self._json(409, {"message": "Lease not renewable"})
        return self._json(200, {"lease_expires_at": expiry})

    def _dead_letter(self, m, q, body, h):
        return self._json(200, {"jobs": self.queue.dead_letter_jobs()})

    def _requeue_job(self, m, q, body, h):
        if self.queue.requeue_dead_letter(m["job_id"]):
            return self._json(200, {"message": "Job requeued"})
        return self._json(404, {"message": "Job not in dead-letter"})

    def _metrics(self, m, q, body, h):
        return 200, REGISTRY.render().encode(), _METRICS_CTYPE

    def _get_statuses(self, m, q, body, h):
        return self._json(200, self.queue.statuses())

    def _update_job(self, m, q, body, h):
        try:
            changes = json.loads(body or b"{}")
        except ValueError:
            return self._json(400, {"message": "Invalid JSON"})
        self._note_perf_saturation(changes)
        if (
            self.qos_cache is not None
            and changes.get("status") == JobStatus.COMPLETE
        ):
            # cache BEFORE the status flip becomes visible: a client
            # that observes "scan complete" and immediately re-submits
            # the content must hit (writeback-after-update left a
            # window where complete-but-uncached raced the re-submit).
            # The output chunk is already durable (the worker uploads
            # before posting COMPLETE), and the chunk store is
            # idempotent by content — caching bytes for an update that
            # then gets fenced stores exactly what /raw serves anyway.
            self._qos_cache_writeback(m["job_id"])
        if self.queue.update_job(m["job_id"], changes):
            return self._json(200, {"message": "Job status updated"})
        return self._json(404, {"message": "Job not found"})

    def _qos_cache_writeback(self, job_id: str) -> None:
        """Feed the gateway-tier cache from a freshly completed chunk
        (docs/GATEWAY.md §QoS): small chunks — interactive probes and
        bulk trickles up to ``qos_cache_max_rows`` target lines — are
        stored under their ``(module, lines)`` content key so a later
        identical interactive submission short-circuits at the gateway.
        Best-effort by design: a failed writeback costs one future
        device round trip, never the 200 this route will earn."""
        max_rows = int(getattr(self.cfg, "qos_cache_max_rows", 0))
        if max_rows <= 0:
            return
        try:
            rec = self.queue.job_record(job_id)
            if rec is None:
                return
            # size fast-path off the job record (queue_scan stamps
            # chunk_rows): a bulk flood's big chunks skip the blob
            # read entirely on this status hot path
            known_rows = rec.get("chunk_rows")
            if isinstance(known_rows, int) and known_rows > max_rows:
                return
            scan_id, chunk_index = rec["scan_id"], int(rec["chunk_index"])
            data = self.queue.input_chunk(scan_id, chunk_index)
            if data is None:
                return
            # size-bail on the raw bytes BEFORE decoding: this hook
            # rides every completed chunk's status POST, and a bulk
            # flood's big chunks must pay a byte count, not a full
            # decode, to learn they're over the bound
            if data.count(b"\n") + 1 > max_rows:
                return
            # the exact inverse of queue_scan's '\n'.join — NOT
            # splitlines(), which also splits on \x0b / \x1c /
            # U+2028 etc. and would alias a one-weird-line chunk's
            # digest with an honest N-line submission's
            lines = data.decode("utf-8", "surrogateescape").split("\n")
            if not any(lines) or len(lines) > max_rows:
                return
            output = self.queue.blobs.get(
                chunk_output_key(scan_id, chunk_index)
            )
            stored = self.qos_cache.writeback(rec["module"], lines, output)
            # trace_id rides the writeback event (satellite: the cache
            # entries a short-circuit later answers from are traceable
            # back to the scan that fed them)
            emit_event(
                "cache.writeback",
                trace_id=rec.get("trace_id"),
                job_id=job_id,
                scan_id=scan_id,
                chunk_index=chunk_index,
                module=rec["module"],
                stored=bool(stored),
            )
            tracing.flight_event(
                "cache.writeback", trace_id=rec.get("trace_id"),
                job_id=job_id, stored=bool(stored),
            )
        except Exception as e:
            print(f"gateway cache writeback skipped for {job_id}: {e}")

    def _note_perf_saturation(self, changes: dict) -> None:
        """Fold a completed job's perf fields into the admission
        pressure signal: the worker's explicit ``inflight_saturation``
        when present, else the scheduler snapshot's stall/wall ratio
        (stall = the submit thread waited on a FULL in-flight window —
        i.e. the accelerator is saturated)."""
        worker_id = changes.get("worker_id")
        perf = changes.get("perf")
        if not worker_id or not isinstance(perf, dict):
            return
        saturation = perf.get("inflight_saturation")
        if saturation is None:
            sched = perf.get("sched")
            if isinstance(sched, dict):
                wall = sched.get("wall_seconds")
                stall = sched.get("stall_seconds")
                if (
                    isinstance(wall, (int, float))
                    and isinstance(stall, (int, float))
                    and wall > 0
                ):
                    saturation = stall / wall
        if saturation is not None:
            self.gateway.note_saturation(worker_id, saturation)

    def _post_spans(self, m, q, body, h):
        """Mid-scan span shipping (docs/OBSERVABILITY.md §Tracing): a
        worker whose attempt outgrows the perf-field batch bound posts
        ``{"scan_id": ..., "spans": [...]}`` here instead. Spans for a
        scan the assembler isn't holding are counted as dropped and
        still 200 — tracing is telemetry, not control flow."""
        try:
            data = json.loads(body or b"{}")
        except ValueError:
            return self._json(400, {"message": "Invalid JSON"})
        scan_id = data.get("scan_id")
        spans = data.get("spans")
        if not scan_id or not SCAN_ID_RE.match(str(scan_id)) or not isinstance(
            spans, list
        ):
            return self._json(
                400, {"message": "scan_id and spans list required"}
            )
        added = self.queue.tracer.add_spans(str(scan_id), spans)
        return self._json(200, {"added": added})

    def _get_trace(self, m, q, body, h):
        """One scan's assembled latency waterfall (memory, blob store,
        or a live partial view of a still-running scan)."""
        scan_id = m["scan_id"]
        if not SCAN_ID_RE.match(scan_id):
            return self._json(400, {"message": "Invalid scan_id"})
        doc = self.queue.tracer.get(scan_id)
        if doc is None:
            return self._json(404, {"message": "No trace for scan"})
        return self._json(200, doc)

    def _get_chunk(self, m, q, body, h):
        content = self.queue.output_chunk(m["scan_id"], int(m["chunk_id"]))
        if content is None:
            return self._json(404, {"message": "Chunk not found"})
        return self._json(200, {"contents": content})

    def _get_latest_chunk(self, m, q, body, h):
        job_id = self.queue.latest_completed_job_id()
        if job_id is None:
            return self._text(204, "")
        return self._text(200, job_id)

    def _parse_job(self, m, q, body, h):
        if self.queue.parse_job(m["job_id"]):
            return self._json(200, {"message": "Job parsed and inserted into mongodb"})
        return self._json(404, {"message": "Job not found"})

    def _raw(self, m, q, body, h):
        return self._text(200, self.queue.raw_scan(m["scan_id"]))

    @staticmethod
    def _header(h: dict, name: str) -> Optional[str]:
        """Case-insensitive header lookup (clients vary in casing)."""
        lname = name.lower()
        for key, value in h.items():
            if key.lower() == lname:
                return value
        return None

    def _pressure_snapshot(self) -> PressureSnapshot:
        """One deterministic observation of the serving tier's load —
        the sole dynamic input of a shed decision (docs/GATEWAY.md)."""
        from swarm_tpu.resilience.breaker import breaker_states

        by_state = self.queue.jobs_by_state()  # probe-storm-cached
        active = sum(
            n for status, n in by_state.items() if status in JobStatus.ACTIVE
        )
        open_breakers = sum(
            1 for state in breaker_states().values() if state != "closed"
        )
        # queue_depth is one llen PER TENANT LIST — only pay for it
        # when the depth component is actually enabled (queue_high 0,
        # the default, disables it); the admission hot path must not
        # scale with tenant count
        depth = (
            self.queue.queue_depth() if self.gateway.queue_high > 0
            else by_state.get(JobStatus.QUEUED, 0)
        )
        return PressureSnapshot(
            queue_depth=depth,
            active_jobs=active,
            saturation=self.gateway.fleet_saturation(),
            open_breakers=open_breakers,
        )

    def _admission_decision(self, tenant: str, qos: Optional[str] = None):
        return self.gateway.decide(
            tenant,
            self._pressure_snapshot(),
            time.monotonic(),
            tenant_depth=self.queue.tenant_depth(tenant),
            qos=qos,
        )

    @staticmethod
    def _shed_response(decision) -> tuple:
        retry_after = max(0.0, decision.retry_after_s)
        import math

        return (
            429,
            json.dumps(
                {
                    "message": "Request shed by admission control",
                    "reason": decision.reason,
                    "retry_after_s": round(retry_after, 3),
                    "pressure": round(decision.pressure, 4),
                }
            ).encode(),
            "application/json",
            {"Retry-After": str(max(1, math.ceil(retry_after)))},
        )

    def _queue_job(self, m, q, body, h):
        t0 = time.perf_counter()
        t_wall = time.time()
        try:
            job_data = json.loads(body or b"{}")
        except ValueError:
            return self._text(400, "Invalid JSON")
        # tenant model (docs/GATEWAY.md): X-Swarm-Tenant names the
        # submitting tenant; absent = the default tenant, preserving
        # the reference wire contract
        tenant = (self._header(h, "X-Swarm-Tenant") or "").strip() or DEFAULT_TENANT
        # QoS class (docs/GATEWAY.md §QoS): X-Swarm-QoS next to the
        # tenant header; absent/"bulk" = None, the reference behavior.
        # An unknown class is a 400, never a silent bulk ride.
        try:
            qos = parse_qos(self._header(h, QOS_HEADER))
        except ValueError as e:
            return self._text(400, str(e))
        # shape-validate BEFORE admission: a malformed submission is a
        # 400, never a consumed rate token or an "admitted" count
        try:
            module, _scan_id, tenant = JobQueueService.validate_scan(
                job_data, tenant
            )
        except ValueError as e:
            return self._text(400, str(e))
        trace_id = header_trace_id(h) or new_trace_id()
        # admission control, ONE decision for every path: shed, never
        # block — a 429 with Retry-After is the overload story, not a
        # growing queue. The decision runs before the cache lookup on
        # purpose: a hit needs the same decision anyway (answering
        # from cache is cheap — no worker, no queue seat — but not
        # free: blobs + a journaled record per chunk, so cached
        # content must not become an unthrottled durable-write path),
        # and under overload the shed skips the digest + tier round
        # trip entirely
        decision = self._admission_decision(tenant, qos=qos)
        if not decision.admitted:
            return self._shed_response(decision)
        # gateway-tier short-circuit (docs/GATEWAY.md §QoS): an
        # admitted interactive submission whose every chunk is
        # fleet-known completes right here — zero worker dispatch.
        # Only chunks the writeback bound (qos_cache_max_rows) can
        # ever have stored are looked up: a big bulk-shaped
        # interactive submission is a guaranteed miss, and must not
        # pay per-chunk digests + a tier round trip to learn it
        if qos == QOS_INTERACTIVE and self.qos_cache is not None:
            lines, batch_size, _base = JobQueueService.parse_submission(
                job_data
            )
            max_rows = int(getattr(self.cfg, "qos_cache_max_rows", 0))
            chunks = (
                list(chunk_generator(lines, batch_size))
                if lines and max_rows > 0 else []
            )
            if any(len(c) > max_rows for c in chunks):
                chunks = []
            lk0 = time.perf_counter()
            lk_wall = time.time()
            outputs = (
                self.qos_cache.lookup_chunks(module, chunks)
                if chunks else None
            )
            lk1 = time.perf_counter()
            if outputs is not None:
                comp_wall = time.time()
                try:
                    result = self.queue.complete_scan_from_cache(
                        job_data, outputs, trace_id=trace_id,
                        tenant=tenant, qos=qos,
                    )
                except ValueError as e:
                    return self._text(400, str(e))
                GATEWAY_SHORT_CIRCUIT.labels(outcome="hit").inc()
                elapsed = time.perf_counter() - t0
                GATEWAY_LATENCY.labels(qos=QOS_INTERACTIVE).observe(
                    elapsed, trace_id=trace_id
                )
                # zero-dispatch waterfall (satellite: short-circuit
                # scans are fully traceable): admission → cache.lookup
                # → completion tile the exact window the latency
                # histogram just observed, so the segments-sum gate
                # holds for this path too
                if tracing.enabled():
                    self.queue.tracer.assemble_short_circuit(
                        result["scan_id"], trace_id, t_wall, elapsed,
                        result["chunks"],
                        [
                            tracing.make_span(
                                "admission", trace_id, t_wall, lk0 - t0,
                                tenant=tenant,
                            ),
                            tracing.make_span(
                                "cache.lookup", trace_id, lk_wall,
                                lk1 - lk0, chunks=result["chunks"],
                            ),
                            tracing.make_span(
                                "completion", trace_id, comp_wall,
                                max(0.0, elapsed - (lk1 - t0)),
                            ),
                        ],
                        qos=QOS_INTERACTIVE, tenant=tenant,
                    )
                    self.queue.tracer.flush()
                return self._text(200, "Job queued successfully")
            GATEWAY_SHORT_CIRCUIT.labels(outcome="miss").inc()
        # trace_id minted above (honoring the client's X-Swarm-Trace)
        # so the short-circuit path and the queued path correlate the
        # same way
        adm_s = time.perf_counter() - t0
        try:
            result = self.queue.queue_scan(
                job_data, trace_id=trace_id, tenant=tenant, qos=qos
            )
        except ValueError as e:
            return self._text(400, str(e))
        # inflow feed for the forecasting advisor (docs/RESILIENCE.md
        # §Preemption): only chunks that will consume a worker seat —
        # short-circuited scans never reach dispatch and must not
        # inflate the fleet-size forecast
        if self.autoscaler.forecaster is not None and result["chunks"]:
            self.autoscaler.forecaster.record(
                result["chunks"], tenant=tenant
            )
        if tracing.enabled():
            # pre-admission handler time, recorded OUTSIDE the
            # gateway-latency window (start < admitted_at by
            # construction — the waterfall's segment sum deliberately
            # excludes it; docs/OBSERVABILITY.md §Tracing)
            self.queue.tracer.add_spans(result["scan_id"], [
                tracing.make_span(
                    "admission", trace_id, t_wall, adm_s, tenant=tenant,
                    qos=qos,
                ),
            ])
        return self._text(200, "Job queued successfully")

    def _stream(self, m, q, body, h):
        """Server-push NDJSON results (gateway/streaming.py): the body
        is a GENERATOR — the HTTP layer writes it chunked as records
        arrive, so the client sees chunk i the moment it lands."""
        scan_id = m["scan_id"]
        if not SCAN_ID_RE.match(scan_id):
            return self._json(400, {"message": "Invalid scan_id"})
        try:
            from_chunk = int((q.get("from") or ["0"])[0])
        except ValueError:
            return self._json(400, {"message": "Invalid from cursor"})
        gen = stream_scan(
            self.queue,
            scan_id,
            from_chunk=max(0, from_chunk),
            poll_s=self.cfg.gateway_stream_poll_s,
            idle_timeout_s=self.cfg.gateway_stream_idle_timeout_s,
        )
        return 200, gen, "application/x-ndjson"

    # ------------------------------------------------------------------
    # Continuous monitoring (docs/MONITORING.md)
    # ------------------------------------------------------------------
    def _submit_monitor_epoch(self, spec, scan_id, epoch) -> Optional[dict]:
        """The ticker's epoch-submit callback: one admission decision
        (epoch fires are rate-limited like any submission — a shed
        epoch returns None and the spec retries next tick, late), then
        a PARTIAL gateway-cache lookup so fleet-known targets complete
        with zero dispatch, then the journaled fire."""
        decision = self._admission_decision(spec.tenant, qos=spec.qos)
        if not decision.admitted:
            return None
        cached = None
        max_rows = int(getattr(self.cfg, "qos_cache_max_rows", 0))
        if self.qos_cache is not None and max_rows > 0:
            lines = [t.rstrip("\n") for t in spec.targets]
            chunks = list(chunk_generator(lines, spec.batch_size))
            outs = self.qos_cache.lookup_chunks_partial(spec.module, chunks)
            if outs:
                cached = {
                    i: o
                    for i, o in enumerate(outs)
                    if o is not None and len(chunks[i]) <= max_rows
                }
        try:
            result = self.queue.fire_monitor_epoch(
                spec.to_wire(), scan_id, epoch,
                cached_outputs=cached, trace_id=new_trace_id(),
            )
            dispatched = result["chunks"] - int(
                result.get("cached_chunks") or 0
            )
            if self.autoscaler.forecaster is not None and dispatched > 0:
                self.autoscaler.forecaster.record(
                    dispatched, tenant=spec.tenant
                )
            return result
        except Exception as e:
            # a failed fire (journal down, malformed spec) must not
            # kill the ticker; the spec stays due and retries
            print(f"monitor epoch fire failed for {spec.monitor_id}: {e}")
            return None

    def _monitor_post(self, m, q, body, h):
        """Register or update a standing monitor spec. Tenant and QoS
        ride the same headers as a one-shot submission; an update
        preserves the existing cadence (epoch, next_fire_at) so
        changing targets never re-fires or rewinds a monitor."""
        if self.monitor is None:
            return self._json(503, {"message": "Monitoring disabled"})
        try:
            data = json.loads(body or b"{}")
        except ValueError:
            return self._json(400, {"message": "Invalid JSON"})
        tenant = (
            self._header(h, "X-Swarm-Tenant") or ""
        ).strip() or DEFAULT_TENANT
        try:
            qos = parse_qos(self._header(h, QOS_HEADER))
        except ValueError as e:
            return self._json(400, {"message": str(e)})
        monitor_id = str(data.get("monitor_id") or "")
        if not monitor_id:
            import uuid

            monitor_id = f"mon-{uuid.uuid4().hex[:12]}"
        try:
            spec = MonitorSpec(
                monitor_id=monitor_id,
                module=str(data.get("module") or ""),
                targets=[str(t) for t in (data.get("targets") or [])],
                interval_s=float(data.get("interval_s") or 0.0),
                tenant=tenant,
                qos=qos,
                batch_size=int(data.get("batch_size") or 0),
                paused=bool(data.get("paused")),
                created_at=time.time(),
            )
        except (TypeError, ValueError) as e:
            return self._json(400, {"message": str(e)})
        problem = spec.validate()
        if problem is not None:
            return self._json(400, {"message": problem})
        existing = self.queue.get_monitor(spec.monitor_id)
        if existing is None:
            limit = int(getattr(self.cfg, "monitor_max_specs", 0))
            if limit > 0 and len(self.queue.list_monitors()) >= limit:
                return self._json(
                    429, {"message": "Monitor registry full"}
                )
        else:
            spec.created_at = float(
                existing.get("created_at") or spec.created_at
            )
            spec.epoch = int(existing.get("epoch") or 0)
            spec.next_fire_at = float(existing.get("next_fire_at") or 0.0)
            spec.last_scan_id = existing.get("last_scan_id")
            spec.refire = bool(existing.get("refire"))
        try:
            self.queue.put_monitor(spec.to_wire())
        except Exception as e:
            return self._json(503, {"message": f"Registration failed: {e}"})
        return self._json(
            200,
            {
                "monitor_id": spec.monitor_id,
                "epoch": spec.epoch,
                "paused": spec.paused,
            },
        )

    def _monitor_list(self, m, q, body, h):
        return self._json(200, {"monitors": self.queue.list_monitors()})

    def _monitor_update(self, m, q, body, h):
        """``{"op": "rm"|"pause"|"resume"}`` — mutations, not a generic
        PATCH; spec changes go through POST /monitor upserts."""
        monitor_id = m["monitor_id"]
        try:
            data = json.loads(body or b"{}")
        except ValueError:
            return self._json(400, {"message": "Invalid JSON"})
        op = data.get("op")
        existing = self.queue.get_monitor(monitor_id)
        if existing is None:
            return self._json(404, {"message": "Monitor not found"})
        if op == "rm":
            self.queue.remove_monitor(monitor_id)
            return self._json(200, {"message": "Monitor removed"})
        if op in ("pause", "resume"):
            spec = dict(existing)
            spec["paused"] = op == "pause"
            try:
                self.queue.put_monitor(spec)
            except Exception as e:
                return self._json(503, {"message": f"Update failed: {e}"})
            return self._json(
                200, {"monitor_id": monitor_id, "paused": spec["paused"]}
            )
        return self._json(400, {"message": "op must be rm, pause or resume"})

    def _monitor_feed(self, m, q, body, h):
        """Resumable NDJSON change feed (docs/MONITORING.md §Feed
        resume contract): ``?from=N`` skips the first N records; the
        generator long-polls for new ones. A removed monitor's stored
        feed stays readable until drained (then ``end``)."""
        monitor_id = m["monitor_id"]
        if not MONITOR_ID_RE.match(monitor_id):
            return self._json(400, {"message": "Invalid monitor_id"})
        try:
            from_seq = int((q.get("from") or ["0"])[0])
        except ValueError:
            return self._json(400, {"message": "Invalid from cursor"})
        if self.queue.get_monitor(monitor_id) is None and not (
            self.queue.blobs.list(feed_prefix(monitor_id))
        ):
            return self._json(404, {"message": "Monitor not found"})
        gen = stream_feed(
            self.queue.blobs,
            monitor_id,
            from_seq=max(0, from_seq),
            poll_s=self.cfg.monitor_feed_poll_s,
            idle_timeout_s=self.cfg.monitor_feed_idle_timeout_s,
            alive=lambda: self.queue.get_monitor(monitor_id) is not None,
        )
        return 200, gen, "application/x-ndjson"

    def _tenants(self, m, q, body, h):
        """Per-tenant operator surface: queue depth, jobs by state,
        admission counters (`swarm tenants`)."""
        depths = self.queue.tenant_depths()
        by_tenant = self.queue.jobs_by_tenant()
        admission = self.gateway.snapshot()
        out = {}
        for tenant in sorted(set(depths) | set(by_tenant) | set(admission)):
            counts = admission.get(tenant, {})
            out[tenant] = {
                "queue_depth": depths.get(tenant, 0),
                "jobs_by_state": by_tenant.get(tenant, {}),
                "admitted": counts.get("admitted", 0),
                "shed": counts.get("shed", 0),
            }
        return self._json(200, {"tenants": out})

    def _autoscale_recommend(self, m, q, body, h):
        prefix = (q.get("prefix") or ["node"])[0]
        return self._json(200, self.autoscaler.recommend(prefix))

    def _autoscale_apply(self, m, q, body, h):
        try:
            data = json.loads(body or b"{}")
        except ValueError:
            return self._json(400, {"message": "Invalid JSON"})
        prefix = data.get("prefix") or "node"
        # dry-run unless the operator armed gateway_autoscale_apply —
        # the advisor itself refuses to touch the provider otherwise
        return self._json(200, self.autoscaler.apply(prefix))

    def _get_job(self, m, q, body, h):
        worker_id = (q.get("worker_id") or [None])[0]
        job = self.queue.next_job(worker_id or "unknown")
        # every poll answer carries the control-plane generation
        # (docs/DURABILITY.md): a worker seeing it change knows the
        # server restarted and re-registers / resets its breakers
        gen = {"X-Swarm-Generation": str(self.queue.generation)}
        # drain signal delivery (docs/RESILIENCE.md §Preemption): the
        # poll loop is the one channel every worker already reads, so
        # the drain order rides it as a response header — no reverse
        # connection into the worker needed
        reason = self.queue.drain_reason(worker_id or "unknown")
        if reason is not None:
            gen["X-Swarm-Drain"] = reason
        if job is None:
            code, payload, ctype = self._text(204, "")
            return code, payload, ctype, gen
        code, payload, ctype = self._json(200, job)
        return code, payload, ctype, gen

    def _spin_up(self, m, q, body, h):
        try:
            data = json.loads(body or b"{}")
        except ValueError:
            return self._json(400, {"message": "Invalid JSON"})
        prefix, nodes = data.get("prefix"), data.get("nodes")
        if prefix is None or nodes is None:
            return self._json(400, {"message": "Both prefix and nodes are required"})
        threading.Thread(
            target=self.fleet.spin_up, args=(prefix, int(nodes)), daemon=True
        ).start()
        return self._json(
            202, {"message": f"Spinning up {nodes} droplets with prefix {prefix}"}
        )

    def _spin_down(self, m, q, body, h):
        try:
            data = json.loads(body or b"{}")
        except ValueError:
            return self._json(400, {"message": "Invalid JSON"})
        prefix = data.get("prefix")
        if prefix is None:
            return self._json(400, {"message": "Prefix is required"})
        self.fleet.teardown_async(prefix)
        return self._json(202, {"message": f"Spinning down droplets with prefix {prefix}"})

    def _drain_worker(self, m, q, body, h):
        """Operator-initiated graceful drain (docs/RESILIENCE.md
        §Preemption): dispatch stops offering the worker jobs; its next
        poll carries X-Swarm-Drain and the worker finishes its lease,
        uploads or spools, deregisters, and exits."""
        try:
            data = json.loads(body or b"{}")
        except ValueError:
            return self._json(400, {"message": "Invalid JSON"})
        reason = str(data.get("reason") or "drain")
        if self.queue.drain_worker(m["worker_id"], reason=reason):
            return self._json(
                200, {"message": "Worker draining", "reason": reason}
            )
        return self._json(409, {"message": "Worker already draining"})

    def _deregister(self, m, q, body, h):
        """The worker is exiting NOW: hand back any lease immediately
        (no grace-window wait) and drop its saturation report — a dead
        node's last word must not pin fleet pressure for a TTL."""
        try:
            data = json.loads(body or b"{}")
        except ValueError:
            return self._json(400, {"message": "Invalid JSON"})
        worker_id = str(data.get("worker_id") or "").strip()
        if not worker_id:
            return self._json(400, {"message": "worker_id is required"})
        result = self.queue.deregister_worker(worker_id)
        self.gateway.drop_saturation(worker_id)
        return self._json(200, {"message": "Worker deregistered", **result})

    def _reset(self, m, q, body, h):
        self.queue.reset()
        return self._json(200, {"message": "Redis database reset"})

    def _get_input_chunk(self, m, q, body, h):
        data = self.queue.input_chunk(m["scan_id"], int(m["chunk_id"]))
        if data is None:
            return self._json(404, {"message": "Chunk not found"})
        return 200, data, "application/octet-stream"

    def _put_output_chunk(self, m, q, body, h):
        self.queue.put_output_chunk(m["scan_id"], int(m["chunk_id"]), body or b"")
        return self._json(200, {"message": "stored"})

    # ------------------------------------------------------------------
    # Dispatch
    # ------------------------------------------------------------------
    UNAUTHENTICATED = {"/healthz", "/metrics"}

    def dispatch(
        self, method: str, path: str, query: dict, headers: dict, body: bytes
    ) -> tuple[int, Any, str, dict]:
        """Returns ``(code, payload, content_type, extra_headers)``.
        Handlers may return 3- or 4-tuples (``_observed`` normalizes);
        a non-bytes payload is an ITERATOR of byte chunks that the HTTP
        layer writes with chunked transfer encoding (/stream)."""
        t0 = time.perf_counter()
        parsed_path = path.rstrip("/") or "/"
        if parsed_path not in self.UNAUTHENTICATED:
            auth = headers.get("Authorization", "")
            if not auth.startswith("Bearer "):
                return self._observed(
                    "_unauthorized", method, t0,
                    self._json(401, {"message": "Authentication required"}),
                )
            if auth.split(" ", 1)[1] != self.cfg.api_key:
                return self._observed(
                    "_unauthorized", method, t0,
                    self._json(401, {"message": "Unauthorized"}),
                )
        for route_method, pattern, handler, route_name in self._routes:
            if route_method != method:
                continue
            match = pattern.match(path)
            if match:
                try:
                    result = handler(match.groupdict(), query, body, headers)
                except Exception as e:  # route crash → 500, keep serving
                    result = self._json(
                        500, {"message": f"{type(e).__name__}: {e}"}
                    )
                return self._observed(route_name, method, t0, result)
        return self._observed(
            "_unmatched", method, t0, self._json(404, {"message": "Not found"})
        )

    @staticmethod
    def _observed(
        route: str, method: str, t0: float, result: tuple
    ) -> tuple[int, Any, str, dict]:
        """Record request count + latency for one dispatched request
        and normalize the handler result to the 4-tuple form (for a
        streaming body the latency covers dispatch, not the stream's
        lifetime — the generator hasn't run yet)."""
        _HTTP_REQUESTS.labels(
            route=route, method=method, code=str(result[0])
        ).inc()
        _HTTP_LATENCY.labels(route=route).observe(time.perf_counter() - t0)
        if len(result) == 3:
            return (result[0], result[1], result[2], {})
        return result

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    def _advertise_url(self) -> None:
        """Align cfg.server_url with the actually-bound port when the
        operator didn't set one: fleet providers hand this URL to the
        workers they spawn (process cmdline / droplet cloud-init), and
        the dataclass default would point them at :5001 regardless of
        --port. An explicit server_url (public address behind NAT)
        always wins; defaulted-ness is captured at construction so a
        restart re-aligns to the newly bound port."""
        if self._url_was_default:
            host = self.cfg.host
            if host == "::":
                # v6 wildcard: stay on the bound address family — the
                # listener may not accept v4-mapped connections
                # (bindv6only), so 127.0.0.1 could be unreachable
                host = "[::1]"
            elif host in ("0.0.0.0", ""):
                host = "127.0.0.1"
            elif ":" in host:  # IPv6 literal needs brackets in a URL
                host = f"[{host}]"
            self.cfg.server_url = f"http://{host}:{self.port}"
            self.cfg.server_url_derived = True

    #: serve_forever's shutdown-check cadence. The stdlib default
    #: (0.5 s) makes every shutdown() block up to half a second —
    #: across a test suite with dozens of server fixtures that is
    #: tens of wasted wall-seconds; 50 ms of idle select cost is
    #: unmeasurable next to request handling.
    POLL_INTERVAL_S = 0.05

    def serve_forever(self) -> None:
        self._httpd = _make_httpd(self)
        self._advertise_url()
        self._httpd.serve_forever(poll_interval=self.POLL_INTERVAL_S)

    def start_background(self) -> threading.Thread:
        self._httpd = _make_httpd(self)
        self._advertise_url()
        httpd = self._httpd  # bind now: shutdown() may None the attr
        thread = threading.Thread(
            target=lambda: httpd.serve_forever(
                poll_interval=self.POLL_INTERVAL_S
            ),
            daemon=True,
        )
        thread.start()
        return thread

    @property
    def port(self) -> int:
        assert self._httpd is not None
        return self._httpd.server_address[1]

    def shutdown(self) -> None:
        if self.monitor is not None:
            self.monitor.stop()
        REGISTRY.remove_collector(self._collector)
        self._flight_unsub()
        # zero the by-state children this server populated: the gauge is
        # process-global, and a later server instance (supervisor
        # restart, sequential test fixtures) must not keep reporting the
        # dead store's counts as live state
        for status in self._seen_states:
            _JOBS_BY_STATE.labels(status=status).set(0)
        self._seen_states.clear()
        for tenant in self._seen_tenants:
            GATEWAY_QUEUED.labels(tenant=tenant).set(0)
        self._seen_tenants.clear()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def _make_httpd(server: SwarmServer) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _run(self, method: str) -> None:
            parsed = urlparse(self.path)
            query = parse_qs(parsed.query)
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            code, payload, ctype, extra = server.dispatch(
                method, parsed.path, query, dict(self.headers), body
            )
            if code == 204:
                # 204 is bodyless by spec; a body here would linger in the
                # socket and corrupt the next keep-alive request
                payload = b""
            if not isinstance(payload, (bytes, bytearray)):
                self._stream_body(method, code, payload, ctype, extra)
                return
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            for key, value in extra.items():
                self.send_header(key, value)
            if code != 204:
                self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            if payload and method != "HEAD":
                self.wfile.write(payload)

        def _stream_body(self, method, code, chunks, ctype, extra) -> None:
            """Write an iterator payload with chunked transfer encoding
            (HTTP/1.1): each yielded record flushes immediately, so a
            /stream client sees results as they land. A client that
            disconnects mid-stream just ends the generator; the broken
            socket is dropped, never reused for keep-alive. (Only GET
            routes produce generator payloads — HEAD requests match no
            GET route in dispatch and 404 before reaching here.)"""
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            for key, value in extra.items():
                self.send_header(key, value)
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                for part in chunks:
                    part = bytes(part)
                    if not part:
                        continue
                    self.wfile.write(
                        f"{len(part):X}\r\n".encode() + part + b"\r\n"
                    )
                    self.wfile.flush()
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionError, OSError):
                self.close_connection = True
            finally:
                close = getattr(chunks, "close", None)
                if close is not None:
                    close()

        def do_GET(self):
            self._run("GET")

        def do_POST(self):
            self._run("POST")

        def do_HEAD(self):
            self._run("HEAD")

    class _Server(ThreadingHTTPServer):
        """ThreadingHTTPServer whose shutdown actually severs clients.

        The stdlib's shutdown() stops the accept loop but keep-alive
        handler threads (daemonized) keep serving the OLD server
        object's routes — a client with a pooled connection would keep
        reading a dead control plane's state across an in-process
        restart (journal recovery made this observable: the stale
        generation kept being served). Track live connections and
        force-close them in server_close(), which is what a real
        process death does to its sockets anyway."""

        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self._live_lock = threading.Lock()  # guards: _live_conns (reads)
            self._live_conns: set = set()

        def process_request(self, request, client_address):
            with self._live_lock:
                self._live_conns.add(request)
            super().process_request(request, client_address)

        def shutdown_request(self, request):
            with self._live_lock:
                self._live_conns.discard(request)
            super().shutdown_request(request)

        def server_close(self):
            super().server_close()
            import socket as _socket

            with self._live_lock:
                conns = list(self._live_conns)
                self._live_conns.clear()
            for conn in conns:
                try:
                    conn.shutdown(_socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass

        def handle_error(self, request, client_address):
            # a /stream client hanging up mid-push (or any keep-alive
            # peer resetting) is normal operation, not a server error —
            # the stdlib default would dump a traceback per disconnect
            import sys as _sys

            exc = _sys.exc_info()[1]
            if isinstance(exc, (BrokenPipeError, ConnectionError)):
                return
            super().handle_error(request, client_address)

    if ":" in server.cfg.host:  # IPv6 literal (e.g. "::1", "fd00::1")
        import socket

        class _V6Server(_Server):
            address_family = socket.AF_INET6

        return _V6Server((server.cfg.host, server.cfg.port), Handler)
    return _Server((server.cfg.host, server.cfg.port), Handler)


def main(argv: Optional[list[str]] = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description="swarm_tpu C2 server")
    parser.add_argument("--host", default=None)
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--api-key", default=None)
    parser.add_argument("--config", default=None)
    args = parser.parse_args(argv)
    cfg = Config.load(
        path=args.config, host=args.host, port=args.port, api_key=args.api_key
    )
    server = SwarmServer(cfg)
    print(f"swarm_tpu server on {cfg.host}:{cfg.port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()


if __name__ == "__main__":
    main()
