"""Job queue service: chunking, dispatch, status rollup, results.

Wire-behavior matches the reference server routes (``server/server.py``):
the same Redis-role key names (``jobs``/``workers`` hashes, ``job_queue``/
``completed`` lists), the same blob layout (``{scan}/input|output/
chunk_{i}.txt``), the same job/scan id formats and status strings — so
the reference client and worker interoperate unchanged.

Fixes over the reference (SURVEY.md §5 "no retry or requeue"):
- **Leases**: a dispatched job carries ``lease_expires_at``; expired
  in-progress jobs are requeued (bounded by ``max_attempts``).
- Failed terminal states can optionally be requeued the same way.
- Worker statuses live in the state store (the reference kept them in a
  process-local dict, losing them on restart).
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import Any, Optional

from swarm_tpu.config import Config
from swarm_tpu.datamodel import (
    SCAN_ID_RE,
    Job,
    JobStatus,
    WorkerInfo,
    WorkerStatus,
    chunk_generator,
    chunk_input_key,
    chunk_output_key,
    generate_scan_id,
    job_id_for,
    rollup_scans,
)
from swarm_tpu.gateway.admission import DEFAULT_TENANT
from swarm_tpu.gateway.qos import QOS_INTERACTIVE, qos_class
from swarm_tpu.resilience.faults import FaultInjected, fault_point
from swarm_tpu.server.journal import QueueJournal
from swarm_tpu.stores import BlobStore, DocStore, StateStore
from swarm_tpu.telemetry import REGISTRY, emit_event
from swarm_tpu.telemetry import tracing
from swarm_tpu.telemetry.gateway_export import GATEWAY_LATENCY
from swarm_tpu.telemetry.journal_export import (
    JOURNAL_CORRUPT,
    JOURNAL_REPLAYED,
    QUEUE_GENERATION,
    QUEUE_RECOVERED,
)
from swarm_tpu.telemetry.monitor_export import MONITOR_SPECS

# Queue-service metric families (process-wide; multiple in-process
# services share them, which matches the one-service-per-server reality)
_JOBS_QUEUED = REGISTRY.counter(
    "swarm_queue_jobs_queued_total", "Jobs accepted into the queue"
)
_JOBS_DISPATCHED = REGISTRY.counter(
    "swarm_queue_jobs_dispatched_total", "Jobs leased out to workers"
)
_JOBS_REQUEUED = REGISTRY.counter(
    "swarm_queue_jobs_requeued_total", "Jobs requeued after lease expiry"
)
_JOBS_RETRIED = REGISTRY.counter(
    "swarm_queue_jobs_retried_total",
    "Jobs requeued after a worker-reported failure",
    ("status",),
)
_JOBS_DEAD_LETTER = REGISTRY.counter(
    "swarm_queue_jobs_dead_letter_total",
    "Jobs quarantined after exhausting max_attempts",
)
_LEASE_RENEWALS = REGISTRY.counter(
    "swarm_queue_lease_renewals_total",
    "Lease renewal requests",
    ("outcome",),
)
_EXPRESS_SERVED = REGISTRY.counter(
    "swarm_queue_express_served_total",
    "Jobs dispatched from the interactive express lane "
    "(docs/GATEWAY.md §QoS)",
)
_JOBS_TERMINAL = REGISTRY.counter(
    "swarm_queue_jobs_terminal_total",
    "Jobs reaching a terminal status",
    ("status",),
)
_JOB_PHASE_SECONDS = REGISTRY.histogram(
    "swarm_job_phase_seconds",
    "Per-phase worker seconds as reported in completed jobs' perf",
    ("phase",),
)
_JOB_ROWS = REGISTRY.counter(
    "swarm_queue_rows_processed_total",
    "Rows processed as reported in completed jobs' perf",
)


class JobQueueService:
    def __init__(
        self,
        cfg: Config,
        state: StateStore,
        blobs: BlobStore,
        docs: DocStore,
        fleet=None,
        journal: Optional[QueueJournal] = None,
    ):
        self.cfg = cfg
        self.state = state
        self.blobs = blobs
        self.docs = docs
        self.fleet = fleet
        self._lock = threading.Lock()
        # generation/cache get their OWN lock: _put_job runs inside
        # `with self._lock` on some paths (PR 4 made dispatch/update
        # atomic) and bare on others — a second small lock avoids both
        # the deadlock and the lost-increment race between request
        # threads (a lost bump could serve a stale by-state cache for
        # a full TTL after a real transition)
        self._gen_lock = threading.Lock()  # guards: _jobs_generation, _by_state_cache
        self._jobs_generation = 0
        self._by_state_cache: tuple[float, int, dict[str, int]] = (0.0, -1, {})
        # weighted-fair dispatch cursor (docs/GATEWAY.md): next_job
        # serves tenant queues round-robin starting AFTER the tenant it
        # served last, so a deep queue from one tenant can never starve
        # the others (equal weights; the cursor only moves on a serve)
        self._rr_cursor = 0  # guarded-by: _lock
        # express-lane twin of the cursor (docs/GATEWAY.md §QoS):
        # interactive tenants rotate fairly among themselves, same rule
        self._rr_cursor_x = 0  # guarded-by: _lock
        # consecutive express serves while bulk work was waiting — the
        # bulk-starvation bound (cfg.qos_express_burst) ticks against
        # this and forces one bulk serve when it trips
        self._express_streak = 0  # guarded-by: _lock
        # durable queue journal (docs/DURABILITY.md): every mutation is
        # journaled BEFORE the state store is touched, so the journal
        # is always a superset of the store and a restart replays it.
        # The journal lock serializes {append → store write} pairs
        # against {snapshot → checkpoint} — without it a checkpoint
        # could fold state that misses an appended-but-unapplied record
        # whose segment it then prunes. It guards an ORDERING, not a
        # field — the journal's own counters carry their own guarded-by
        # annotations (server/journal.py). The acquisition order below
        # is declared for the lockorder pass: checkpoint takes only
        # _journal_lock, so no cycle.
        # lock-order: _lock -> _journal_lock
        self._journal_lock = threading.RLock()
        # drain set (docs/RESILIENCE.md §Preemption): worker id →
        # reason ("drain" | "preempted" | "sigterm"). Dispatch refuses
        # these workers so they can finish their current lease and
        # exit. Guarded by _journal_lock, NOT _lock: every mutation
        # pairs with its WAL append under that lock (append-before-
        # apply, like jobs), and _journal_state() snapshots it while
        # already holding _journal_lock — guarding it with _lock there
        # would invert the declared _lock -> _journal_lock order.
        self._draining: dict[str, str] = {}  # guarded-by: _journal_lock
        if journal is None and cfg.journal_enabled:
            journal = QueueJournal(
                blobs, compact_segments=cfg.journal_compact_segments
            )
        self._journal = journal
        #: monotonic control-plane generation: bumped once per
        #: journal-enabled boot (0 = journal disabled). Rides the
        #: X-Swarm-Generation header so workers detect restarts.
        self.generation = 0
        #: per-scan trace-waterfall assembler (docs/OBSERVABILITY.md
        #: §Tracing). Constructed BEFORE recovery so recovered scans
        #: can re-register and keep their original trace ids; every
        #: method no-ops when tracing is disabled.
        self.tracer = tracing.TraceAssembler(blobs)
        #: summary of the boot-time recovery (None when nothing was
        #: recovered) — surfaced on /healthz for operators
        self.recovery_summary: Optional[dict] = None
        if self._journal is not None:
            self.recovery_summary = self.recover()

    # ------------------------------------------------------------------
    # Tenant queues (docs/GATEWAY.md)
    # ------------------------------------------------------------------
    @staticmethod
    def _queue_list(tenant: Optional[str], qos: Optional[str] = None) -> str:
        """Dispatch-list key for one (tenant, QoS lane). The default
        tenant's bulk lane keeps the reference's bare ``job_queue``
        list so legacy tooling (and a real Redis populated by the
        reference server) interoperates unchanged; other tenants get
        their own bounded list, and the interactive express lane gets
        a ``:x``-prefixed twin per tenant (docs/GATEWAY.md §QoS)."""
        if qos == QOS_INTERACTIVE:
            if not tenant or tenant == DEFAULT_TENANT:
                return "job_queue:x"
            return f"job_queue:x:t:{tenant}"
        if not tenant or tenant == DEFAULT_TENANT:
            return "job_queue"
        return f"job_queue:t:{tenant}"

    def _lane_names(
        self, qos: Optional[str] = None, tenants: Optional[list] = None
    ) -> list[str]:
        """ONE lane's dispatch lists, default tenant first then
        registered tenants in sorted order (a stable rotation order
        for that lane's fair cursor). ``tenants`` lets the dispatch
        hot path reuse one registry read for both lanes."""
        if tenants is None:
            tenants = sorted(self.state.hkeys("tenants"))
        names = [self._queue_list(None, qos)]
        for tenant in tenants:
            if tenant != DEFAULT_TENANT:
                names.append(self._queue_list(tenant, qos))
        return names

    def _queue_names(self) -> list[str]:
        """Every dispatch list across both lanes, express first (the
        order dispatch consults them)."""
        return self._lane_names(QOS_INTERACTIVE) + self._lane_names()

    def tenants(self) -> list[str]:
        """Registered tenants (default always listed first)."""
        rest = sorted(
            t for t in self.state.hkeys("tenants") if t != DEFAULT_TENANT
        )
        return [DEFAULT_TENANT] + rest

    def tenant_depths(self) -> dict[str, int]:
        """Waiting-job depth per tenant, both lanes (two O(1) llens
        per tenant)."""
        return {
            tenant: self.tenant_depth(tenant) for tenant in self.tenants()
        }

    def tenant_depth(self, tenant: Optional[str]) -> int:
        """ONE tenant's waiting-job depth across both lanes — two
        llens, for the admission hot path (the all-tenant map is
        O(tenants) store calls)."""
        return self.state.llen(self._queue_list(tenant)) + self.state.llen(
            self._queue_list(tenant, QOS_INTERACTIVE)
        )

    # ------------------------------------------------------------------
    # Telemetry snapshots (scrape-time: /metrics and /healthz)
    # ------------------------------------------------------------------
    #: jobs_by_state cache TTL — the scan is O(all job records), and it
    #: feeds UNAUTHENTICATED endpoints (/healthz probes every few
    #: seconds, Prometheus scrapes): within the TTL, repeated probes of
    #: an UNCHANGED job table cost zero backend reads. Any local job
    #: mutation bumps the generation and invalidates immediately, so
    #: the cache never hides a transition.
    BY_STATE_TTL_S = 2.0

    def queue_depth(self) -> int:
        """Jobs currently waiting across ALL tenants' dispatch lists
        (O(tenants) llen calls)."""
        return sum(self.state.llen(n) for n in self._queue_names())

    def jobs_by_state(self) -> dict[str, int]:
        """Status → count over every job record (probe-storm-cached)."""
        now = time.monotonic()
        cached_at, gen, counts = self._by_state_cache
        with self._gen_lock:
            fresh = (
                gen == self._jobs_generation
                and now - cached_at < self.BY_STATE_TTL_S
            )
            gen = self._jobs_generation
        if fresh:
            return dict(counts)
        counts = {}
        for _job_id, raw in self.state.hgetall("jobs").items():
            try:
                status = json.loads(raw).get("status") or "unknown"
            except ValueError:
                status = "unparseable"
            counts[status] = counts.get(status, 0) + 1
        with self._gen_lock:
            self._by_state_cache = (now, gen, counts)
        return dict(counts)

    def jobs_by_tenant(self) -> dict[str, dict[str, int]]:
        """Tenant → (status → count) over every job record.

        Snapshot-then-render: the ONE ``hgetall`` copies the raw hash
        under the store's own lock; every ``json.loads`` runs on that
        snapshot afterwards, so neither the dispatch lock nor the
        store lock is ever held across serialization — a huge job
        table cannot stall submits or dispatches while it renders."""
        raw_jobs = self.state.hgetall("jobs")  # the snapshot
        out: dict[str, dict[str, int]] = {}
        for raw in raw_jobs.values():
            try:
                rec = json.loads(raw)
                status = rec.get("status") or "unknown"
                tenant = rec.get("tenant") or DEFAULT_TENANT
            except ValueError:
                status, tenant = "unparseable", DEFAULT_TENANT
            per = out.setdefault(tenant, {})
            per[status] = per.get(status, 0) + 1
        return out

    # ------------------------------------------------------------------
    # Streaming support (gateway/streaming.py reads these; neither may
    # hold queue locks — the stream generator polls them in a loop)
    # ------------------------------------------------------------------
    def scan_chunk_states(self, scan_id: str) -> dict[int, str]:
        """Chunk index → job status for one scan (snapshot-then-render,
        like :meth:`jobs_by_tenant`)."""
        out: dict[int, str] = {}
        for _job_id, raw in self.state.hgetall("jobs").items():
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if rec.get("scan_id") != scan_id:
                continue
            try:
                out[int(rec.get("chunk_index"))] = rec.get("status") or "unknown"
            except (TypeError, ValueError):
                continue
        return out

    def chunk_status(self, scan_id: str, chunk_index: int) -> Optional[str]:
        """ONE chunk's job status — a single hget, the stream
        generator's hot-loop probe (the full scan_chunk_states render
        is O(all jobs) and reserved for the rare gap/end decision)."""
        raw = self.state.hget("jobs", job_id_for(scan_id, chunk_index))
        if raw is None:
            return None
        try:
            return json.loads(raw).get("status") or "unknown"
        except ValueError:
            return "unparseable"

    def stored_output_chunks(self, scan_id: str) -> set[int]:
        """Chunk indices present in the durable output store — the
        restart-resume source of truth for /stream."""
        out: set[int] = set()
        for key in self.blobs.list(f"{scan_id}/output/"):
            m = re.search(r"chunk_(\d+)\.txt$", key)
            if m:
                out.add(int(m.group(1)))
        return out

    # ------------------------------------------------------------------
    # Submission (reference queue_job, server.py:414-461)
    # ------------------------------------------------------------------
    @staticmethod
    def validate_scan(
        job_data: dict, tenant: Optional[str] = None
    ) -> tuple[str, str, str]:
        """Shape-validate one submission WITHOUT side effects; returns
        ``(module, scan_id, tenant)`` or raises ValueError. The
        gateway runs this BEFORE admission so a malformed request
        never burns a tenant's rate token or counts as admitted;
        queue_scan re-uses it so the two sites cannot drift."""
        module = job_data.get("module")
        if not module:
            raise ValueError("Module must be provided")
        if not SCAN_ID_RE.match(str(module)):
            raise ValueError("Invalid module name")
        scan_id = job_data.get("scan_id") or generate_scan_id(module)
        if not SCAN_ID_RE.match(str(scan_id)):
            raise ValueError("Invalid scan_id")
        tenant = tenant or DEFAULT_TENANT
        if not SCAN_ID_RE.match(tenant):
            raise ValueError("Invalid tenant")
        # the numeric fields must coerce exactly the way queue_scan
        # will coerce them — a submission that would 400 downstream
        # must fail HERE, before it can burn an admission token
        try:
            int(float(job_data.get("batch_size") or 0))
            int(job_data.get("chunk_index") or 0)
        except (TypeError, ValueError):
            raise ValueError("Invalid batch_size or chunk_index")
        return str(module), str(scan_id), tenant

    @staticmethod
    def parse_submission(job_data: dict) -> tuple[list, int, int]:
        """``(lines, batch_size, base_index)`` of one submission — the
        ONE normalization site. queue_scan, complete_scan_from_cache
        and the gateway's short-circuit lookup all chunk through this,
        so the cache lookup's digests and the persisted chunks can
        never drift apart (a drift would silently misalign cached
        outputs against chunks)."""
        lines = [
            l.rstrip("\n") for l in (job_data.get("file_content") or [])
        ]
        batch_size = int(float(job_data.get("batch_size") or 0))
        base_index = int(job_data.get("chunk_index") or 0)
        return lines, batch_size, base_index

    # orders: _put_job < state.rpush (journaled record before the dispatch-list push)
    def queue_scan(
        self,
        job_data: dict,
        trace_id: Optional[str] = None,
        tenant: Optional[str] = None,
        qos: Optional[str] = None,
        monitor_id: Optional[str] = None,
        monitor_epoch: Optional[int] = None,
        cached_outputs: Optional[dict] = None,
    ) -> dict:
        """``monitor_id``/``monitor_epoch`` stamp epoch scans with
        their provenance (extra wire fields, absent for one-shots).
        ``cached_outputs`` maps chunk OFFSET → fleet-known output
        bytes: those chunks complete at the gateway (output persisted,
        record created COMPLETE) while the rest dispatch normally — the
        partial short-circuit a 95%-unchanged monitor epoch rides
        (docs/MONITORING.md §Cost model)."""
        module, scan_id, tenant = self.validate_scan(job_data, tenant)
        lines, batch_size, base_index = self.parse_submission(job_data)

        if self._journal is not None and not self.state.hget("tenants", tenant):
            # tenant-registry op journaled BEFORE the registry write,
            # like every other mutation (recovery rebuilds the registry
            # and the per-tenant dispatch lists from these records)
            with self._journal_lock:
                self._journal.append({"op": "tenant", "tenant": tenant})  # blocking-ok: WAL append under _journal_lock is the append->apply atom (docs/DURABILITY.md)
        self.state.hset("tenants", tenant, "1")
        # QoS lane selection (docs/GATEWAY.md §QoS): interactive scans
        # land on the tenant's express list; qos None (every reference
        # submission) keeps the exact pre-QoS list
        queue_list = self._queue_list(tenant, qos)
        admitted_at = time.time()
        queued = 0
        completed = 0
        total = 0
        for offset, chunk in enumerate(chunk_generator(lines, batch_size)):
            chunk_index = base_index + offset
            self.blobs.put(
                chunk_input_key(scan_id, chunk_index), "\n".join(chunk).encode()
            )
            job = Job.create(
                scan_id, chunk_index, module, trace_id=trace_id,
                tenant=tenant, qos=qos, admitted_at=admitted_at,
                chunk_rows=len(chunk),
                monitor_id=monitor_id, monitor_epoch=monitor_epoch,
            )
            total += 1
            cached = (cached_outputs or {}).get(offset)
            if cached is not None:
                # fleet-known chunk: output BEFORE the COMPLETE record,
                # same ordering contract as complete_scan_from_cache
                self.blobs.put(chunk_output_key(scan_id, chunk_index), cached)
                job.status = JobStatus.COMPLETE
                job.completed_at = time.time()
                self._put_job(job)
                self.state.rpush("completed", job.job_id)
                _JOBS_TERMINAL.labels(status=JobStatus.COMPLETE).inc()
                completed += 1
                emit_event(
                    "job.short_circuit",
                    trace_id=trace_id,
                    job_id=job.job_id,
                    scan_id=scan_id,
                    module=module,
                    chunk_index=chunk_index,
                    tenant=tenant,
                    qos=qos,
                )
                continue
            self._put_job(job)
            self.state.rpush(queue_list, job.job_id)
            queued += 1
            _JOBS_QUEUED.inc()
            emit_event(
                "job.queued",
                trace_id=trace_id,
                job_id=job.job_id,
                scan_id=scan_id,
                module=module,
                chunk_index=chunk_index,
                tenant=tenant,
                qos=qos,
            )
        self.tracer.register_scan(
            scan_id, trace_id, admitted_at, total, qos=qos, tenant=tenant,
            generation=self.generation or None, done=completed,
        )
        self._maybe_checkpoint()
        result = {"scan_id": scan_id, "chunks": total}
        if cached_outputs is not None:
            # extra key only on the monitor epoch path: the one-shot
            # submission response stays byte-identical to the reference
            result["cached_chunks"] = completed
        return result

    # orders: _journal.append < state.hset (append-before-ack, docs/DURABILITY.md)
    # blocking-ok: the WAL append + record write under _journal_lock IS
    # the append->apply atom the durability design requires
    def _put_job(self, job: Job) -> None:
        """Persist one job record, WRITE-AHEAD: the journal append is
        ordered before the state-store write (and therefore before any
        route's 200 — an admitted job is never unjournaled). A journal
        failure raises and the store is left untouched: the mutation
        observably never happened."""
        if self._journal is not None:
            with self._journal_lock:
                self._journal.append(
                    {
                        "op": "job",
                        "job": job.to_wire(),
                        "rr_cursor": self._rr_cursor,
                        "rr_cursor_x": self._rr_cursor_x,
                    }
                )
                self.state.hset("jobs", job.job_id, job.to_json())
        else:
            self.state.hset("jobs", job.job_id, job.to_json())
        with self._gen_lock:
            self._jobs_generation += 1

    def _get_job_record(self, job_id: str) -> Optional[Job]:
        raw = self.state.hget("jobs", job_id)
        return Job.from_json(raw) if raw else None

    def job_record(self, job_id: str) -> Optional[dict]:
        """One job's wire record (public: the gateway's cache-writeback
        hook reads a completed job's module/QoS/chunk coordinates)."""
        job = self._get_job_record(job_id)
        return job.to_wire() if job is not None else None

    # orders: blobs.put < _put_job (output chunk durable before the COMPLETE record —
    # recovery's output-present=>complete reconciliation reads the blob store as truth)
    def complete_scan_from_cache(
        self,
        job_data: dict,
        outputs: list,
        trace_id: Optional[str] = None,
        tenant: Optional[str] = None,
        qos: Optional[str] = None,
    ) -> dict:
        """Gateway-tier short-circuit (docs/GATEWAY.md §QoS): persist
        fleet-known outputs and create already-COMPLETE job records —
        the scan finishes without touching a dispatch list or a
        worker. ``outputs`` aligns 1:1 with the submission's chunks
        (the caller looked every one of them up in the shared tier);
        every downstream surface — /raw, /stream, /get-statuses, the
        tail client's ``completed`` pop-list — behaves exactly as if a
        worker had drained the scan."""
        module, scan_id, tenant = self.validate_scan(job_data, tenant)
        lines, batch_size, base_index = self.parse_submission(job_data)
        if self._journal is not None and not self.state.hget("tenants", tenant):
            with self._journal_lock:
                self._journal.append({"op": "tenant", "tenant": tenant})  # blocking-ok: WAL append under _journal_lock is the append->apply atom (docs/DURABILITY.md)
        self.state.hset("tenants", tenant, "1")
        admitted_at = time.time()
        done = 0
        for offset, (chunk, output) in enumerate(
            zip(chunk_generator(lines, batch_size), outputs)
        ):
            chunk_index = base_index + offset
            self.blobs.put(
                chunk_input_key(scan_id, chunk_index),
                "\n".join(chunk).encode(),
            )
            # output BEFORE the record: a COMPLETE record must never
            # exist without its chunk
            self.blobs.put(chunk_output_key(scan_id, chunk_index), output)
            job = Job.create(
                scan_id, chunk_index, module, trace_id=trace_id,
                tenant=tenant, qos=qos, admitted_at=admitted_at,
                chunk_rows=len(chunk),
            )
            job.status = JobStatus.COMPLETE
            job.completed_at = time.time()
            self._put_job(job)
            # the tail client follows the same pop-list a worker-drained
            # completion feeds
            self.state.rpush("completed", job.job_id)
            _JOBS_TERMINAL.labels(status=JobStatus.COMPLETE).inc()
            done += 1
            emit_event(
                "job.short_circuit",
                trace_id=trace_id,
                job_id=job.job_id,
                scan_id=scan_id,
                module=module,
                chunk_index=chunk_index,
                tenant=tenant,
                qos=qos,
            )
        self._maybe_checkpoint()
        return {"scan_id": scan_id, "chunks": done}

    # ------------------------------------------------------------------
    # Monitor registry (docs/MONITORING.md): standing-rescan specs are
    # queue state — journaled like jobs, snapshot like jobs, recovered
    # like jobs. The ticker (monitor/service.py) only READS this
    # registry; every mutation funnels through these three methods.
    # ------------------------------------------------------------------
    def list_monitors(self) -> list[dict]:
        out = []
        for _mid, raw in sorted(self.state.hgetall("monitors").items()):
            try:
                out.append(json.loads(raw))
            except ValueError:
                continue
        return out

    def get_monitor(self, monitor_id: str) -> Optional[dict]:
        raw = self.state.hget("monitors", monitor_id)
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def _monitor_gauge(self) -> None:
        MONITOR_SPECS.labels().set(len(self.state.hkeys("monitors")))

    # orders: _journal.append < state.hset (append-before-ack: a registered
    # spec is never unjournaled, docs/DURABILITY.md)
    # blocking-ok: the WAL append + registry write under _journal_lock IS
    # the append->apply atom the durability design requires
    def put_monitor(self, spec_wire: dict) -> None:
        """Register/update one spec (add, pause, resume, cadence
        advance). WRITE-AHEAD like every queue mutation: a journal
        failure raises and the registry is untouched."""
        monitor_id = str(spec_wire["monitor_id"])
        payload = json.dumps(spec_wire, separators=(",", ":"))
        if self._journal is not None:
            with self._journal_lock:
                self._journal.append({"op": "monitor_spec", "spec": spec_wire})  # blocking-ok: WAL append under _journal_lock is the append->apply atom (docs/DURABILITY.md)
                self.state.hset("monitors", monitor_id, payload)
        else:
            self.state.hset("monitors", monitor_id, payload)
        self._monitor_gauge()
        self._maybe_checkpoint()

    # orders: _journal.append < state.hdel (same append-before-apply atom)
    # blocking-ok: the WAL append + registry delete under _journal_lock IS
    # the append->apply atom the durability design requires
    def remove_monitor(self, monitor_id: str) -> bool:
        if self.state.hget("monitors", monitor_id) is None:
            return False
        if self._journal is not None:
            with self._journal_lock:
                self._journal.append({"op": "monitor_rm", "monitor_id": monitor_id})  # blocking-ok: WAL append under _journal_lock is the append->apply atom (docs/DURABILITY.md)
                self.state.hdel("monitors", monitor_id)
        else:
            self.state.hdel("monitors", monitor_id)
        self._monitor_gauge()
        self._maybe_checkpoint()
        return True

    # orders: _journal.append < queue_scan (append-before-fire: the epoch
    # advance is journaled before any job record exists, so kill-9 leaves
    # either a fired epoch or a journaled-but-unfired one that recovery
    # flags for a single late re-fire — never a double fire)
    # blocking-ok: the WAL append + cadence write under _journal_lock IS
    # the append->apply atom the durability design requires
    def fire_monitor_epoch(
        self,
        spec_wire: dict,
        scan_id: str,
        epoch: int,
        cached_outputs: Optional[dict] = None,
        trace_id: Optional[str] = None,
    ) -> dict:
        """Advance one spec's cadence and submit its epoch scan. The
        journaled spec update (epoch, next_fire_at, last_scan_id) and
        the scan submission are deliberately ordered append-first: the
        journal may claim an epoch whose scan never happened (recovery
        re-fires it once, late, under the same scan id), but a scan can
        never exist that the journal doesn't know about.

        ``next_fire_at = now + interval`` — never ``+= k*interval`` —
        is the fire-once-late rule for missed-while-down epochs."""
        now = time.time()
        spec = dict(spec_wire)
        spec["epoch"] = int(epoch)
        spec["last_scan_id"] = scan_id
        spec["next_fire_at"] = now + float(spec.get("interval_s") or 0.0)
        spec["refire"] = False
        monitor_id = str(spec["monitor_id"])
        payload = json.dumps(spec, separators=(",", ":"))
        if self._journal is not None:
            with self._journal_lock:
                self._journal.append(
                    {
                        "op": "monitor_epoch",
                        "monitor_id": monitor_id,
                        "epoch": int(epoch),
                        "scan_id": scan_id,
                        "spec": spec,
                    }
                )  # blocking-ok: WAL append under _journal_lock is the append->apply atom (docs/DURABILITY.md)
                self.state.hset("monitors", monitor_id, payload)
        else:
            self.state.hset("monitors", monitor_id, payload)
        result = self.queue_scan(
            {
                "module": spec.get("module"),
                "file_content": list(spec.get("targets") or []),
                "batch_size": spec.get("batch_size") or 0,
                "scan_id": scan_id,
            },
            trace_id=trace_id,
            tenant=spec.get("tenant"),
            qos=spec.get("qos"),
            monitor_id=monitor_id,
            monitor_epoch=int(epoch),
            cached_outputs=cached_outputs if cached_outputs is not None else {},
        )
        return result

    # ------------------------------------------------------------------
    # Graceful drain + deregistration (docs/RESILIENCE.md §Preemption)
    # ------------------------------------------------------------------
    def drain_reason(self, worker_id: str) -> Optional[str]:
        """Why this worker is draining, or None (the dispatch-refusal
        probe; also rides the X-Swarm-Drain response header)."""
        with self._journal_lock:
            return self._draining.get(worker_id)

    def draining_workers(self) -> dict[str, str]:
        """Worker id → drain reason snapshot (/healthz, tests)."""
        with self._journal_lock:
            return dict(self._draining)

    # append-before-apply: the WAL append precedes the drain-set write
    # (a worker told to drain is never offered a job by the next boot)
    # blocking-ok: the WAL append + drain-set add under _journal_lock IS
    # the append->apply atom the durability design requires
    def drain_worker(self, worker_id: str, reason: str = "drain") -> bool:
        """Mark one worker draining: dispatch stops offering it jobs
        (it finishes its current lease, uploads or spools, then calls
        :meth:`deregister_worker`). Sources: the operator route
        ``POST /drain/<worker>``, a provider preemption notice, or an
        armed ``fleet.preempt`` chaos clause. Journaled so a server
        restart mid-drain keeps refusing the worker. Returns False if
        the worker was already draining."""
        with self._journal_lock:
            if worker_id in self._draining:
                return False
            if self._journal is not None:
                self._journal.append(
                    {"op": "drain", "worker": worker_id, "reason": reason}
                )  # blocking-ok: WAL append under _journal_lock is the append->apply atom (docs/DURABILITY.md)
            self._draining[worker_id] = reason
        worker = self._load_worker(worker_id)
        worker.status = (
            WorkerStatus.PREEMPTED
            if reason == "preempted"
            else WorkerStatus.DRAINING
        )
        self._save_worker(worker)
        emit_event("worker.drain", worker_id=worker_id, reason=reason)
        self._maybe_checkpoint()
        return True

    # orders: _put_job < state.hdel
    # (record-first requeue, same discipline as _requeue_expired;
    # append-before-apply: the WAL append precedes the drain-set drop)
    # blocking-ok: the lease handback must be atomic against dispatch —
    # a concurrent next_job must see either the old lease or the
    # requeued job, never a half-released one
    def deregister_worker(self, worker_id: str) -> dict:
        """The worker is exiting NOW: drop its drain entry, hand back
        any lease it still holds immediately (no grace-window wait —
        the node is gone, waiting out the lease just delays the
        requeue), and mark it inactive. Runs under the dispatch lock so
        the handback and a concurrent ``_requeue_expired`` serialize:
        whichever runs first requeues the job, the other sees QUEUED /
        a cleared assignee and does nothing — exactly one requeue.
        The caller (the route) also drops the worker's admission
        saturation report. Returns ``{"requeued", "was_draining"}``."""
        requeued = 0
        with self._lock:
            with self._journal_lock:
                was = self._draining.pop(worker_id, None)
                if self._journal is not None:
                    self._journal.append(
                        {"op": "deregister", "worker": worker_id}
                    )  # blocking-ok: WAL append under _journal_lock is the append->apply atom (docs/DURABILITY.md)
            for job_id in list(self.state.hkeys("leases")):
                job = self._get_job_record(job_id)
                if (
                    job is None
                    or job.worker_id != worker_id
                    or job.status not in JobStatus.ACTIVE
                ):
                    continue
                self._record_failure(job, "worker deregistered")
                if job.attempts >= self.cfg.max_attempts:
                    self._quarantine(job, reason="deregistered")
                    continue
                job.status = JobStatus.QUEUED
                job.worker_id = None
                job.lease_expires_at = None
                # journaled record FIRST, lease-index drop after — the
                # same never-strand ordering _requeue_expired documents
                self._put_job(job)
                self.state.hdel("leases", job_id)
                self.state.rpush(
                    self._queue_list(job.tenant, job.qos), job.job_id
                )
                requeued += 1
                _JOBS_REQUEUED.inc()
                emit_event(
                    "job.requeued", trace_id=job.trace_id, job_id=job_id,
                    attempts=job.attempts,
                )
            worker = self._load_worker(worker_id)
            worker.status = WorkerStatus.INACTIVE
            self._save_worker(worker)
        # quarantines above can close a scan's waterfall; persist it
        # now that the dispatch lock is released
        self.tracer.flush()
        self._maybe_checkpoint()
        emit_event(
            "worker.deregistered",
            worker_id=worker_id,
            requeued=requeued,
            was_draining=was is not None,
        )
        return {"requeued": requeued, "was_draining": was is not None}

    # ------------------------------------------------------------------
    # Dispatch (reference get_job, server.py:465-515) + leases
    # ------------------------------------------------------------------
    # orders: _put_job < state.hset (record-first: the lease index follows the journaled record)
    # blocking-ok: dispatch atomicity — the pop->lease transition must be
    # invisible to a concurrent renew/update (docs/DURABILITY.md), so the
    # dispatch lock intentionally spans the control-plane store writes
    def next_job(self, worker_id: str) -> Optional[dict]:
        now = time.time()
        worker = self._load_worker(worker_id)
        worker.last_contact = now

        # chaos injection site (docs/RESILIENCE.md §Preemption): an
        # armed ``fleet.preempt`` clause INJECTS a preemption notice
        # for the polling worker — the dispatch path is the one place
        # every worker is guaranteed to pass, so the plan can target
        # any fleet without knowing node names. Gated on the fleet
        # actually being preemptible (SimulatedProvider & co): a
        # NullProvider server in the same process must not consume the
        # plan's counts on a fleet that cannot be preempted.
        if getattr(self.fleet, "preempt", None) is not None:
            try:
                fault_point("fleet.preempt", detail=worker_id)
            except FaultInjected:
                self.drain_worker(worker_id, reason="preempted")
        reason = self.drain_reason(worker_id)
        if reason is not None:
            # draining worker: no dispatch — and its idle-poll counter
            # must NOT creep toward teardown while it finishes its
            # current lease (the drain path owns the exit)
            worker.status = (
                WorkerStatus.PREEMPTED
                if reason == "preempted"
                else WorkerStatus.DRAINING
            )
            self._save_worker(worker)
            return None

        job: Optional[Job] = None
        express = False
        with self._lock:
            self._requeue_expired(now)
            # lane policy (docs/GATEWAY.md §QoS): the express lane is
            # served ahead of bulk so an interactive job admitted
            # mid-flood pre-empts the backlog — but at most
            # qos_express_burst consecutive times while bulk work is
            # actually waiting, then one bulk serve is forced. With no
            # interactive submissions the express lists are empty and
            # this is byte-identical to the pre-QoS dequeue.
            burst = max(1, int(self.cfg.qos_express_burst))
            # ONE registry read serves both lanes' list names and the
            # starvation check — the dispatch hot path must not scale
            # its store round trips with how many places need the list
            tenants = sorted(self.state.hkeys("tenants"))
            lane_names = {
                QOS_INTERACTIVE: self._lane_names(QOS_INTERACTIVE, tenants),
                None: self._lane_names(None, tenants),
            }
            lanes = [QOS_INTERACTIVE, None]
            if self._express_streak >= burst:
                lanes = [None, QOS_INTERACTIVE]
            for lane in lanes:
                job, name = self._pop_lane(lane, lane_names[lane])
                if job is not None:
                    express = lane == QOS_INTERACTIVE
                    break
            if job is not None:
                if express and any(
                    self.state.llen(n) for n in lane_names[None]
                ):
                    # the streak only grows while bulk work waits — an
                    # idle bulk lane means nothing is being starved
                    self._express_streak += 1
                else:
                    self._express_streak = 0

            if job is not None:
                # lease assignment stays under the store lock: between
                # the pop and the IN_PROGRESS write a concurrent
                # update/renew must not observe a half-dispatched job
                job.status = JobStatus.IN_PROGRESS
                job.started_at = now
                job.worker_id = worker_id
                job.lease_expires_at = now + self.cfg.lease_seconds
                job.attempts += 1
                try:
                    self._put_job(job)
                except Exception:
                    # journal append failed: the dispatch observably
                    # never happened — restore the popped id to the
                    # FRONT of its list so the job isn't stranded
                    # QUEUED-but-unlisted until a restart
                    self.state.lpush(name, job.job_id)
                    raise
                self.state.hset(
                    "leases", job.job_id, str(job.lease_expires_at)
                )

        # lease-expiry quarantines above can finish (degrade) a scan's
        # waterfall; persist it now that the dispatch lock is released
        self.tracer.flush()

        if job is not None:
            worker.polls_with_no_jobs = 0
            worker.status = WorkerStatus.ACTIVE
            self._save_worker(worker)
            _JOBS_DISPATCHED.inc()
            if express:
                _EXPRESS_SERVED.inc()
            # server-stamped enqueue→lease wait for this attempt: both
            # endpoints are this process's own clock, so the waterfall's
            # dominant segment needs no cross-host clock agreement
            self.tracer.record_queue_wait(job, now)
            emit_event(
                "job.dispatch",
                trace_id=job.trace_id,
                job_id=job.job_id,
                worker_id=worker_id,
                attempts=job.attempts,
                qos=job.qos,
            )
            return job.to_wire()

        worker.polls_with_no_jobs += 1
        worker.status = WorkerStatus.PENDING
        if worker.polls_with_no_jobs > self.cfg.idle_polls_before_teardown:
            worker.status = WorkerStatus.INACTIVE
            if self.fleet is not None:
                self.fleet.teardown_async(worker_id)
        self._save_worker(worker)
        return None

    # requires-lock: _lock (runs inside next_job's dispatch transaction)
    # blocking-ok: the lane pop IS the dispatch transaction's first
    # half — the pop->lease transition must be invisible to a
    # concurrent renew/update (the same waiver next_job documents)
    def _pop_lane(
        self, qos: Optional[str], names: list
    ) -> tuple[Optional[Job], Optional[str]]:
        """Weighted-fair dequeue over ONE lane's tenant lists
        (docs/GATEWAY.md): scan round-robin from the lane's cursor,
        serve the first non-empty list, park the cursor AFTER it — one
        tenant's backlog can delay another by at most (tenants - 1)
        serves. Returns ``(job, list_name)`` or ``(None, None)``."""
        is_x = qos == QOS_INTERACTIVE
        cursor = self._rr_cursor_x if is_x else self._rr_cursor
        for k in range(len(names)):
            name = names[(cursor + k) % len(names)]
            # loop (not recursion): drop dangling ids from queue/hash
            # desync (e.g. /reset racing a submit) without blowing the
            # stack
            while True:
                job_id = self.state.lpop(name)
                if job_id is None:
                    break
                job = self._get_job_record(job_id)
                if job is not None and job.status == JobStatus.QUEUED:
                    # dangling ids, or a job that left QUEUED while its
                    # id was still in the list (e.g. completed unfenced
                    # after a lease-expiry requeue), are dropped above —
                    # never re-leased
                    nxt = (cursor + k + 1) % len(names)
                    if is_x:
                        self._rr_cursor_x = nxt
                    else:
                        self._rr_cursor = nxt
                    return job, name
        return None, None

    # requires-lock: _lock (runs inside next_job's dispatch transaction)
    # orders: _put_job < state.rpush; orders: _put_job < state.hdel (record-first requeue)
    # blocking-ok: lease recovery is part of the dispatch transaction
    def _requeue_expired(self, now: float) -> None:
        """Lease enforcement: in-progress jobs whose lease lapsed go back
        to the queue (the reference loses them forever).

        Scans only the ``leases`` index (jobs currently leased), not the
        whole jobs hash, so dispatch latency stays O(in-flight) rather
        than O(all jobs ever)."""
        for job_id, expiry in self.state.hgetall("leases").items():
            try:
                if float(expiry) >= now:
                    continue
            except ValueError:
                pass
            raw = self.state.hget("jobs", job_id)
            if raw is None:
                self.state.hdel("leases", job_id)  # protocol-ok: dangling lease (no job record) — nothing to journal
                continue
            try:
                job = Job.from_json(raw)
            except (ValueError, KeyError, TypeError):
                self.state.hdel("leases", job_id)  # protocol-ok: unparseable record — index hygiene, no record mutation paired
                continue
            # any ACTIVE status is leased: a worker dying mid-execution
            # leaves "executing" (not "in progress"), and its job must
            # still come back — restricting to IN_PROGRESS silently
            # lost every job whose worker died after the first status
            # update (resilience PR regression find)
            if job.status not in JobStatus.ACTIVE or job.lease_expires_at is None:
                self.state.hdel("leases", job_id)  # protocol-ok: terminal/unleased record — index hygiene, no record mutation paired
                continue
            if job.lease_expires_at >= now:
                continue
            self._record_failure(job, "lease expired")
            if job.attempts >= self.cfg.max_attempts:
                # quarantine, not a silent terminal failure: the job
                # parks in dead-letter WITH its failure history and can
                # be inspected/requeued (`swarm dead-letter`)
                self._quarantine(job, reason="lease_exhausted")
                continue
            job.status = JobStatus.QUEUED
            job.worker_id = None
            job.lease_expires_at = None
            # journaled record FIRST, auxiliary keys after: if the
            # append fails the lease-index entry is still present, so
            # the next dispatch retries this requeue — dropping the
            # lease first would strand an ACTIVE job nothing scans
            self._put_job(job)
            self.state.hdel("leases", job_id)
            # a requeue goes back to ITS tenant's list IN ITS LANE:
            # lease recovery must not launder an abusive tenant's jobs
            # into another tenant's dispatch share, and an interactive
            # job must keep its QoS class across retries
            self.state.rpush(
                self._queue_list(job.tenant, job.qos), job.job_id
            )
            _JOBS_REQUEUED.inc()
            emit_event(
                "job.requeued", trace_id=job.trace_id, job_id=job_id,
                attempts=job.attempts,
            )

    @staticmethod
    def _record_failure(job: Job, status: str) -> None:
        history = list(job.failure_history or ())
        history.append(
            {"ts": time.time(), "worker_id": job.worker_id, "status": status}
        )
        job.failure_history = history

    # requires-lock: _lock; orders: _put_job < state.hdel (record-first quarantine)
    # blocking-ok: the terminal transition rides its caller's dispatch transaction
    def _quarantine(self, job: Job, reason: str) -> None:
        """Move a job to the dead-letter state (caller holds the lock
        and has already recorded the triggering failure)."""
        job.status = JobStatus.DEAD_LETTER
        job.worker_id = None
        job.lease_expires_at = None
        self._put_job(job)
        self.state.hdel("leases", job.job_id)
        _JOBS_TERMINAL.labels(status=JobStatus.DEAD_LETTER).inc()
        _JOBS_DEAD_LETTER.inc()
        # a quarantined chunk still closes its scan's waterfall (as
        # degraded), and the flight ring is dumped for the post-mortem.
        # Both are memory-only under this lock: the assembler stages,
        # flush() persists later; the dump's sinks run on a daemon
        # thread (tracing.FlightRecorder contract)
        self.tracer.job_terminal(
            job.scan_id, job.job_id, JobStatus.DEAD_LETTER,
            time.time(),
        )
        tracing.flight_dump(
            "dead_letter", detail=f"{job.job_id} after {job.attempts} attempts"
        )
        emit_event(
            "job.dead_letter",
            trace_id=job.trace_id,
            job_id=job.job_id,
            attempts=job.attempts,
            reason=reason,
            failures=job.failure_history,
        )

    # ------------------------------------------------------------------
    # Lease heartbeats (resilience PR): POST /renew-lease/<job_id>
    # ------------------------------------------------------------------
    # orders: _put_job < state.hset (record-first lease extension)
    # blocking-ok: the fenced renew must be atomic against dispatch/expiry
    def renew_lease(self, job_id: str, worker_id: Optional[str]) -> Optional[float]:
        """Extend a live lease for its current assignee. Returns the
        new expiry, or None when the renewal is rejected — unknown job,
        a job that was requeued/re-leased (fencing), or one already
        terminal. Rejection tells the worker the job is no longer its
        own."""
        now = time.time()
        with self._lock:
            job = self._get_job_record(job_id)
            if (
                job is None
                or job.status in JobStatus.TERMINAL
                or job.status == JobStatus.QUEUED
                or job.lease_expires_at is None
                or worker_id is None
                or job.worker_id != worker_id
            ):
                _LEASE_RENEWALS.labels(outcome="rejected").inc()
                return None
            job.lease_expires_at = now + self.cfg.lease_seconds
            self._put_job(job)
            self.state.hset("leases", job_id, str(job.lease_expires_at))
        # heartbeats are the steadiest journal writer — give them the
        # compaction duty too, or an idle-but-leased fleet would grow
        # the WAL without bound
        self._maybe_checkpoint()
        _LEASE_RENEWALS.labels(outcome="renewed").inc()
        emit_event(
            "job.lease_renewed",
            trace_id=job.trace_id,
            job_id=job_id,
            worker_id=worker_id,
            lease_expires_at=job.lease_expires_at,
        )
        return job.lease_expires_at

    # ------------------------------------------------------------------
    # Dead-letter surface (resilience PR)
    # ------------------------------------------------------------------
    def dead_letter_jobs(self) -> list[dict]:
        """Wire records of every quarantined job (failure history
        included) — the `swarm dead-letter` inspection surface."""
        out = []
        for _job_id, raw in self.state.hgetall("jobs").items():
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if rec.get("status") == JobStatus.DEAD_LETTER:
                out.append(rec)
        return sorted(out, key=lambda r: r.get("job_id") or "")

    # orders: _put_job < state.rpush (journaled record before the dispatch-list push)
    # blocking-ok: the requeue transition must be atomic against dispatch
    def requeue_dead_letter(self, job_id: str) -> bool:
        """Operator action: put a quarantined job back in the queue
        with a fresh attempt budget (history is kept)."""
        with self._lock:
            job = self._get_job_record(job_id)
            if job is None or job.status != JobStatus.DEAD_LETTER:
                return False
            job.status = JobStatus.QUEUED
            job.worker_id = None
            job.lease_expires_at = None
            job.attempts = 0
            self._put_job(job)
            # operator requeue keeps the tenant and the QoS lane too
            self.state.rpush(
                self._queue_list(job.tenant, job.qos), job.job_id
            )
        _JOBS_REQUEUED.inc()
        emit_event(
            "job.dead_letter_requeued", trace_id=job.trace_id, job_id=job_id
        )
        return True

    def _load_worker(self, worker_id: str) -> WorkerInfo:
        raw = self.state.hget("workers", worker_id)
        if raw:
            try:
                return WorkerInfo.from_wire(worker_id, json.loads(raw))
            except (ValueError, TypeError):
                pass
        return WorkerInfo(worker_id=worker_id, polls_with_no_jobs=-1)

    def _save_worker(self, worker: WorkerInfo) -> None:
        self.state.hset("workers", worker.worker_id, json.dumps(worker.to_wire()))

    # ------------------------------------------------------------------
    # Status transitions (reference update_job, server.py:308-335)
    # ------------------------------------------------------------------
    def update_job(self, job_id: str, changes: dict) -> bool:
        # one lock over load → check → write: the fencing decision and
        # the dead-letter/requeue transition must be atomic against a
        # concurrent dispatch or _requeue_expired (satellite: a zombie
        # whose lease expired must never complete a re-leased job)
        with self._lock:
            out = self._update_job_locked(job_id, changes)
        # persist any waterfall the transition just finished — blob IO,
        # so outside the lock (same placement rule as _maybe_checkpoint)
        self.tracer.flush()
        self._maybe_checkpoint()
        return out

    # requires-lock: _lock (update_job wraps; fencing decision + transition atomicity)
    # orders: _put_job < state.hdel; orders: _put_job < state.rpush (record-first, docs/DURABILITY.md)
    # blocking-ok: the fenced status transition rides the dispatch lock by design
    def _update_job_locked(self, job_id: str, changes: dict) -> bool:
        job = self._get_job_record(job_id)
        if job is None:
            return False
        changes = dict(changes)
        # Fencing token (not a mutation): our worker sends its id so a
        # zombie whose lease expired and whose job was reassigned cannot
        # clobber the new assignee's state. Reference workers omit it and
        # stay unfenced, preserving wire behavior.
        fence = changes.pop("worker_id", None)
        if fence is not None and fence != job.worker_id:
            # also rejects fenced updates to a requeued job (worker_id
            # None): a zombie must not touch a job back in the queue
            return False
        if "status" in changes and job.status in JobStatus.TERMINAL:
            # terminal states never regress (duplicate 'completed' pushes
            # would make the client tail re-emit chunks)
            return False
        # Poison-job discipline: a worker-reported failed terminal state
        # consumes one attempt. With budget left the job requeues (the
        # reference went terminal on the first hiccup); an exhausted job
        # is quarantined in dead-letter with its failure history.
        # FENCED updates only: an unfenced (reference-worker) failure
        # can come from a zombie whose job was already re-leased —
        # requeuing it would put an actively-executing job back in the
        # queue and double-execute it. Unfenced failures keep the
        # reference's terminal wire behavior below.
        # worker-shipped span batch rides perf but must NOT persist into
        # the job record (spans are assembly input, and a record that
        # grows with span volume would bloat every journal checkpoint).
        # Extracted BEFORE the retry branch: a failed attempt's spans
        # still belong to the scan's waterfall — a retried job must
        # assemble into ONE trace carrying every attempt.
        spans = None
        perf_in = changes.get("perf")
        if isinstance(perf_in, dict) and "spans" in perf_in:
            perf_in = dict(perf_in)
            spans = perf_in.pop("spans")
            changes["perf"] = perf_in
        new_status = changes.get("status")
        if (
            self.cfg.retry_failed
            and fence is not None
            and new_status in JobStatus.FAILED
            and new_status != JobStatus.DEAD_LETTER
        ):
            if spans:
                self.tracer.add_spans(job.scan_id, spans)
            self._record_failure(job, new_status)
            if job.attempts >= self.cfg.max_attempts:
                self._quarantine(job, reason="attempts_exhausted")
            else:
                job.status = JobStatus.QUEUED
                job.worker_id = None
                job.lease_expires_at = None
                # journaled record FIRST, lease-index drop after: if the
                # append fails the lease entry survives and the expiry
                # scan retries this transition — dropping the lease
                # first would strand an ACTIVE job nothing scans (the
                # same rule _requeue_expired documents; found by the
                # swarmlint protocol pass)
                self._put_job(job)
                self.state.hdel("leases", job_id)
                # retries keep the tenant AND the QoS lane
                self.state.rpush(
                    self._queue_list(job.tenant, job.qos), job.job_id
                )
                _JOBS_RETRIED.labels(status=new_status).inc()
                emit_event(
                    "job.retry",
                    trace_id=job.trace_id,
                    job_id=job_id,
                    attempts=job.attempts,
                    status=new_status,
                )
            return True
        wire = job.to_wire()
        became_complete = False
        for key, value in changes.items():
            if key in wire and key is not None:
                wire[key] = value
                if key == "status" and value == JobStatus.COMPLETE:
                    wire["completed_at"] = time.time()
                    became_complete = True
        updated = Job.from_wire(wire)
        if updated.status in JobStatus.TERMINAL:
            updated.lease_expires_at = None
        # journaled record FIRST (a failed append 500s with nothing
        # half-applied), auxiliary keys after — pushing `completed`
        # before the record write could feed the tail client a
        # completion whose job record never updated, and a retried
        # update would then push it twice
        self._put_job(updated)
        if updated.status in JobStatus.TERMINAL:
            self.state.hdel("leases", job_id)
        if became_complete:
            self.state.rpush("completed", job_id)
        if updated.status in JobStatus.TERMINAL and updated.status != job.status:
            _JOBS_TERMINAL.labels(status=updated.status).inc()
            # fold the worker-reported perf sample into the fleet-wide
            # phase histograms: remote workers' /metrics aren't scraped
            # by this server, but their phase timings flow through the
            # same status API the reference used for timestamps
            perf = updated.perf if isinstance(updated.perf, dict) else {}
            if updated.status == JobStatus.COMPLETE:
                # finiteness-guarded: json.loads accepts Infinity/NaN,
                # and one such perf value from a buggy worker would
                # wedge a monotonic counter / histogram sum for the
                # life of the process
                import math

                for phase in ("download", "execute", "upload"):
                    v = perf.get(f"{phase}_s")
                    if isinstance(v, (int, float)) and math.isfinite(v):
                        # exemplar-carrying observe: the worst recent
                        # observation's trace_id rides the +Inf bucket
                        # line when SWARM_METRICS_EXEMPLARS is set
                        _JOB_PHASE_SECONDS.labels(phase=phase).observe(
                            v, trace_id=updated.trace_id
                        )
                rows = perf.get("rows")
                if (
                    isinstance(rows, (int, float))
                    and math.isfinite(rows)
                    and rows > 0
                ):
                    _JOB_ROWS.inc(rows)
                # admission-to-verdict latency, per QoS class
                # (docs/GATEWAY.md §QoS): one observation per job at
                # its COMPLETE transition. Finiteness/sign-guarded —
                # the stamps ride job records a buggy worker's update
                # could have clobbered
                if isinstance(updated.admitted_at, (int, float)) and isinstance(
                    updated.completed_at, (int, float)
                ):
                    dt = updated.completed_at - updated.admitted_at
                    if math.isfinite(dt) and dt >= 0:
                        GATEWAY_LATENCY.labels(
                            qos=qos_class(updated.qos)
                        ).observe(dt, trace_id=updated.trace_id)
            # waterfall assembly: attach this chunk's span batch and
            # close the scan when its last chunk lands. Memory-only
            # here (caller holds _lock); update_job flushes after.
            self.tracer.job_terminal(
                updated.scan_id, job_id, updated.status,
                updated.completed_at, spans=spans,
            )
            emit_event(
                "job.terminal",
                trace_id=updated.trace_id,
                job_id=job_id,
                status=updated.status,
                worker_id=updated.worker_id,
                perf=perf or None,
            )
        return True

    # ------------------------------------------------------------------
    # Status aggregation (reference get_statuses, server.py:219-305)
    # ------------------------------------------------------------------
    def statuses(self) -> dict:
        # snapshot-then-render: both hgetall calls copy under the
        # store's internal lock only; parsing, rollup and the doc-store
        # writes below run on the snapshots with NO queue/store lock
        # held (a slow doc backend must not stall dispatch)
        raw_workers = self.state.hgetall("workers")
        raw_jobs = self.state.hgetall("jobs")
        workers = {}
        for worker_id, raw in raw_workers.items():
            try:
                workers[worker_id] = json.loads(raw)
            except ValueError:
                continue
        jobs = {}
        for job_id, raw in raw_jobs.items():
            try:
                jobs[job_id] = json.loads(raw)
            except ValueError:
                continue
        scans = rollup_scans(jobs)
        for scan in scans:
            if scan["percent_complete"] == 100:
                self._persist_scan_summary(scan)
        # per-tenant rollup from the SAME snapshot (one parse pass is
        # plenty: the records are already dicts here)
        tenants: dict[str, dict[str, int]] = {}
        for rec in jobs.values():
            tenant = rec.get("tenant") or DEFAULT_TENANT
            status = rec.get("status") or "unknown"
            per = tenants.setdefault(tenant, {})
            per[status] = per.get(status, 0) + 1
        return {
            "workers": workers, "jobs": jobs, "scans": scans,
            "tenants": tenants,
            # worker id → drain reason for the mid-drain set (`swarm
            # workers` annotates the State column with it; authed
            # endpoint, unlike /healthz's bare count)
            "draining": self.draining_workers(),
        }

    def _persist_scan_summary(self, scan: dict) -> None:
        coll = self.docs.collection("scans")
        if coll.find_one({"scan_id": scan["scan_id"]}) is None:
            coll.insert_one(
                {
                    "scan_id": scan["scan_id"],
                    "total_chunks": scan["total_chunks"],
                    "chunks_complete": scan["chunks_complete"],
                    "percent_complete": scan["percent_complete"],
                    "module": scan["module"],
                    "scan_started": scan["scan_started"],
                    "scan_completed": scan["completed_at"],
                    "scan_status": "complete",
                }
            )

    # ------------------------------------------------------------------
    # Results (reference get_chunk / get_latest_chunk / raw / parse_job)
    # ------------------------------------------------------------------
    def output_chunk(self, scan_id: str, chunk_index: int) -> Optional[str]:
        key = chunk_output_key(scan_id, chunk_index)
        try:
            return self.blobs.get(key).decode("utf-8", "replace")
        except (KeyError, FileNotFoundError, OSError):
            return None

    def input_chunk(self, scan_id: str, chunk_index: int) -> Optional[bytes]:
        try:
            return self.blobs.get(chunk_input_key(scan_id, chunk_index))
        except (KeyError, FileNotFoundError, OSError):
            return None

    def put_output_chunk(self, scan_id: str, chunk_index: int, data: bytes) -> None:
        self.blobs.put(chunk_output_key(scan_id, chunk_index), data)

    def latest_completed_job_id(self) -> Optional[str]:
        return self.state.lpop("completed")

    def raw_scan(self, scan_id: str) -> str:
        contents = []
        for key in self.blobs.list(f"{scan_id}/output/"):
            if key.endswith(".txt"):
                contents.append(self.blobs.get(key).decode("utf-8", "replace"))
        return "".join(contents)

    def parse_job(self, job_id: str) -> bool:
        """Parse one output chunk into the per-scan document collection.

        The reference (server.py:362-396) reads job metadata from a Mongo
        ``jobs`` collection nothing populates; this reads the live job
        record instead, keeping the route's observable behavior.
        """
        job = self._get_job_record(job_id)
        if job is None:
            return False
        content = self.output_chunk(job.scan_id, job.chunk_index)
        if content is None:
            return False
        self.docs.collection(job.scan_id).insert_one(
            {
                "scan_id": job.scan_id,
                "chunk_index": job.chunk_index,
                "module": job.module,
                "worker_id": job.worker_id,
                "start_time": job.started_at,
                "end_time": job.completed_at,
                "job_id": job_id,
                "content": content,
            }
        )
        return True

    # ------------------------------------------------------------------
    # blocking-ok: flush + journal clear must be one atom — a mutation
    # interleaved between them would survive into the next boot's replay
    def reset(self) -> None:
        """Flush all queue/scan state (reference /reset, server.py:550-554)."""
        with self._journal_lock:
            self.state.flushall()
            self._draining.clear()
            if self._journal is not None:
                # the journal must die with the state it describes, or
                # the next boot would resurrect a deliberately-flushed
                # queue (the generation counter survives — a reset is
                # an operational event, not a new server identity)
                self._journal.clear()
        with self._lock:
            self._rr_cursor = 0
            self._rr_cursor_x = 0
            self._express_streak = 0
        with self._gen_lock:
            self._jobs_generation += 1
        self._monitor_gauge()

    # ------------------------------------------------------------------
    # Durable journal: recovery + checkpointing (docs/DURABILITY.md)
    # ------------------------------------------------------------------
    # requires-lock: _journal_lock
    # blocking-ok: the snapshot read must exclude concurrent appends —
    # that exclusion is the journal lock's documented purpose
    def _journal_state(self) -> dict:
        """The full queue state in journal-snapshot form. Callers hold
        ``_journal_lock`` so no append can land between this read and
        the checkpoint that prunes the segments it covers."""
        jobs: dict[str, Any] = {}
        for job_id, raw in self.state.hgetall("jobs").items():
            try:
                jobs[job_id] = json.loads(raw)
            except ValueError:
                continue
        queues = {
            name: self.state.lrange(name, 0, -1)
            for name in self._queue_names()
        }
        monitors: dict[str, Any] = {}
        for mid, raw in self.state.hgetall("monitors").items():
            try:
                monitors[mid] = json.loads(raw)
            except ValueError:
                continue
        return {
            "jobs": jobs,
            "queues": queues,
            "tenants": self.tenants(),
            "rr_cursor": self._rr_cursor,
            "rr_cursor_x": self._rr_cursor_x,
            "monitors": monitors,
            "draining": dict(self._draining),
        }

    # blocking-ok: the snapshot->checkpoint pair holds _journal_lock so
    # no append lands between the state read and the segment prune
    def _maybe_checkpoint(self) -> None:
        """Opportunistic compaction: fold the WAL into a snapshot once
        enough segments accumulated. Runs on mutating routes' threads
        (never under ``_lock``); the unlucky caller pays one O(jobs)
        snapshot write — control-plane rates make that cheap, and the
        next boot's replay stays O(snapshot + recent WAL)."""
        journal = self._journal
        if journal is None:
            return
        if journal.segments_pending < journal.compact_segments:
            return
        with self._journal_lock:
            if journal.segments_pending < journal.compact_segments:
                return  # another thread compacted first
            try:
                journal.checkpoint(self._journal_state())
            except Exception as e:
                # compaction is an optimization: a failure must never
                # fail the mutating route that happened to trigger it —
                # the WAL just keeps growing until a checkpoint lands
                print(f"journal checkpoint failed (will retry): {e}")

    # blocking-ok: boot-time recovery runs before any route thread exists;
    # the post-recovery checkpoint holds _journal_lock like every other
    def recover(self) -> Optional[dict]:
        """Boot-time recovery: bump the server generation, replay the
        journal into the state store, reconcile against the idempotent
        chunk-output store, and re-arm leases with a short grace
        window. Returns a summary dict, or None when the journal holds
        no state (fresh deployment)."""
        journal = self._journal
        if journal is None:
            return None
        self.generation = journal.bump_generation()
        QUEUE_GENERATION.set(self.generation)
        if not journal.has_state():
            return None
        now = time.time()
        snapshot, records = journal.replay()

        jobs: dict[str, Job] = {}
        order: dict[str, int] = {}
        tenants: set[str] = set()
        monitors: dict[str, dict] = {}
        draining: dict[str, str] = {}
        cursor = 0
        cursor_x = 0
        idx = 0
        replayed = 0

        def _adopt(job_id: str, wire: dict) -> None:
            nonlocal idx
            try:
                jobs[job_id] = Job.from_wire(wire)
            except (KeyError, TypeError, ValueError):
                JOURNAL_CORRUPT.inc()
                return
            order[job_id] = idx
            idx += 1

        if snapshot:
            for job_id, wire in (snapshot.get("jobs") or {}).items():
                _adopt(job_id, wire)
                replayed += 1
            # the snapshot's queue lists carry the REAL dispatch order;
            # jobs they name sort ahead of later WAL mutations
            for ids in (snapshot.get("queues") or {}).values():
                for job_id in ids:
                    if job_id in order:
                        order[job_id] = idx
                        idx += 1
            tenants.update(
                t for t in (snapshot.get("tenants") or ()) if isinstance(t, str)
            )
            try:
                cursor = int(snapshot.get("rr_cursor") or 0)
            except (TypeError, ValueError):
                cursor = 0
            try:
                cursor_x = int(snapshot.get("rr_cursor_x") or 0)
            except (TypeError, ValueError):
                cursor_x = 0
            for mid, wire in (snapshot.get("monitors") or {}).items():
                if isinstance(wire, dict):
                    monitors[str(mid)] = wire
            for w, why in (snapshot.get("draining") or {}).items():
                if isinstance(w, str):
                    draining[w] = str(why or "drain")
        for rec in records:
            replayed += 1
            if rec.get("op") == "tenant":
                tenant = rec.get("tenant")
                if isinstance(tenant, str):
                    tenants.add(tenant)
                continue
            # monitor ops branch BEFORE the job fallback: an
            # unrecognized op would otherwise count as a corrupt job
            if rec.get("op") in ("monitor_spec", "monitor_epoch"):
                wire = rec.get("spec")
                if isinstance(wire, dict) and wire.get("monitor_id"):
                    monitors[str(wire["monitor_id"])] = wire
                else:
                    JOURNAL_CORRUPT.inc()
                continue
            if rec.get("op") == "monitor_rm":
                monitors.pop(str(rec.get("monitor_id") or ""), None)
                continue
            # drain-set ops branch BEFORE the job fallback too — the
            # same unknown-op-is-not-a-job rule the monitor ops follow
            if rec.get("op") == "drain":
                w = rec.get("worker")
                if isinstance(w, str):
                    draining[w] = str(rec.get("reason") or "drain")
                else:
                    JOURNAL_CORRUPT.inc()
                continue
            if rec.get("op") == "deregister":
                draining.pop(str(rec.get("worker") or ""), None)
                continue
            wire = rec.get("job")
            if not isinstance(wire, dict) or not wire.get("job_id"):
                JOURNAL_CORRUPT.inc()
                continue
            _adopt(str(wire["job_id"]), wire)
            if "rr_cursor" in rec:
                try:
                    cursor = int(rec["rr_cursor"])
                except (TypeError, ValueError):
                    pass
            if "rr_cursor_x" in rec:
                try:
                    cursor_x = int(rec["rr_cursor_x"])
                except (TypeError, ValueError):
                    pass
        JOURNAL_REPLAYED.inc(replayed)

        # tenant registry: journaled tenant ops plus every tenant a job
        # record names (belt and braces — the registry is reconstructed,
        # never trusted to survive)
        for job in jobs.values():
            if job.tenant:
                tenants.add(job.tenant)
        for tenant in sorted(tenants):
            self.state.hset("tenants", tenant, "1")

        # rebuild, never merge: on a backend whose state survived (real
        # Redis) stale dispatch lists / leases would double-push
        for name in set(self._queue_names()) | {"job_queue"}:
            self.state.lclear(name)
        for job_id in self.state.hkeys("leases"):
            self.state.hdel("leases", job_id)
        for mid in self.state.hkeys("monitors"):
            self.state.hdel("monitors", mid)

        # monitor cadence reconciliation (docs/MONITORING.md §Crash
        # points): a journaled epoch whose scan has no job record and no
        # output blob died between append and fire — flag it for ONE
        # late re-fire under its journaled scan id. Everything else
        # resumes its cadence from the journaled next_fire_at (a spec
        # that slept through N intervals is simply due, and the ticker's
        # `now + interval` advance fires it once, not N times).
        scan_ids = {j.scan_id for j in jobs.values()}
        for mid, spec in monitors.items():
            sid = spec.get("last_scan_id")
            if (
                sid
                and int(spec.get("epoch") or 0) > 0
                and sid not in scan_ids
                and not self.blobs.list(f"{sid}/output/")
            ):
                spec = dict(spec)
                spec["refire"] = True
                spec["next_fire_at"] = 0.0
                monitors[mid] = spec
            self.state.hset(
                "monitors", mid, json.dumps(monitors[mid], separators=(",", ":"))
            )
        self._monitor_gauge()

        grace = self.cfg.journal_recovery_grace_s or (
            self.cfg.lease_seconds / 2.0
        )
        counts = {
            "queued": 0, "leased": 0, "terminal": 0,
            "completed_from_store": 0,
        }
        queued: list[str] = []
        for job_id, job in jobs.items():
            # "output present ⇒ complete" only applies to jobs that
            # were actually DISPATCHED at least once (ACTIVE, or
            # requeued with attempts consumed): a never-dispatched
            # QUEUED job whose output key exists is a REUSED scan_id's
            # stale blob (/reset keeps chunk outputs, reference
            # behavior) and must re-execute, not adopt old results
            ran = job.status in JobStatus.ACTIVE or job.attempts > 0
            if (
                job.status not in JobStatus.TERMINAL
                and ran
                and self.blobs.exists(
                    chunk_output_key(job.scan_id, job.chunk_index)
                )
            ):
                # the idempotent chunk store is truth: output present
                # means the chunk WAS completed, whatever the journal
                # tail says — never re-execute finished work. (Not
                # pushed to the legacy `completed` pop-list: replaying
                # a pre-crash push would re-emit the chunk to a tail
                # client — docs/DURABILITY.md.)
                job.status = JobStatus.COMPLETE
                job.completed_at = job.completed_at or now
                job.lease_expires_at = None
                counts["completed_from_store"] += 1
            elif job.status == JobStatus.QUEUED:
                queued.append(job_id)
                counts["queued"] += 1
            elif job.status in JobStatus.ACTIVE:
                # recovered leases are EXPIRED down to a short grace
                # window: a live worker's next heartbeat re-leases its
                # job through the normal fenced renew path; a worker
                # that died with the server lets the grace lapse and
                # the job requeues through _requeue_expired
                job.lease_expires_at = now + grace
                self.state.hset(
                    "leases", job_id, str(job.lease_expires_at)
                )
                counts["leased"] += 1
            else:
                counts["terminal"] += 1
            self.state.hset("jobs", job_id, job.to_json())
        for job_id in sorted(queued, key=lambda j: order.get(j, 0)):
            # rebuilt into the job's OWN (tenant, QoS lane) list — a
            # restart must not demote recovered interactive jobs to
            # the bulk lane
            job = jobs[job_id]
            self.state.rpush(self._queue_list(job.tenant, job.qos), job_id)

        with self._lock:
            self._rr_cursor = cursor
            self._rr_cursor_x = cursor_x
        with self._journal_lock:
            # a worker told to drain before the crash stays refused
            # after it: the drain set survives restarts so a preempted
            # node can't be handed work during its kill-after-grace
            self._draining = dict(draining)
        with self._gen_lock:
            self._jobs_generation += 1
        for outcome, n in counts.items():
            if n:
                QUEUE_RECOVERED.labels(outcome=outcome).inc(n)
        # fold everything into a fresh snapshot so the NEXT boot's
        # replay is O(live state), not O(history). Best-effort:
        # recovery already succeeded, and the un-compacted WAL replays
        # identically next time.
        with self._journal_lock:
            try:
                journal.checkpoint(self._journal_state())
            except Exception as e:
                print(f"post-recovery checkpoint failed (will retry): {e}")
        summary = {
            "generation": self.generation,
            "replayed_records": replayed,
            "monitors": len(monitors),
            "draining": len(draining),
            **counts,
        }
        # re-register unfinished scans with the waterfall assembler
        # under their ORIGINAL trace ids — a kill-9'd scan's recovered
        # attempts land in the same trace the client started, which is
        # what links pre- and post-restart work in `swarm trace`
        if tracing.enabled():
            by_scan: dict[str, list[Job]] = {}
            for job in jobs.values():
                by_scan.setdefault(job.scan_id, []).append(job)
            for scan_id, sjobs in by_scan.items():
                done = sum(
                    1 for j in sjobs if j.status in JobStatus.TERMINAL
                )
                if done >= len(sjobs):
                    continue
                trace_id = next((j.trace_id for j in sjobs if j.trace_id), None)
                admitted = min(
                    (j.admitted_at for j in sjobs
                     if isinstance(j.admitted_at, (int, float))),
                    default=None,
                )
                self.tracer.register_scan(
                    scan_id, trace_id, admitted, len(sjobs),
                    qos=next((j.qos for j in sjobs if j.qos), None),
                    tenant=next((j.tenant for j in sjobs if j.tenant), None),
                    generation=self.generation,
                    done=done,
                )
                if trace_id:
                    self.tracer.add_spans(scan_id, [tracing.make_span(
                        "journal-recovery", trace_id, now, 0.0,
                        generation=self.generation,
                        recovered_jobs=len(sjobs),
                    )])
        # always-on flight dump: the ring captured the pre-replay boot
        # context, and post-mortems of whatever killed the previous
        # generation start here
        tracing.flight_dump(
            "journal_recovery",
            detail=f"generation={self.generation} replayed={replayed}",
        )
        emit_event("queue.recovered", **summary)
        return summary
