"""Always-registered ``swarm_journal_*`` / recovery metric families
(docs/DURABILITY.md).

The durable queue journal (``swarm_tpu/server/journal.py``) is the
control plane's write-ahead log: every queue mutation appends a record
before the state store is touched, and a restarting server replays the
log to recover its job table. These families register at telemetry
import time — not on first journal construction — so EVERY process's
``/metrics`` carries them with rendered samples
(``tools/check_metrics.py`` requires them on a server that has never
journaled a record). Label combinations are pre-seeded for the same
reason.
"""

from __future__ import annotations

from swarm_tpu.telemetry.metrics import REGISTRY

#: journal records appended, by record kind (``job`` = a queue
#: mutation's full job record, ``tenant`` = tenant-registry add,
#: ``checkpoint`` = a compaction snapshot)
JOURNAL_APPENDS = REGISTRY.counter(
    "swarm_journal_appends_total",
    "Write-ahead journal records appended, by record kind",
    ("op",),
)
for _op in ("job", "tenant", "checkpoint"):
    JOURNAL_APPENDS.labels(op=_op)
del _op

#: records applied during boot-time recovery (snapshot entries + WAL
#: segment records)
JOURNAL_REPLAYED = REGISTRY.counter(
    "swarm_journal_replayed_total",
    "Journal records applied during boot-time recovery",
)

#: snapshot-compaction cycles (segments folded into a snapshot blob)
JOURNAL_COMPACTIONS = REGISTRY.counter(
    "swarm_journal_compactions_total",
    "Journal checkpoint compactions (segments folded into a snapshot)",
)

#: live WAL segment count (set at append/checkpoint/recovery time)
JOURNAL_SEGMENTS = REGISTRY.gauge(
    "swarm_journal_segments",
    "Write-ahead journal segments not yet folded into a snapshot",
)

#: records skipped during replay because they failed to parse — always
#: zero unless the journal was externally damaged (operator runbook:
#: docs/DURABILITY.md)
JOURNAL_CORRUPT = REGISTRY.counter(
    "swarm_journal_corrupt_records_total",
    "Journal records skipped at recovery because they failed to parse",
)

#: jobs materialized by recovery, by what recovery decided about them
#: (``queued`` = back on a dispatch list, ``leased`` = still leased
#: under the re-lease grace window, ``terminal`` = already finished,
#: ``completed_from_store`` = non-terminal in the journal but the
#: output blob exists, so the chunk store proves completion)
QUEUE_RECOVERED = REGISTRY.counter(
    "swarm_queue_recovered_jobs_total",
    "Jobs materialized by journal recovery, by recovery outcome",
    ("outcome",),
)
for _o in ("queued", "leased", "terminal", "completed_from_store"):
    QUEUE_RECOVERED.labels(outcome=_o)
del _o

#: monotonic server generation (bumped once per journal-enabled boot;
#: 0 = journal disabled). Workers read it from the X-Swarm-Generation
#: header to detect control-plane restarts.
QUEUE_GENERATION = REGISTRY.gauge(
    "swarm_queue_generation",
    "Monotonic control-plane generation (bumped per journal-enabled boot)",
)
