"""Fleet-wide telemetry: metrics registry + correlated event tracing.

Two complementary planes (neither exists in the reference, whose only
observability is ``print()`` plus job timestamps — SURVEY.md §5):

- :mod:`swarm_tpu.telemetry.metrics` — a process-wide, thread-safe
  registry of counters/gauges/histograms with label support and
  Prometheus text-format exposition, served from the C2 server's
  ``GET /metrics`` route and scraped by ``swarm metrics``.
- :mod:`swarm_tpu.telemetry.events` — structured JSON event lines
  (``ts, trace_id, job_id, phase, …``) emitted by every layer, keyed by
  a trace ID the client mints per scan and the server propagates via
  the ``X-Swarm-Trace`` header into each job record, so one grep
  reconstructs a whole scan's lifecycle across client → server →
  worker → engine.
"""

from swarm_tpu.telemetry.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    get_registry,
)
from swarm_tpu.telemetry.events import (  # noqa: F401
    emit_event,
    new_trace_id,
    subscribe,
)

# swarm_walk_* / swarm_device_* / swarm_shard_* / swarm_memo_* /
# swarm_gateway_* / swarm_journal_* / swarm_aot_* / swarm_monitor_*
# families register at import time so every process's /metrics carries
# them (docs/HOST_WALK.md, docs/DEVICE_MATCH.md, docs/SHARDING.md,
# docs/CACHING.md, docs/GATEWAY.md, docs/DURABILITY.md, docs/AOT.md,
# docs/MONITORING.md; check_metrics contract)
from swarm_tpu.telemetry import walk_export  # noqa: E402,F401
from swarm_tpu.telemetry import device_export  # noqa: E402,F401
from swarm_tpu.telemetry import shard_export  # noqa: E402,F401
from swarm_tpu.telemetry import memo_export  # noqa: E402,F401
from swarm_tpu.telemetry import gateway_export  # noqa: E402,F401
from swarm_tpu.telemetry import sched_export  # noqa: E402,F401
from swarm_tpu.telemetry import journal_export  # noqa: E402,F401
from swarm_tpu.telemetry import aot_export  # noqa: E402,F401
from swarm_tpu.telemetry import trace_export  # noqa: E402,F401
from swarm_tpu.telemetry import monitor_export  # noqa: E402,F401
from swarm_tpu.telemetry import fleet_export  # noqa: E402,F401
from swarm_tpu.telemetry import workflow_export  # noqa: E402,F401
