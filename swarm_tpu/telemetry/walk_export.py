"""Always-registered ``swarm_walk_*`` metric families (docs/HOST_WALK.md).

The host walk's batched-confirm counters live in ``EngineStats`` (the
hot path never touches a real metric); these gauges are the scrape-time
surface. They are created at telemetry import time — not on first
engine registration — so EVERY process's ``/metrics`` carries the
families with a rendered sample (``tools/check_metrics.py`` requires
them on a server that has no engine at all). Values are aggregated from
live engines by the collector in
:mod:`swarm_tpu.telemetry.engine_export` at scrape time.
"""

from __future__ import annotations

from swarm_tpu.telemetry.metrics import REGISTRY

#: widest live walk pool in the process (0 = batching runs inline on
#: the walk thread, or the serial reference walk is pinned)
WALK_POOL_THREADS = REGISTRY.gauge(
    "swarm_walk_pool_threads",
    "Widest live engine walk pool (SWARM_WALK_THREADS; 0 = inline or "
    "serial)",
)
#: (row, matcher) / (row, op) confirm pairs resolved by the grouped
#: GIL-released native passes instead of the per-pair serial path
WALK_BATCHED_PAIRS = REGISTRY.gauge(
    "swarm_walk_batched_pairs",
    "Confirm pairs resolved by the walk's batched native passes",
)
WALK_BATCH_ROUNDS = REGISTRY.gauge(
    "swarm_walk_batch_rounds",
    "Walk batches that dispatched at least one grouped confirm pass",
)
WALK_PRECOMPUTE_SECONDS = REGISTRY.gauge(
    "swarm_walk_precompute_seconds",
    "Seconds in the walk's confirm plan+dispatch (subset of "
    "host_confirm_seconds)",
)
#: host-walk sub-phase attribution (all subsets of
#: ``swarm_engine_host_confirm_seconds``): uncertainty resolution, the
#: extraction pass, memo inserts, member fan-out/fixup
WALK_PHASE_SECONDS = REGISTRY.gauge(
    "swarm_walk_phase_seconds",
    "Host-walk sub-phase seconds across live engines",
    ("phase",),
)
# pre-seed every phase label so the family always renders samples
# (a labeled family with no observed combos renders no lines, which
# would read as "family missing" to the exposition check)
for _ph in ("unc", "ext", "insert", "fixup"):
    WALK_PHASE_SECONDS.labels(phase=_ph).set(0.0)
del _ph
