"""Correlated structured logging: one JSON line per lifecycle event.

Every scan gets a trace ID minted by the client (:func:`new_trace_id`),
carried in the ``X-Swarm-Trace`` header through ``/queue``, stored on
each job record, handed back out via ``/get-job``, and echoed by the
worker — so every layer's events for one scan share a ``trace_id`` and
``grep <trace_id>`` reconstructs the whole lifecycle:

    {"ts": ..., "event": "scan.submit",     "trace_id": "ab12...", ...}
    {"ts": ..., "event": "job.queued",      "trace_id": "ab12...", "job_id": ...}
    {"ts": ..., "event": "job.dispatch",    "trace_id": "ab12...", "worker_id": ...}
    {"ts": ..., "event": "job.start",       "trace_id": "ab12...", "module": ...}
    {"ts": ..., "event": "job.phase",       "trace_id": "ab12...", "phase": "executing"}
    {"ts": ..., "event": "job.terminal",    "trace_id": "ab12...", "status": "complete"}
    {"ts": ..., "event": "job.worker_done", "trace_id": "ab12...", "perf": {...}}

(``job.terminal`` is the server's view of a terminal transition;
``job.worker_done`` the worker's. ``job.requeued`` /
``job.lease_exhausted`` / ``scan.stream_start`` round out the set.)

Emission sinks, all optional and independent:

- ``SWARM_EVENTS`` env: ``stderr``/``1`` streams lines to stderr; any
  other value is an append-path for a JSONL event log.
- in-process subscribers (:func:`subscribe`) — how tests and embedded
  tooling observe the stream without parsing stderr.
- the ``swarm_events_total{event=...}`` counter, so event volume is
  itself visible on ``/metrics``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import uuid
from typing import Callable, Optional

from swarm_tpu.telemetry import metrics as _metrics

import re

#: Name of the trace-propagation header (client → server → worker).
TRACE_HEADER = "X-Swarm-Trace"

#: What the server accepts from the wire: trace ids are stored into
#: every job record and event line of the scan, so a hostile header
#: must not smuggle multi-KB blobs or control characters through the
#: telemetry plane (same defense-in-depth posture as SCAN_ID_RE).
TRACE_ID_RE = re.compile(r"^[A-Za-z0-9_-]{1,64}$")

ENV_SINK = "SWARM_EVENTS"

_lock = threading.Lock()
_subscribers: list[Callable[[dict], None]] = []  # guarded-by: _lock (reads)

_EVENTS_TOTAL = _metrics.REGISTRY.counter(
    "swarm_events_total", "Structured telemetry events emitted", ("event",)
)


def new_trace_id() -> str:
    """Mint a scan-scoped trace ID (32 hex chars, uuid4)."""
    return uuid.uuid4().hex


def subscribe(fn: Callable[[dict], None]) -> Callable[[], None]:
    """Register an in-process event observer; returns an unsubscribe."""
    with _lock:
        _subscribers.append(fn)

    def unsubscribe() -> None:
        with _lock:
            try:
                _subscribers.remove(fn)
            except ValueError:
                pass

    return unsubscribe


def emit_event(
    event: str,
    trace_id: Optional[str] = None,
    job_id: Optional[str] = None,
    **fields,
) -> dict:
    """Emit one structured event line; returns the record.

    ``None``-valued fields are dropped so records stay grep-friendly
    (absent beats ``"trace_id": null``).
    """
    rec: dict = {"ts": round(time.time(), 6), "event": event}
    if trace_id is not None:
        rec["trace_id"] = trace_id
    if job_id is not None:
        rec["job_id"] = job_id
    for k, v in fields.items():
        if v is not None:
            rec[k] = v
    _EVENTS_TOTAL.labels(event=event).inc()

    sink = os.environ.get(ENV_SINK, "")
    if sink:
        try:
            line = json.dumps(rec, sort_keys=True, default=str)
            if sink in ("1", "stderr"):
                print(line, file=sys.stderr, flush=True)
            else:
                with open(sink, "a") as f:
                    f.write(line + "\n")
        except (OSError, TypeError, ValueError):
            pass  # telemetry must never take down the data path

    with _lock:
        subs = list(_subscribers)
    for fn in subs:
        try:
            fn(rec)
        except Exception:
            pass
    return rec


def header_trace_id(headers: dict) -> Optional[str]:
    """Case-insensitive ``X-Swarm-Trace`` lookup in a header dict.

    Returns None for absent, empty, or invalid values (anything not
    matching :data:`TRACE_ID_RE`) — the caller then mints a fresh id,
    so a hostile header degrades to an ignored one."""
    want = TRACE_HEADER.lower()
    for k, v in headers.items():
        if str(k).lower() == want:
            v = str(v).strip()
            return v if TRACE_ID_RE.match(v) else None
    return None
