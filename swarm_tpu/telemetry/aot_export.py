"""Always-registered ``swarm_aot_*`` metric families (docs/AOT.md).

The AOT executable cache ships serialized XLA executables through the
shared Redis/S3-role stores so a joining worker FETCHES its compiled
kernels instead of compiling them (the fleet cold-start story). These
families are registered at telemetry import time — not on first
client construction — so EVERY process's ``/metrics`` carries them
with rendered samples (``tools/check_metrics.py`` requires them on a
server that has no engine and no AOT store at all). Label combos are
pre-seeded for the same reason: a labeled family with no observed
combos renders no lines, which would read as "family missing" to the
exposition check.
"""

from __future__ import annotations

from swarm_tpu.telemetry.metrics import REGISTRY

#: artifact fetch outcomes. ``hit`` = a published executable was
#: loaded (from the prewarm pool or the store) instead of compiling;
#: ``miss`` = nothing published for this key (the worker compiles and,
#: when publishing is on, becomes the publisher); ``deserialize_error``
#: = the artifact existed but failed to load (foreign
#: jaxlib/device topology or corrupt bytes) — the worker falls back to
#: a live compile, it never blocks (docs/RESILIENCE.md).
AOT_FETCHES = REGISTRY.counter(
    "swarm_aot_fetch_total",
    "AOT executable-cache fetches by outcome (hit = loaded instead "
    "of compiled; deserialize_error = artifact present but unloadable, "
    "fell back to compile)",
    ("outcome",),
)
for _o in ("hit", "miss", "deserialize_error"):
    AOT_FETCHES.labels(outcome=_o)
del _o

#: artifact publish outcomes after a local compile. ``fenced`` =
#: rejected by the store's fencing-token check (a superseded writer);
#: ``error`` = the breaker-wrapped store op failed (store degraded,
#: artifact dropped — the executable still serves locally).
AOT_PUBLISHES = REGISTRY.counter(
    "swarm_aot_publish_total",
    "AOT executable-cache publishes by outcome",
    ("outcome",),
)
for _o in ("stored", "fenced", "error"):
    AOT_PUBLISHES.labels(outcome=_o)
del _o

#: wall seconds an executable took to become servable on THIS worker:
#: observed per fetch-load (deserialize_and_load) and per local
#: compile on the AOT-managed path — the fetch/compile bring-up gap is
#: the whole point (bench's ``aot_coldstart_speedup``).
AOT_BRINGUP_SECONDS = REGISTRY.histogram(
    "swarm_aot_bringup_seconds",
    "Seconds to make one executable servable (fetch+deserialize on a "
    "hit, trace+compile on a miss), by source",
    ("source",),
)
AOT_BRINGUP_SECONDS.labels(source="fetch")
AOT_BRINGUP_SECONDS.labels(source="compile")

#: byte size of the most recently moved artifact (published or
#: fetched) — the operator's "how big are these things" gauge.
AOT_ARTIFACT_BYTES = REGISTRY.gauge(
    "swarm_aot_artifact_bytes",
    "Size in bytes of the most recently published or fetched AOT "
    "artifact",
)
AOT_ARTIFACT_BYTES.set(0)
