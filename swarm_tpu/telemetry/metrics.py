"""Process-wide metrics registry with Prometheus text exposition.

The registry is the measurement substrate every layer reports through:
the server's route counters/latency histograms, the queue's job-state
counters and depth gauges, the worker's per-phase histograms, and the
match engine's device/host kernel counters (registered as a *collector*
so scrape-time snapshots never touch the engine hot path).

Implemented against the stdlib only (``prometheus_client`` is not a
dependency of this image): three metric kinds — :class:`Counter`,
:class:`Gauge`, :class:`Histogram` (fixed buckets) — all labeled, all
thread-safe, rendered in the Prometheus text format 0.0.4 that real
scrapers (and ``tools/check_metrics.py``) parse.

Usage::

    from swarm_tpu.telemetry import REGISTRY

    REQS = REGISTRY.counter("swarm_http_requests_total",
                            "HTTP requests", ("route", "code"))
    REQS.labels(route="/queue", code="200").inc()
    print(REGISTRY.render())
"""

from __future__ import annotations

import math
import os
import re
import threading
import time
from typing import Callable, Iterable, Optional, Sequence

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Default histogram buckets — tuned for request/phase latencies in
#: seconds (5 ms … 60 s); callers with other shapes pass their own.
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0,
)

#: Exemplar rendering gate (docs/OBSERVABILITY.md §Tracing). Histograms
#: can carry the trace ID of their worst recent observation, rendered
#: in the OpenMetrics-style ``# {trace_id="..."} <value>`` suffix on
#: the ``+Inf`` bucket line — but ONLY when this env var is truthy,
#: because the strict 0.0.4 text format (and this module's own
#: ``parse_exposition``) rejects exemplar suffixes. Default off keeps
#: every existing scraper green; opt in for OpenMetrics-aware backends.
EXEMPLARS_ENV = "SWARM_METRICS_EXEMPLARS"

#: Exemplar replacement policy: a stored exemplar survives until a
#: worse (larger) observation arrives or it ages past this horizon —
#: "worst RECENT observation", so a single historic spike doesn't pin
#: the exemplar forever.
EXEMPLAR_MAX_AGE_S = 60.0


def exemplars_enabled() -> bool:
    return os.environ.get(EXEMPLARS_ENV, "").strip().lower() in (
        "1", "true", "yes", "on"
    )


def escape_label_value(value: str) -> str:
    """Prometheus text-format label escaping: backslash, quote, newline."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_value(v: float) -> str:
    """Render a sample value: integers without the trailing .0 (cosmetic
    but matches common exporters), +Inf/NaN spelled the Prometheus way."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{escape_label_value(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Metric:
    """Shared labeled-family plumbing. Child state lives in ``_data``
    keyed by the label-value tuple; subclasses define what a child's
    state is and how it renders."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, labelnames: Sequence[str]):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        # writes only — the unlabeled () child is created here and read
        # lock-free (the key is never removed)
        self._data: dict[tuple, object] = {}  # guarded-by: _lock
        if not self.labelnames:
            self._data[()] = self._new_child()

    # -- subclass surface ---------------------------------------------
    def _new_child(self):
        raise NotImplementedError

    def _render_child(self, label_values: tuple, child) -> Iterable[str]:
        raise NotImplementedError

    def _observe_exemplar(self, child, label_values, value, trace_id) -> None:
        # only histograms keep exemplars; for other kinds this defers
        # to _observe, which raises the usual kind mismatch
        self._observe(child, value)

    # -----------------------------------------------------------------
    def labels(self, *values, **kw) -> "_Handle":
        if kw:
            if values:
                raise ValueError("pass labels positionally or by name, not both")
            try:
                values = tuple(str(kw[n]) for n in self.labelnames)
            except KeyError as e:
                raise ValueError(f"missing label {e.args[0]!r} for {self.name}")
            if len(kw) != len(self.labelnames):
                raise ValueError(f"unexpected labels for {self.name}: {kw}")
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got {values}"
            )
        with self._lock:
            child = self._data.get(values)
            if child is None:
                child = self._data[values] = self._new_child()
        return _Handle(self, values, child)

    def _unlabeled(self) -> "_Handle":
        if self.labelnames:
            raise ValueError(f"{self.name} requires labels {self.labelnames}")
        return _Handle(self, (), self._data[()])

    def render(self) -> list[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        # child STATE is copied under the lock, not just the item list:
        # a concurrent observe() racing a lock-free read of a live
        # histogram child could expose a torn (non-monotonic) series
        with self._lock:
            items = [(lv, list(child)) for lv, child in self._data.items()]
        for label_values, child in sorted(items, key=lambda kv: kv[0]):
            lines.extend(self._render_child(label_values, child))
        return lines

    def snapshot(self) -> dict:
        """JSON-able view (bench attachments, the CLI table)."""
        with self._lock:
            items = [(lv, list(child)) for lv, child in self._data.items()]
        samples = []
        for label_values, child in sorted(items, key=lambda kv: kv[0]):
            samples.append(
                {
                    "labels": dict(zip(self.labelnames, label_values)),
                    "value": self._child_value(child),
                }
            )
        return {
            "name": self.name,
            "type": self.kind,
            "help": self.help,
            "samples": samples,
        }

    def _child_value(self, child):
        raise NotImplementedError


class _Handle:
    """A (metric, label-values) pair — what callers inc/set/observe on."""

    __slots__ = ("_metric", "_label_values", "_child")

    def __init__(self, metric: _Metric, label_values: tuple, child):
        self._metric = metric
        self._label_values = label_values
        self._child = child

    def inc(self, amount: float = 1.0) -> None:
        self._metric._inc(self._child, amount)

    def set(self, value: float) -> None:
        self._metric._set(self._child, value)

    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        if trace_id is None:
            self._metric._observe(self._child, value)
        else:
            self._metric._observe_exemplar(
                self._child, self._label_values, value, trace_id
            )

    @property
    def value(self):
        return self._metric._child_value(self._child)


class Counter(_Metric):
    """Monotonically increasing count. ``inc()`` only."""

    kind = "counter"

    def _new_child(self):
        return [0.0]

    def _inc(self, child, amount: float) -> None:
        if amount < 0:
            raise ValueError("counters cannot decrease")
        with self._lock:
            child[0] += amount

    def _set(self, child, value) -> None:
        raise TypeError(f"{self.name} is a counter; use inc()")

    _observe = _set

    def _child_value(self, child):
        return child[0]

    def _render_child(self, label_values, child):
        yield (
            f"{self.name}{_labels_str(self.labelnames, label_values)} "
            f"{_fmt_value(child[0])}"
        )

    # convenience for the unlabeled family
    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)


class Gauge(_Metric):
    """A value that goes up and down. ``set()`` / ``inc()``."""

    kind = "gauge"

    def _new_child(self):
        return [0.0]

    def _inc(self, child, amount: float) -> None:
        with self._lock:
            child[0] += amount

    def _set(self, child, value: float) -> None:
        with self._lock:
            child[0] = float(value)

    def _observe(self, child, value) -> None:
        raise TypeError(f"{self.name} is a gauge; use set()/inc()")

    def _child_value(self, child):
        return child[0]

    def _render_child(self, label_values, child):
        yield (
            f"{self.name}{_labels_str(self.labelnames, label_values)} "
            f"{_fmt_value(child[0])}"
        )

    def set(self, value: float) -> None:
        self._unlabeled().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)


class Histogram(_Metric):
    """Fixed-bucket histogram: cumulative ``_bucket{le=...}`` counts
    plus ``_sum`` and ``_count``, the shape Prometheus quantile queries
    expect. ``observe()`` only."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_text: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError("histogram needs at least one bucket")
        if bounds != sorted(set(bounds)):
            raise ValueError("duplicate histogram buckets")
        self.buckets = tuple(bounds)
        super().__init__(name, help_text, labelnames)
        # label-values → (observed value, trace_id, wall ts): the worst
        # recent observation per series, rendered as an exemplar suffix
        # when SWARM_METRICS_EXEMPLARS is set
        self._exemplars: dict[tuple, tuple] = {}  # guarded-by: _lock

    def _new_child(self):
        # [per-bucket counts..., count, sum]
        return [0] * len(self.buckets) + [0, 0.0]

    def _observe(self, child, value: float) -> None:
        value = float(value)
        with self._lock:
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    child[i] += 1
                    break
            child[-2] += 1
            child[-1] += value

    def _observe_exemplar(self, child, label_values, value, trace_id) -> None:
        value = float(value)
        now = time.time()
        with self._lock:
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    child[i] += 1
                    break
            child[-2] += 1
            child[-1] += value
            cur = self._exemplars.get(label_values)
            if (
                cur is None
                or value >= cur[0]
                or now - cur[2] > EXEMPLAR_MAX_AGE_S
            ):
                self._exemplars[label_values] = (value, str(trace_id), now)

    def _inc(self, child, amount) -> None:
        raise TypeError(f"{self.name} is a histogram; use observe()")

    _set = _inc

    def _child_value(self, child):
        n = child[-2]
        return {
            "count": n,
            "sum": child[-1],
            "buckets": {
                _fmt_value(b): int(sum(child[: i + 1]))
                for i, b in enumerate(self.buckets)
            },
        }

    def _render_child(self, label_values, child):
        cumulative = 0
        for i, bound in enumerate(self.buckets):
            cumulative += child[i]
            lv = label_values + (_fmt_value(bound),)
            ln = self.labelnames + ("le",)
            yield f"{self.name}_bucket{_labels_str(ln, lv)} {cumulative}"
        lv = label_values + ("+Inf",)
        ln = self.labelnames + ("le",)
        inf_line = f"{self.name}_bucket{_labels_str(ln, lv)} {child[-2]}"
        if exemplars_enabled():
            # render() calls this OUTSIDE self._lock (child is a copy),
            # so a brief re-acquire for the exemplar read is safe
            with self._lock:
                ex = self._exemplars.get(label_values)
            if ex is not None:
                inf_line += (
                    f' # {{trace_id="{escape_label_value(ex[1])}"}}'
                    f" {_fmt_value(ex[0])}"
                )
        yield inf_line
        base = _labels_str(self.labelnames, label_values)
        yield f"{self.name}_sum{base} {_fmt_value(child[-1])}"
        yield f"{self.name}_count{base} {child[-2]}"


class MetricsRegistry:
    """Thread-safe name → metric table plus scrape-time collectors.

    ``counter``/``gauge``/``histogram`` are get-or-create: the second
    caller with the same name gets the SAME family (so the server and a
    test can both reach ``swarm_queue_depth``), and a kind/label
    mismatch on an existing name raises instead of silently forking.

    Collectors are callables run at the top of every ``render()`` /
    ``snapshot()`` — the hook scrape-time state flows through (queue
    depth read from the state store, engine stats copied from
    ``EngineStats``) without any cost on the instrumented hot paths.
    """

    def __init__(self):
        self._lock = threading.RLock()  # guards: _metrics (reads), _collectors (reads)
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable[[], None]] = []

    # -- factories ----------------------------------------------------
    def _get_or_create(self, cls, name, help_text, labelnames, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.labelnames != tuple(
                    labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{existing.labelnames}"
                    )
                return existing
            metric = cls(name, help_text, labelnames, **kw)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help_text: str = "", labelnames: Sequence[str] = ()
    ) -> Counter:
        return self._get_or_create(Counter, name, help_text, labelnames)

    def gauge(
        self, name: str, help_text: str = "", labelnames: Sequence[str] = ()
    ) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labelnames)

    def histogram(
        self,
        name: str,
        help_text: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help_text, labelnames, buckets=buckets
        )

    # -- collectors ---------------------------------------------------
    def add_collector(self, fn: Callable[[], None]) -> Callable[[], None]:
        """Register a scrape-time callback (returns it, decorator-style)."""
        with self._lock:
            self._collectors.append(fn)
        return fn

    def remove_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                # a broken collector must never take down the scrape
                pass

    # -- exposition ---------------------------------------------------
    def render(self) -> str:
        """Prometheus text format 0.0.4 (the ``/metrics`` body)."""
        self._run_collectors()
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: list[str] = []
        for metric in metrics:
            lines.extend(metric.render())
        return "\n".join(lines) + "\n" if lines else ""

    def snapshot(self) -> dict:
        """JSON-able {name: family snapshot} — what ``bench.py`` attaches
        to its emitted records and the CLI renders as a table."""
        self._run_collectors()
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        return {m.name: m.snapshot() for m in metrics}

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)


# ---------------------------------------------------------------------------
# Exposition parsing — the scrape side (``swarm metrics``,
# tools/check_metrics.py). Strict: a malformed line raises ValueError
# with its line number, which is exactly what the preflight check wants.
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[^ ]+)(?: (?P<ts>-?[0-9]+))?$"
)
_LABEL_PAIR_RE = re.compile(
    r'\s*(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\.)*)"\s*(?:,|$)'
)


def _unescape_label_value(value: str) -> str:
    out = []
    i = 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_exposition(text: str) -> list[tuple[str, dict, float]]:
    """Parse Prometheus text format into ``(name, labels, value)`` rows.

    Raises ``ValueError`` (with the offending line number) on any line
    that is neither a comment, blank, nor a well-formed sample — the
    contract ``tools/check_metrics.py`` enforces in preflight.
    """
    samples: list[tuple[str, dict, float]] = []
    for lineno, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] in ("HELP", "TYPE"):
                if len(parts) < 3:
                    raise ValueError(f"line {lineno}: malformed {parts[1]} comment")
                if parts[1] == "TYPE" and (
                    len(parts) < 4
                    or parts[3] not in (
                        "counter", "gauge", "histogram", "summary", "untyped"
                    )
                ):
                    raise ValueError(f"line {lineno}: bad TYPE: {line!r}")
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample: {line!r}")
        labels: dict = {}
        raw = m.group("labels")
        if raw:
            pos = 0
            while pos < len(raw):
                lm = _LABEL_PAIR_RE.match(raw, pos)
                if not lm:
                    raise ValueError(
                        f"line {lineno}: malformed labels: {raw!r}"
                    )
                labels[lm.group("name")] = _unescape_label_value(
                    lm.group("value")
                )
                pos = lm.end()
        val = m.group("value")
        try:
            value = float(
                {"+Inf": "inf", "-Inf": "-inf", "NaN": "nan"}.get(val, val)
            )
        except ValueError:
            raise ValueError(f"line {lineno}: bad value {val!r}")
        samples.append((m.group("name"), labels, value))
    return samples


#: The process-wide default registry every layer instruments against.
REGISTRY = MetricsRegistry()

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def get_registry() -> MetricsRegistry:
    return REGISTRY
