"""Always-registered ``swarm_monitor_*`` metric families (docs/MONITORING.md).

The continuous-monitoring subsystem (``swarm_tpu/monitor``) turns
one-shot scans into standing rescans: journaled specs, cadence-fired
epochs, per-target verdict diffs and an NDJSON change feed. Every
epoch firing, diff record and steady-state cache outcome reports
through these families, registered at telemetry import time — not on
first monitor registration — so EVERY process's ``/metrics`` carries
them with rendered samples (``tools/check_metrics.py`` requires them
on a server that has never seen a monitor spec). Label combinations
for the diff-record kinds are pre-seeded for the same reason: a
labeled family with no observed combos renders no lines, which would
read as "family missing" to the exposition check.
"""

from __future__ import annotations

from swarm_tpu.telemetry.metrics import REGISTRY

#: epochs actually fired (spec due + admitted + submitted — a shed
#: epoch retries late and only counts when it finally fires)
MONITOR_EPOCHS = REGISTRY.counter(
    "swarm_monitor_epochs_fired_total",
    "Monitor epochs fired through the admission path",
)

#: diff records appended to monitors' change feeds, by kind (``new`` =
#: first verdict for a target, ``changed`` = verdict differs from the
#: prior epoch's, ``resolved`` = a previously reported verdict went
#: empty / the target left the spec)
MONITOR_DIFF_RECORDS = REGISTRY.counter(
    "swarm_monitor_diff_records_total",
    "Change-feed diff records emitted, by kind",
    ("kind",),
)
for _k in ("new", "changed", "resolved"):
    MONITOR_DIFF_RECORDS.labels(kind=_k)
del _k

#: fraction of the most recent completed epoch's targets answered from
#: the shared tier without worker dispatch (the steady-state cost
#: story: ~1.0 on an unchanged fleet, docs/MONITORING.md §Cost model)
MONITOR_RESCAN_HIT_RATIO = REGISTRY.gauge(
    "swarm_monitor_rescan_cache_hit_ratio",
    "Per-epoch fraction of monitor targets served from cache",
)
MONITOR_RESCAN_HIT_RATIO.labels().set(0.0)

#: registered standing monitor specs (paused specs included — they
#: hold a registry slot even while emitting nothing)
MONITOR_SPECS = REGISTRY.gauge(
    "swarm_monitor_standing_specs",
    "Registered standing monitor specs (paused included)",
)
MONITOR_SPECS.labels().set(0)
