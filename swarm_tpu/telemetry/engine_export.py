"""Match-engine stats → metrics registry bridge.

``MatchEngine`` keeps its hot-path counters in a plain
:class:`~swarm_tpu.ops.engine.EngineStats` dataclass (mutating a real
metric per batch would tax the walk). This module registers ONE
scrape-time collector that aggregates the stats of every live engine in
the process into ``swarm_engine_*`` gauges — device seconds, host
confirm work, memo hit rate, batch fill — so the kernel layer shows up
on ``/metrics`` without touching engine hot paths.

Engines are held through a ``WeakSet``: telemetry must never extend an
engine's lifetime (tests construct hundreds).
"""

from __future__ import annotations

import dataclasses
import threading
import weakref

from swarm_tpu.telemetry.metrics import REGISTRY

_lock = threading.Lock()
_engines: "weakref.WeakSet" = weakref.WeakSet()  # guarded-by: _lock (reads)
_collector_added = False  # guarded-by: _lock (reads)

_G = {}


def _gauges() -> dict:
    if not _G:
        g = REGISTRY.gauge
        _G.update(
            engines=g("swarm_engine_instances", "Live MatchEngine instances"),
            rows=g("swarm_engine_rows", "Rows matched by all live engines"),
            batches=g("swarm_engine_batches", "Device batches dispatched"),
            device_seconds=g(
                "swarm_engine_device_seconds",
                "Seconds spent in device kernel dispatch",
            ),
            device_compile_seconds=g(
                "swarm_engine_device_compile_seconds",
                "Seconds spent compiling device match executables "
                "(new batch shapes)",
            ),
            host_confirm_seconds=g(
                "swarm_engine_host_confirm_seconds",
                "Seconds spent in the sparse host confirmation walk",
            ),
            host_confirm_pairs=g(
                "swarm_engine_host_confirm_pairs",
                "(row, matcher) pairs re-checked on the host",
            ),
            host_always_pairs=g(
                "swarm_engine_host_always_pairs",
                "(row, template) hits from the host-only template tail",
            ),
            overflow_rows=g(
                "swarm_engine_overflow_rows",
                "Rows re-run end to end on the host (overflow/truncation)",
            ),
            memo_rows=g(
                "swarm_engine_memo_rows",
                "Rows served by the cross-batch verdict memo",
            ),
            memo_hit_rate=g(
                "swarm_engine_memo_hit_rate",
                "Fraction of rows served by the verdict memo",
            ),
            batch_fill=g(
                "swarm_engine_batch_fill",
                "Mean fraction of batch capacity actually filled",
            ),
            degraded=g(
                "swarm_engine_degraded",
                "Engines currently running with an open device breaker "
                "(CPU-oracle fallback; results stay exact)",
            ),
            degraded_batches=g(
                "swarm_engine_degraded_batches",
                "Batches served by the CPU-oracle fallback after a "
                "device-path failure",
            ),
            device_faults=g(
                "swarm_engine_device_faults",
                "Device-path failures observed (compile/OOM/dispatch)",
            ),
        )
    return _G


def register_engine(engine) -> None:
    """Track a MatchEngine for the aggregate ``swarm_engine_*`` gauges."""
    global _collector_added
    with _lock:
        _engines.add(engine)
        if not _collector_added:
            REGISTRY.add_collector(_collect)
            _collector_added = True


def _collect() -> None:
    from swarm_tpu.telemetry import walk_export as we

    g = _gauges()
    with _lock:
        engines = list(_engines)
    rows = batches = confirm_pairs = always_pairs = overflow = memo = 0
    degraded = degraded_batches = device_faults = 0
    dev_s = confirm_s = compile_s = 0.0
    capacity = 0
    walk_pairs = walk_rounds = walk_pool = 0
    walk_pre_s = 0.0
    phase_s = {"unc": 0.0, "ext": 0.0, "insert": 0.0, "fixup": 0.0}
    for eng in engines:
        s = eng.stats
        rows += s.rows
        batches += s.batches
        confirm_pairs += s.host_confirm_pairs
        always_pairs += s.host_always_pairs
        overflow += s.overflow_rows
        memo += s.memo_slots
        dev_s += s.device_seconds
        compile_s += getattr(s, "device_compile_seconds", 0.0)
        confirm_s += s.host_confirm_seconds
        capacity += s.batches * getattr(eng, "batch_rows", 0)
        degraded_batches += getattr(s, "degraded_batches", 0)
        device_faults += getattr(s, "device_faults", 0)
        walk_pairs += getattr(s, "walk_batched_pairs", 0)
        walk_rounds += getattr(s, "walk_batch_rounds", 0)
        walk_pre_s += getattr(s, "walk_precompute_seconds", 0.0)
        walk_pool = max(walk_pool, getattr(s, "walk_pool_threads", 0))
        phase_s["unc"] += getattr(s, "unc_seconds", 0.0)
        phase_s["ext"] += getattr(s, "ext_seconds", 0.0)
        phase_s["insert"] += getattr(s, "insert_seconds", 0.0)
        phase_s["fixup"] += getattr(s, "fixup_seconds", 0.0)
        board = getattr(eng, "_device_breakers", None)
        if board is not None and board.any_open():
            degraded += 1
    we.WALK_POOL_THREADS.set(walk_pool)
    we.WALK_BATCHED_PAIRS.set(walk_pairs)
    we.WALK_BATCH_ROUNDS.set(walk_rounds)
    we.WALK_PRECOMPUTE_SECONDS.set(walk_pre_s)
    for ph, v in phase_s.items():
        we.WALK_PHASE_SECONDS.labels(phase=ph).set(v)
    g["engines"].set(len(engines))
    g["rows"].set(rows)
    g["batches"].set(batches)
    g["device_seconds"].set(dev_s)
    g["device_compile_seconds"].set(compile_s)
    g["host_confirm_seconds"].set(confirm_s)
    g["host_confirm_pairs"].set(confirm_pairs)
    g["host_always_pairs"].set(always_pairs)
    g["overflow_rows"].set(overflow)
    g["memo_rows"].set(memo)
    g["memo_hit_rate"].set(memo / rows if rows else 0.0)
    g["batch_fill"].set(rows / capacity if capacity else 0.0)
    g["degraded"].set(degraded)
    g["degraded_batches"].set(degraded_batches)
    g["device_faults"].set(device_faults)


def engine_stats_snapshot(engine) -> dict:
    """One engine's EngineStats as a JSON-able dict (bench attachments)."""
    return dataclasses.asdict(engine.stats)
