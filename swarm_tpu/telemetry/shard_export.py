"""Always-registered ``swarm_shard_*`` metric families (docs/SHARDING.md).

The mesh serving path's counters are the scrape-time surface of
:class:`~swarm_tpu.parallel.sharded.ShardedMatcher`. They are created
at telemetry import time — not on first sharded dispatch — so EVERY
process's ``/metrics`` carries the families with a rendered sample
(``tools/check_metrics.py`` requires them on a server that has no mesh
at all; a fleet operator can then tell "no mesh configured" from
"family missing" at a glance).
"""

from __future__ import annotations

from swarm_tpu.telemetry.metrics import REGISTRY

#: mesh axis sizes of the most recently constructed ShardedMatcher
#: (data × model × seq — docs/SHARDING.md; 0 = no mesh in this process)
MESH_AXIS = REGISTRY.gauge(
    "swarm_shard_mesh_axis_size",
    "Mesh axis size of the live sharded matcher (0 = unsharded)",
    ("axis",),
)
#: min per-data-rank real-row occupancy of the most recent sharded
#: batch (the scheduler-aware placement target: every rank should hold
#: ~1/R of the batch's REAL rows, not one rank all-real + R-1 all-pad)
RANK_FILL = REGISTRY.gauge(
    "swarm_shard_rank_fill_ratio",
    "Min per-data-rank real-row fill of the most recent sharded batch",
)
#: slot/overflow plane bytes entering the cross-rank psum per dispatch
#: (global rows × (2·slots + overflow) int32 lanes; 0 when the mesh has
#: no communicating model/seq axis)
PSUM_BYTES = REGISTRY.counter(
    "swarm_shard_psum_bytes_total",
    "Bit-plane bytes combined over ICI by the sharded match psum",
)
#: ppermute halo-exchange bytes per dispatch (2 × halo × rows per
#: stream; 0 on seq-unsharded meshes), labeled by the PHASE whose
#: kernel paid the round — the compacted path fuses the exchange into
#: phase a and carries extended views, so phase="b" stays flat there
#: and only the fused reference twin would ever have charged it
HALO_BYTES = REGISTRY.counter(
    "swarm_shard_halo_bytes_total",
    "Response-stream bytes exchanged as seq-axis ppermute halos",
    ("phase",),
)
#: halo bytes the single-round fused exchange did NOT ship (the
#: historical phase-B re-exchange, charged here instead of to
#: swarm_shard_halo_bytes_total — the fusion win, directly scrapeable)
HALO_SAVED = REGISTRY.counter(
    "swarm_shard_halo_bytes_saved_total",
    "Halo bytes avoided by fusing the seq-axis exchange into phase A",
)
SHARD_DISPATCHES = REGISTRY.counter(
    "swarm_shard_dispatches_total",
    "Batches dispatched through the sharded mesh matcher",
)
#: compacted dispatches whose predecessor's deferred cross-rank
#: reduction was flushed behind this dispatch's phase A — the
#: double-buffered overlap actually happening (collect-forced and
#: inline launches don't count)
OVERLAPPED = REGISTRY.counter(
    "swarm_shard_overlapped_dispatches_total",
    "Sharded dispatches that overlapped the previous batch's deferred "
    "reduction behind their own phase A",
)
#: wall seconds collect() spent blocked on the deferred reduction
#: (launch-if-needed + device wait + the fused host read); ≈0 per
#: batch when the in-flight window keeps the overlap fed
REDUCTION_WAIT = REGISTRY.counter(
    "swarm_shard_reduction_wait_seconds",
    "Seconds collect() stalled waiting on deferred sharded reductions",
)
#: the most recent compacted sharded batch's global max per-row
#: survivor count (the host-read maxima that size the probe rung)
SURVIVOR_MAX = REGISTRY.gauge(
    "swarm_shard_survivor_max",
    "Max per-row prefilter survivors (global pmax) in the most recent "
    "compacted sharded batch",
)

# pre-seed the axis/phase labels so the families always render samples
# (a labeled family with no observed combos renders no lines, which
# would read as "family missing" to the exposition check)
for _ax in ("data", "model", "seq"):
    MESH_AXIS.labels(axis=_ax).set(0)
del _ax
for _ph in ("a", "b"):
    HALO_BYTES.labels(phase=_ph).inc(0)
del _ph
