"""End-to-end span tracing (docs/OBSERVABILITY.md §Tracing).

One scan's latency is spent across six processes-and-layers — gateway
admission, queue wait, scheduler coalescing, device dispatch, host
walk, blob upload — and before this module the only decomposition tool
was grep over flat trace_id'd JSON events. This module adds the
missing structure: lightweight SPANS (span_id / parent_id / trace_id /
wall start / monotonic duration / attrs) recorded per attempt on the
worker, stamped server-side for queue wait, shipped back on the
completed-job ``perf`` field (or ``POST /spans`` for long scans), and
assembled by the server into a per-scan WATERFALL blob under
``_traces/<scan_id>.json`` served at ``GET /trace/<scan_id>``.

Three design rules, in priority order:

1. **Near-zero cost when disabled** (the default). ``span()`` is two
   global loads and one thread-local getattr before returning the
   shared no-op span; the completed-job wire payload is byte-identical
   to the untraced build. Enable with ``SWARM_TRACE=1`` (env) or
   ``tracing.set_enabled(True)`` (runtime override, used by tests and
   the bench so they never mutate os.environ).
2. **Spans never block the data path.** Every collection structure is
   bounded (per-attempt list, per-scan assembly state, scan LRU,
   blob retention) and overflow increments
   ``swarm_trace_spans_dropped_total`` instead of growing; blob IO
   happens only in ``TraceAssembler.flush()`` / sink threads, never
   under a queue or breaker lock.
3. **Clocks**: span ``start`` is wall time (``time.time()`` — it must
   line up with server-stamped ``admitted_at``/``completed_at``
   across processes on one host), span ``duration_s`` is a
   ``perf_counter`` delta (monotonic, immune to NTP steps mid-span).

The always-on FLIGHT recorder is separate from the enable gate: a
fixed ring of recent span/event records per process, dumped to the
blob store when a breaker opens, a job dead-letters, journal recovery
runs, or a chaos-plan fault fires — post-mortems of a kill-9'd or
degraded worker get the last N records of context for free.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Iterable, Optional

from swarm_tpu.telemetry.trace_export import (
    TRACE_ASSEMBLED,
    TRACE_FLIGHT_DUMPS,
    TRACE_SPANS,
    TRACE_SPANS_DROPPED,
)

#: either env key arms tracing process-wide; same truthy set as
#: config.py's bool coercion so SWARM_TRACE_ENABLED matches the
#: ``trace_enabled`` config field's env form
_ENV_KEYS = ("SWARM_TRACE", "SWARM_TRACE_ENABLED")
_TRUTHY = ("1", "true", "yes", "on")

_override: Optional[bool] = None  # set_enabled() runtime override
_env_cached: Optional[bool] = None  # lazy one-time env read


def _read_env() -> bool:
    global _env_cached
    val = any(
        os.environ.get(k, "").strip().lower() in _TRUTHY for k in _ENV_KEYS
    )
    _env_cached = val
    return val


def enabled() -> bool:
    """Is tracing armed in this process? Override wins over env."""
    if _override is not None:
        return _override
    env = _env_cached
    return _read_env() if env is None else env


def set_enabled(on: Optional[bool]) -> None:
    """Force tracing on/off at runtime; ``None`` falls back to the env
    gate (re-read, so tests that toggled os.environ see the change)."""
    global _override, _env_cached
    _override = on
    _env_cached = None


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def make_span(
    name: str,
    trace_id: str,
    start: float,
    duration_s: float,
    parent_id: Optional[str] = None,
    span_id: Optional[str] = None,
    **attrs: Any,
) -> dict:
    """One wire-format span dict (the only span shape — live spans,
    server-stamped spans and synthesized spans all converge here)."""
    span = {
        "span_id": span_id or new_span_id(),
        "parent_id": parent_id,
        "trace_id": trace_id,
        "name": name,
        "start": start,
        "duration_s": duration_s,
    }
    clean = {k: v for k, v in attrs.items() if v is not None}
    if clean:
        span["attrs"] = clean
    return span


# ambient per-thread state: .ctx = active TraceContext, .span = current
# parent span_id for nesting. threading.local, not a lock.
_tls = threading.local()


class TraceContext:
    """One attempt's span collector.

    Created per job attempt on the worker (``attempt_context``), bound
    to the executing thread with ``activate``; spans opened anywhere
    under that binding — engine, scheduler, cache tier, walk pool
    threads that re-activate it — append here. The list is bounded:
    past MAX_SPANS further spans count into
    ``swarm_trace_spans_dropped_total{reason="context_full"}``.
    """

    MAX_SPANS = 2048

    def __init__(self, trace_id: str, name: str = "attempt", **attrs: Any):
        self.trace_id = trace_id
        self.root_id = new_span_id()
        self._root_name = name
        self._root_attrs = {k: v for k, v in attrs.items() if v is not None}
        self._start_wall = time.time()
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()  # guards: _spans, _finished
        self._spans: list[dict] = []
        self._finished = False

    def add(self, span: dict) -> None:
        with self._lock:
            if self._finished or len(self._spans) >= self.MAX_SPANS:
                TRACE_SPANS_DROPPED.labels(reason="context_full").inc()
                return
            self._spans.append(span)
        TRACE_SPANS.inc()
        FLIGHT.record(
            "span", span["name"], trace_id=self.trace_id,
            duration_s=span.get("duration_s"),
        )

    def add_synth(
        self,
        name: str,
        start: float,
        duration_s: float,
        parent_id: Optional[str] = None,
        **attrs: Any,
    ) -> str:
        """Record a span synthesized from pre-measured timings (e.g.
        EngineStats phase deltas); returns its span_id so callers can
        hang children off it."""
        span = make_span(
            name, self.trace_id, start, duration_s,
            parent_id=parent_id or self.root_id, **attrs,
        )
        self.add(span)
        return span["span_id"]

    def span_count(self) -> int:
        with self._lock:
            return len(self._spans)

    def drain(self) -> list[dict]:
        """Hand off collected spans mid-attempt (the POST /spans path
        for long scans) without closing the root."""
        with self._lock:
            out, self._spans = self._spans, []
        return out

    def finish(self) -> list[dict]:
        """Close the attempt root and return the full wire batch
        (root first). Idempotent-ish: a second call returns only spans
        added since the first."""
        duration = time.perf_counter() - self._t0
        with self._lock:
            spans, self._spans = self._spans, []
            first = not self._finished
            self._finished = True
        if not first:
            return spans
        root = make_span(
            self._root_name, self.trace_id, self._start_wall, duration,
            span_id=self.root_id, **self._root_attrs,
        )
        TRACE_SPANS.inc()
        return [root] + spans


class activate:
    """Bind ``ctx`` as the calling thread's ambient trace context for
    the ``with`` body (restores the previous binding on exit). A None
    ctx is a no-op binding — callers never need their own branch for
    the disabled case."""

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx
        self._prev_ctx: Optional[TraceContext] = None
        self._prev_span: Optional[str] = None

    def __enter__(self) -> Optional[TraceContext]:
        self._prev_ctx = getattr(_tls, "ctx", None)
        self._prev_span = getattr(_tls, "span", None)
        _tls.ctx = self._ctx
        _tls.span = None
        return self._ctx

    def __exit__(self, *exc: Any) -> bool:
        _tls.ctx = self._prev_ctx
        _tls.span = self._prev_span
        return False


def attempt_context(trace_id: Optional[str], **attrs: Any) -> Optional[TraceContext]:
    """Worker entry point: a fresh per-attempt context, or None when
    tracing is off / the job carries no trace id."""
    if not trace_id or not enabled():
        return None
    return TraceContext(trace_id, name="attempt", **attrs)


def current_context() -> Optional[TraceContext]:
    return getattr(_tls, "ctx", None)


class _NullSpan:
    """Shared no-op span: the entire cost of ``with span(...)`` when
    tracing is disabled or no context is bound."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set_attrs(self, **attrs: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    #: ``span_id`` and ``start`` are public — callers that need to hang
    #: synthesized children off a live span (the worker's device/walk
    #: spans under "execute") read them after ``__enter__``
    __slots__ = ("_ctx", "_name", "_attrs", "_prev", "span_id", "start", "_t0")

    def __init__(self, ctx: TraceContext, name: str, attrs: dict):
        self._ctx = ctx
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> "_LiveSpan":
        self.span_id = new_span_id()
        self._prev = getattr(_tls, "span", None)
        _tls.span = self.span_id
        self.start = time.time()
        self._t0 = time.perf_counter()
        return self

    def set_attrs(self, **attrs: Any) -> None:
        self._attrs.update(attrs)

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        duration = time.perf_counter() - self._t0
        _tls.span = self._prev
        if exc_type is not None:
            self._attrs.setdefault("error", exc_type.__name__)
        self._ctx.add(make_span(
            self._name, self._ctx.trace_id, self.start, duration,
            parent_id=self._prev or self._ctx.root_id,
            span_id=self.span_id, **self._attrs,
        ))
        return False


def span(name: str, **attrs: Any):
    """Open a child span under the thread's ambient context. Returns
    the shared no-op when tracing is off or no context is bound, so
    call sites never branch."""
    on = _override
    if on is None:
        on = _env_cached
        if on is None:
            on = _read_env()
    if not on:
        return _NULL_SPAN
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return _NULL_SPAN
    return _LiveSpan(ctx, name, attrs)


# ---------------------------------------------------------------------------
# flight recorder


#: pre-seeded dump-reason label values; anything else folds into
#: "other" so fault-plan point names can't explode the label space
_DUMP_REASONS = ("breaker_open", "dead_letter", "journal_recovery", "fault", "other")


class FlightRecorder:
    """Per-process fixed ring of recent span/event records, dumped on
    fault firings so post-mortems have the last moments of context.

    ``record`` is always-on and cheap (one bounded deque append under a
    lock); ``dump`` snapshots the ring synchronously — memory only, so
    it is safe to call under a caller's lock (the breaker dumps from
    inside ``_transition``) — and hands the payload to registered sinks
    on a daemon thread, keeping blob IO off the faulting path.
    """

    RING = 512

    def __init__(self, ring: int = RING):
        self._lock = threading.Lock()  # guards: _ring, _sinks, _seq, _dumps
        self._ring: deque = deque(maxlen=ring)
        self._sinks: list[Callable[[dict], None]] = []
        self._seq = 0
        self._dumps: deque = deque(maxlen=8)

    def record(self, kind: str, name: str, **fields: Any) -> None:
        rec = {"ts": time.time(), "kind": kind, "name": name}
        rec.update({k: v for k, v in fields.items() if v is not None})
        with self._lock:
            self._ring.append(rec)

    def add_sink(self, fn: Callable[[dict], None]) -> Callable[[], None]:
        """Register a dump consumer; returns its unsubscribe."""
        with self._lock:
            self._sinks.append(fn)

        def _remove() -> None:
            with self._lock:
                try:
                    self._sinks.remove(fn)
                except ValueError:
                    pass

        return _remove

    def dump(self, reason: str, detail: Optional[str] = None) -> dict:
        with self._lock:
            self._seq += 1
            payload = {
                "reason": reason,
                "detail": detail,
                "ts": time.time(),
                "seq": self._seq,
                "records": list(self._ring),
            }
            self._dumps.append(payload)
            sinks = list(self._sinks)
        label = reason if reason in _DUMP_REASONS else "other"
        TRACE_FLIGHT_DUMPS.labels(reason=label).inc()
        if sinks:
            threading.Thread(
                target=self._run_sinks, args=(sinks, payload),
                name="flight-dump", daemon=True,
            ).start()
        return payload

    @staticmethod
    def _run_sinks(sinks: list, payload: dict) -> None:
        for fn in sinks:
            try:
                fn(payload)
            except Exception:
                pass  # a broken sink must never mask the original fault

    def last_dumps(self) -> list[dict]:
        with self._lock:
            return list(self._dumps)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)


FLIGHT = FlightRecorder()


def flight_event(name: str, **fields: Any) -> None:
    """Record an always-on event into the process flight ring."""
    FLIGHT.record("event", name, **fields)


def flight_dump(reason: str, detail: Optional[str] = None) -> dict:
    return FLIGHT.dump(reason, detail)


def blob_flight_sink(blobs: Any, prefix: str = "_flight/", retain: int = 20):
    """A dump sink persisting payloads to a blob store under
    ``prefix`` with bounded retention (oldest keys deleted past
    ``retain``). Runs on the dump daemon thread, never under a lock."""

    def _sink(payload: dict) -> None:
        key = "%sdump_%06d_%s.json" % (
            prefix, payload["seq"],
            "".join(c for c in str(payload["reason"]) if c.isalnum() or c in "._-"),
        )
        blobs.put(key, json.dumps(payload, default=str).encode("utf-8"))
        keys = sorted(blobs.list(prefix))
        for old in keys[: max(0, len(keys) - retain)]:
            try:
                blobs.delete(old)
            except Exception:
                pass

    return _sink


# ---------------------------------------------------------------------------
# server-side waterfall assembly


class TraceAssembler:
    """Per-scan waterfall assembly on the server.

    The queue registers a scan at admission, stamps queue-wait spans at
    dispatch, feeds worker span batches as jobs complete, and when the
    last chunk goes terminal the finished waterfall is STAGED under the
    lock and persisted by ``flush()`` — which the queue calls outside
    its own lock (blob IO never runs under ``JobQueueService._lock``).

    The waterfall root is the scan itself: ``start = admitted_at``,
    ``duration = max(completed_at) - admitted_at`` — by construction
    the same quantity ``swarm_gateway_latency_seconds`` observes, which
    is what makes the smoke gate "segments sum within 10% of the
    gateway latency observation" a structural property rather than a
    tuning exercise.
    """

    MAX_SCANS = 256          # open assembly states (oldest evicted)
    MAX_SPANS_PER_SCAN = 4096
    FINALIZED_CACHE = 64     # recent finished waterfalls kept in memory
    RETAIN = 128             # _traces/ blobs kept on disk
    PREFIX = "_traces/"

    def __init__(self, blobs: Any = None):
        self._blobs = blobs
        self._lock = threading.Lock()  # guards: _scans, _ready, _finalized, _written
        self._scans: dict[str, dict] = {}
        self._ready: list[dict] = []
        self._finalized: dict[str, dict] = {}
        self._written: list[str] = []
        if blobs is not None:
            try:
                self._written = sorted(blobs.list(self.PREFIX))
            except Exception:
                self._written = []

    # -- ingestion ---------------------------------------------------------

    def register_scan(
        self,
        scan_id: str,
        trace_id: Optional[str],
        admitted_at: Optional[float],
        chunks: int,
        qos: Any = None,
        tenant: Any = None,
        generation: Any = None,
        done: int = 0,
    ) -> None:
        if not trace_id or not enabled():
            return
        with self._lock:
            st = self._scans.get(scan_id)
            if st is None:
                while len(self._scans) >= self.MAX_SCANS:
                    self._scans.pop(next(iter(self._scans)))
                st = self._scans[scan_id] = {
                    "scan_id": scan_id,
                    "trace_id": trace_id,
                    "admitted_at": admitted_at,
                    "chunks": int(chunks),
                    "done": int(done),
                    "spans": [],
                    "qos": qos,
                    "tenant": tenant,
                    "completed_at": None,
                    "degraded": False,
                }
            if generation is not None:
                st["generation"] = generation

    def add_spans(self, scan_id: str, spans: Iterable[dict]) -> int:
        """Attach worker/server spans to an open scan; spans for scans
        the assembler never saw (tracing flipped on mid-flight,
        LRU-evicted state) are counted as dropped, not errors."""
        batch = [s for s in (spans or []) if isinstance(s, dict) and s.get("name")]
        if not batch:
            return 0
        with self._lock:
            st = self._scans.get(scan_id)
            if st is None:
                TRACE_SPANS_DROPPED.labels(reason="unregistered").inc(len(batch))
                return 0
            return self._add_locked(st, batch)

    def _add_locked(self, st: dict, batch: list[dict]) -> int:
        # requires-lock: _lock
        added = 0
        for s in batch:
            if len(st["spans"]) >= self.MAX_SPANS_PER_SCAN:
                TRACE_SPANS_DROPPED.labels(reason="scan_limit").inc()
                continue
            st["spans"].append(s)
            added += 1
        return added

    def record_queue_wait(self, job: Any, now: float) -> None:
        """Server-stamped enqueue→lease span for one dispatch attempt.

        Attempt 1 waits from scan admission; attempt N>1 waits from the
        failure that requeued it (``failure_history[-1]["ts"]``) — so a
        retried job's waterfall shows each attempt's wait separately.
        """
        trace_id = getattr(job, "trace_id", None)
        if not trace_id or not enabled():
            return
        start = getattr(job, "admitted_at", None)
        attempt = getattr(job, "attempts", 1)
        history = getattr(job, "failure_history", None)
        if attempt > 1 and history:
            try:
                start = float(history[-1]["ts"])
            except (KeyError, TypeError, ValueError, IndexError):
                pass
        if not isinstance(start, (int, float)):
            start = now
        s = make_span(
            "queue-wait", trace_id, float(start),
            max(0.0, now - float(start)),
            job_id=getattr(job, "job_id", None),
            attempt=attempt,
            qos=getattr(job, "qos", None),
        )
        TRACE_SPANS.inc()
        self.add_spans(job.scan_id, [s])

    def job_terminal(
        self,
        scan_id: str,
        job_id: str,
        status: str,
        completed_at: Optional[float],
        spans: Optional[Iterable[dict]] = None,
    ) -> bool:
        """One chunk reached a terminal state; returns True when the
        whole scan just finished (waterfall staged — call ``flush()``
        once outside any queue lock to persist it)."""
        batch = [s for s in (spans or []) if isinstance(s, dict) and s.get("name")]
        with self._lock:
            st = self._scans.get(scan_id)
            if st is None:
                if batch:
                    TRACE_SPANS_DROPPED.labels(
                        reason="unregistered").inc(len(batch))
                return False
            if batch:
                self._add_locked(st, batch)
            st["done"] += 1
            if isinstance(completed_at, (int, float)):
                prev = st["completed_at"]
                if prev is None or completed_at > prev:
                    st["completed_at"] = float(completed_at)
            if status != "complete":
                st["degraded"] = True
            if st["done"] < st["chunks"]:
                return False
            self._scans.pop(scan_id, None)
            self._ready.append(self._build(st))
        TRACE_ASSEMBLED.inc()
        return True

    def assemble_short_circuit(
        self,
        scan_id: str,
        trace_id: str,
        start: float,
        duration_s: float,
        chunks: int,
        spans: Iterable[dict],
        qos: Any = None,
        tenant: Any = None,
    ) -> Optional[dict]:
        """Zero-dispatch gateway completion: the whole waterfall is
        known inline (admission + cache lookup + completion), so build
        and stage it in one shot. Caller flushes — the gateway handler
        thread holds no queue lock, so it can do so immediately."""
        if not trace_id or not enabled():
            return None
        st = {
            "scan_id": scan_id,
            "trace_id": trace_id,
            "admitted_at": start,
            "chunks": int(chunks),
            "done": int(chunks),
            "spans": [s for s in (spans or []) if isinstance(s, dict)],
            "qos": qos,
            "tenant": tenant,
            "completed_at": start + duration_s,
            "degraded": False,
            "short_circuit": True,
        }
        doc = self._build(st)
        with self._lock:
            self._ready.append(doc)
        TRACE_ASSEMBLED.inc()
        return doc

    # -- assembly ----------------------------------------------------------

    def _build(self, st: dict) -> dict:
        """Finalize one scan's waterfall document. Pure computation on
        an already-detached state dict — no locks, no IO."""
        admitted = st.get("admitted_at")
        completed = st.get("completed_at")
        if not isinstance(admitted, (int, float)):
            admitted = min(
                (s["start"] for s in st["spans"]
                 if isinstance(s.get("start"), (int, float))),
                default=time.time(),
            )
        if not isinstance(completed, (int, float)) or completed < admitted:
            completed = max(
                (s["start"] + s.get("duration_s", 0.0) for s in st["spans"]
                 if isinstance(s.get("start"), (int, float))),
                default=admitted,
            )
        root = make_span(
            "scan", st["trace_id"], float(admitted),
            max(0.0, float(completed) - float(admitted)),
            span_id="scan-" + st["scan_id"],
            scan_id=st["scan_id"], chunks=st["chunks"],
            qos=st.get("qos"), tenant=st.get("tenant"),
        )
        spans = []
        for s in st["spans"]:
            c = dict(s)
            # parentless spans hang off the scan root by design; spans
            # whose declared parent is missing stay orphaned so the
            # smoke clause can detect a lossy assembly
            if not c.get("parent_id"):
                c["parent_id"] = root["span_id"]
            spans.append(c)
        # the acceptance quantity: wall-clock COVERAGE of the gateway-
        # latency window by the root's direct children — an interval
        # union, not a plain sum, because one chunk's attempt
        # legitimately overlaps a later chunk's queue-wait (both are
        # real, concurrent root-level segments) and overlap must not
        # read as >100% coverage. Within 10% of the window ⇒ no
        # unattributed blind spots. A small start grace absorbs
        # cross-process wall-clock quantization; the pre-admission
        # handler span deliberately starts before admitted_at and is
        # excluded here.
        root_end = root["start"] + root["duration_s"]
        ivs = sorted(
            (max(c["start"], root["start"]),
             min(c["start"] + (c.get("duration_s") or 0.0), root_end))
            for c in spans
            if c.get("parent_id") == root["span_id"]
            and isinstance(c.get("start"), (int, float))
            and c["start"] >= root["start"] - 0.005
        )
        seg, cov_end = 0.0, None
        for s0, s1 in ivs:
            if s1 <= s0:
                continue
            if cov_end is None or s0 > cov_end:
                seg += s1 - s0
                cov_end = s1
            elif s1 > cov_end:
                seg += s1 - cov_end
                cov_end = s1
        doc = {
            "scan_id": st["scan_id"],
            "trace_id": st["trace_id"],
            "qos": st.get("qos"),
            "tenant": st.get("tenant"),
            "chunks": st["chunks"],
            "status": (
                "short_circuit" if st.get("short_circuit")
                else "degraded" if st.get("degraded") else "complete"
            ),
            "root": root,
            "spans": spans,
            "gateway_latency_s": root["duration_s"],
            "segments_sum_s": seg,
        }
        if "generation" in st:
            doc["generation"] = st["generation"]
        return doc

    # -- persistence / retrieval ------------------------------------------

    def flush(self) -> int:
        """Persist staged waterfalls (memory cache + ``_traces/`` blobs
        with bounded retention). MUST be called with no queue lock held
        — this is the only ingestion-path method that does blob IO."""
        with self._lock:
            ready, self._ready = self._ready, []
            for doc in ready:
                self._finalized[doc["scan_id"]] = doc
                while len(self._finalized) > self.FINALIZED_CACHE:
                    self._finalized.pop(next(iter(self._finalized)))
        if not ready:
            return 0
        if self._blobs is not None:
            stale: list[str] = []
            with self._lock:
                for doc in ready:
                    key = self.PREFIX + doc["scan_id"] + ".json"
                    if key not in self._written:
                        self._written.append(key)
                while len(self._written) > self.RETAIN:
                    stale.append(self._written.pop(0))
            for doc in ready:
                try:
                    self._blobs.put(
                        self.PREFIX + doc["scan_id"] + ".json",
                        json.dumps(doc, default=str).encode("utf-8"),
                    )
                except Exception:
                    pass  # tracing must never fail the completion path
            for key in stale:
                try:
                    self._blobs.delete(key)
                except Exception:
                    pass
        return len(ready)

    def get(self, scan_id: str) -> Optional[dict]:
        """Finished waterfall (memory, then blob), or a live partial
        view of a still-open scan (status ``open``)."""
        with self._lock:
            doc = self._finalized.get(scan_id)
            st = self._scans.get(scan_id)
            if doc is None and st is not None:
                st = dict(st, spans=list(st["spans"]))
        if doc is not None:
            return doc
        if st is not None:
            partial = self._build(st)
            partial["status"] = "open"
            return partial
        if self._blobs is not None:
            try:
                raw = self._blobs.get(self.PREFIX + scan_id + ".json")
            except Exception:
                raw = None
            if raw:
                try:
                    return json.loads(raw.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    return None
        return None


# ---------------------------------------------------------------------------
# waterfall analysis (shared by the CLI renderer and the bench gates)


def waterfall_orphans(doc: dict) -> list[dict]:
    """Spans whose parent_id resolves to no span in the document."""
    ids = {doc["root"]["span_id"]}
    ids.update(s["span_id"] for s in doc.get("spans", ()) if s.get("span_id"))
    return [
        s for s in doc.get("spans", ())
        if s.get("parent_id") not in ids
    ]


def critical_path(doc: dict) -> list[tuple[str, float, float]]:
    """Per-segment attribution: ``(name, seconds, fraction-of-root)``
    for the root's direct children, merged by name, largest first —
    the "queue-wait 61%, device 22%, upload 9%" summary.

    Same-name siblings are merged by interval UNION, not sum: a
    multi-chunk scan's later queue-waits overlap its earlier attempts
    (they all start at admission), and a plain sum would report
    queue-wait at >100% of the scan. The union answers the operator's
    actual question — "for what share of this scan's wall clock was at
    least one chunk waiting / executing?"."""
    root = doc["root"]
    total = root.get("duration_s") or 0.0
    by_name: dict[str, list] = {}
    for s in doc.get("spans", ()):
        if s.get("parent_id") == root["span_id"] and isinstance(
            s.get("start"), (int, float)
        ):
            by_name.setdefault(s["name"], []).append(
                (s["start"], s["start"] + (s.get("duration_s") or 0.0))
            )
    out = []
    for name, ivs in by_name.items():
        ivs.sort()
        secs, cov_end = 0.0, None
        for s0, s1 in ivs:
            if s1 <= s0:
                continue
            if cov_end is None or s0 > cov_end:
                secs += s1 - s0
                cov_end = s1
            elif s1 > cov_end:
                secs += s1 - cov_end
                cov_end = s1
        out.append((name, secs, (secs / total) if total > 0 else 0.0))
    out.sort(key=lambda t: -t[1])
    return out
