"""Always-registered ``swarm_memo_*`` metric families (docs/CACHING.md).

The content-addressed result cache is a two-level hierarchy: the
engine's native verdict memo is the L1, the Redis/S3-backed shared
tier (``swarm_tpu/cache``) sits behind it. Both levels report through
these families, registered at telemetry import time — not on first
cache construction — so EVERY process's ``/metrics`` carries them with
rendered samples (``tools/check_metrics.py`` requires them on a server
that has no engine and no tier at all). Label combinations are
pre-seeded for the same reason: a labeled family with no observed
combos renders no lines, which would read as "family missing" to the
exposition check.
"""

from __future__ import annotations

from swarm_tpu.telemetry.metrics import REGISTRY

#: per-level lookup outcomes. ``tier="l1"`` is the engine's native
#: verdict memo (counted per batch at encode time, rows as the unit);
#: ``tier="shared"`` is the remote tier (counted per DISTINCT content
#: digest actually queried — suppressed re-lookups of a recent miss
#: are not counted, they never left the process).
MEMO_LOOKUPS = REGISTRY.counter(
    "swarm_memo_lookups_total",
    "Result-cache lookups by level (l1 = native memo rows, shared = "
    "remote tier digests) and outcome",
    ("tier", "outcome"),
)
L1_HITS = MEMO_LOOKUPS.labels(tier="l1", outcome="hit")
L1_MISSES = MEMO_LOOKUPS.labels(tier="l1", outcome="miss")
SHARED_HITS = MEMO_LOOKUPS.labels(tier="shared", outcome="hit")
SHARED_MISSES = MEMO_LOOKUPS.labels(tier="shared", outcome="miss")

#: shared-tier writeback outcomes per value family. ``fenced`` =
#: rejected by the tier's fencing-token check (a superseded writer —
#: the poisoning case the discipline exists for); ``error`` = the
#: breaker-wrapped store op failed (tier degraded, entry dropped).
MEMO_WRITEBACKS = REGISTRY.counter(
    "swarm_memo_writebacks_total",
    "Shared result-tier writebacks by value family and outcome",
    ("family", "outcome"),
)
for _f in ("verdict", "confirm"):
    for _o in ("stored", "fenced", "error"):
        MEMO_WRITEBACKS.labels(family=_f, outcome=_o)
del _f, _o

#: TTL/size-policy evictions (docs/CACHING.md): ``ttl`` = an entry
#: whose age exceeded ``cache_ttl_s`` was dropped at lookup (lazy
#: expiry, counted as a miss), ``size`` = the oldest entries were
#: dropped at write time to honor ``cache_max_entries`` per family
#: namespace. Zero forever under the default policy-off config.
MEMO_EVICTIONS = REGISTRY.counter(
    "swarm_memo_evictions_total",
    "Shared result-tier entries evicted by the TTL/size policy",
    ("reason",),
)
for _r in ("ttl", "size"):
    MEMO_EVICTIONS.labels(reason=_r)
del _r

#: process-lifetime shared hit ratio (hits / (hits + misses) over
#: every client in the process; 0 until the first shared lookup)
MEMO_HIT_RATIO = REGISTRY.gauge(
    "swarm_memo_shared_hit_ratio",
    "Shared result-tier hit ratio over this process's lifetime",
)
MEMO_HIT_RATIO.labels().set(0.0)

#: latency of one batched shared-tier lookup round trip (unlabeled so
#: the family renders bucket/sum/count lines even before a tier is
#: attached). Buckets sized for embedded-store (~us) through remote
#: Redis (~ms) round trips.
MEMO_LOOKUP_SECONDS = REGISTRY.histogram(
    "swarm_memo_shared_lookup_seconds",
    "Wall seconds per batched shared result-tier lookup",
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0),
)

#: the tier's invalidation epoch GENERATION (the operator-bump half of
#: the epoch; the corpus-digest half is a hash, not a number). -1 until
#: a client binds.
MEMO_EPOCH = REGISTRY.gauge(
    "swarm_memo_epoch_generation",
    "Shared result-tier epoch generation this process is bound to "
    "(-1 = no tier attached)",
)
MEMO_EPOCH.labels().set(-1.0)
