"""Always-registered ``swarm_workflow_*`` metric families (docs/WORKFLOWS.md).

Device-plane workflow gating surfaces: how much of the workflow corpus
the compiler lowered onto the device, how often the vectorized
gate-apply stage ran, how the per-content step memo (shared-tier family
"w" + the runner's L1) is performing, and how often a row fell back to
the host-loop reference twin. Created at telemetry import time — not on
first runner construction — so EVERY process's ``/metrics`` carries the
families with a rendered sample (``tools/check_metrics.py`` requires
them on a server that has no workflow runner at all).
"""

from __future__ import annotations

from swarm_tpu.telemetry.metrics import REGISTRY

#: workflow steps the compiler lowered into device gate planes for the
#: live corpus (plan ``steps_compiled``; host-only workflows excluded)
WORKFLOW_STEPS_COMPILED = REGISTRY.gauge(
    "swarm_workflow_steps_compiled",
    "Workflow steps lowered into device gate planes (live corpus)",
)
#: batches whose verdict tail ran the vectorized gate-apply stage and
#: shipped per-row workflow planes back to the host
WORKFLOW_GATE_PLANE_BATCHES = REGISTRY.counter(
    "swarm_workflow_gate_plane_batches_total",
    "Match batches decoded through device workflow gate planes",
)
#: per-content workflow gating results served without evaluation, by
#: memo tier (l1 = runner-local dict, shared = tier family "w")
WORKFLOW_STEP_MEMO_HITS = REGISTRY.counter(
    "swarm_workflow_step_memo_hits_total",
    "Workflow gating results served from the step memo",
    ("tier",),
)
WORKFLOW_STEP_MEMO_MISSES = REGISTRY.counter(
    "swarm_workflow_step_memo_misses_total",
    "Workflow gating lookups the step memo could not serve",
)
#: rows gated by the host-loop reference twin instead of device planes
#: (host-only workflows, plane-less rows, or the twin flag)
WORKFLOW_HOST_TWIN_FALLBACKS = REGISTRY.counter(
    "swarm_workflow_host_twin_fallbacks_total",
    "Workflow rows gated by the host-loop twin instead of device planes",
)
# pre-seed both tier labels so the family always renders samples (a
# labeled family with no observed combos renders no lines, which would
# read as "family missing" to the exposition check)
for _tier in ("l1", "shared"):
    WORKFLOW_STEP_MEMO_HITS.labels(tier=_tier).inc(0)
del _tier
