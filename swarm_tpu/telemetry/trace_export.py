"""Always-registered ``swarm_trace_*`` metric families (docs/OBSERVABILITY.md §Tracing).

The span-tracing layer (``telemetry/tracing.py``) reports span
production, drops, waterfall assembly and flight-recorder dumps through
these families, registered at telemetry import time — not on first
span — so EVERY process's ``/metrics`` carries them with rendered
samples (``tools/check_metrics.py`` requires them on a server that has
never traced a scan). Label combinations are pre-seeded for the same
reason: a labeled family with no observed combos renders no lines,
which would read as "family missing" to the exposition check.
"""

from __future__ import annotations

from swarm_tpu.telemetry.metrics import REGISTRY

#: spans recorded (live context-manager spans, server-stamped queue-wait
#: spans, and worker-synthesized device/walk children all count here)
TRACE_SPANS = REGISTRY.counter(
    "swarm_trace_spans_total",
    "Trace spans recorded across all layers",
)

#: spans dropped instead of recorded: ``context_full`` = one attempt's
#: bounded span list overflowed, ``scan_limit`` = one scan's assembly
#: state hit its per-scan bound, ``unregistered`` = spans arrived for a
#: scan the assembler never registered (e.g. tracing enabled mid-scan)
TRACE_SPANS_DROPPED = REGISTRY.counter(
    "swarm_trace_spans_dropped_total",
    "Trace spans dropped instead of recorded, by reason",
    ("reason",),
)
for _r in ("context_full", "scan_limit", "unregistered"):
    TRACE_SPANS_DROPPED.labels(reason=_r)
del _r

#: per-scan waterfalls finalized by the server-side assembler
TRACE_ASSEMBLED = REGISTRY.counter(
    "swarm_trace_assembled_total",
    "Per-scan trace waterfalls assembled",
)

#: flight-recorder ring dumps, by triggering fault class
TRACE_FLIGHT_DUMPS = REGISTRY.counter(
    "swarm_trace_flight_dumps_total",
    "Flight-recorder ring dumps, by trigger reason",
    ("reason",),
)
for _d in ("breaker_open", "dead_letter", "journal_recovery", "fault", "other"):
    TRACE_FLIGHT_DUMPS.labels(reason=_d)
del _d
