"""Always-registered ``swarm_fleet_*`` / ``swarm_worker_drain_*``
families (docs/RESILIENCE.md §Preemption runbook).

The closed-loop elastic fleet (``server/fleet.py``) — EWMA inflow
forecasting, preemptible simulated nodes, graceful worker drain —
reports through these families, registered at telemetry import time so
EVERY process's ``/metrics`` carries them with rendered samples
(``tools/check_metrics.py`` requires them on a server that never
scaled). State/action/outcome label combos are pre-seeded for the same
reason: a labeled family with no observed combos renders no lines.
"""

from __future__ import annotations

from swarm_tpu.telemetry.metrics import REGISTRY

#: live fleet nodes by lifecycle state (SimulatedProvider bookkeeping;
#: real providers report only ``ready``): ``booting`` = spun up, still
#: inside its cold-start window; ``ready`` = servable; ``draining`` =
#: preemption notice received, kill-after-grace pending
FLEET_NODES = REGISTRY.gauge(
    "swarm_fleet_nodes",
    "Fleet nodes by lifecycle state",
    ("state",),
)
for _s in ("booting", "ready", "draining"):
    FLEET_NODES.labels(state=_s).set(0)
del _s

#: the advisor's most recent fleet-size target (forecast-driven,
#: clamped, hysteresis applied) — compare against swarm_fleet_nodes
FLEET_TARGET = REGISTRY.gauge(
    "swarm_fleet_target_nodes",
    "AutoscaleAdvisor's most recent target fleet size",
)
FLEET_TARGET.labels().set(0)

#: the EWMA inflow forecast the target was derived from (jobs/second,
#: aggregated across tenants)
FLEET_FORECAST = REGISTRY.gauge(
    "swarm_fleet_forecast_rate",
    "EWMA-forecasted job inflow rate (jobs/s, all tenants)",
)
FLEET_FORECAST.labels().set(0.0)

#: advisor-applied scale actions (``scale_to_zero`` counts a
#: spin-down that parked the whole fleet for an idle tenant set)
FLEET_SCALE_EVENTS = REGISTRY.counter(
    "swarm_fleet_scale_events_total",
    "Autoscale actions applied to the provider",
    ("action",),
)
for _a in ("spin_up", "spin_down", "scale_to_zero"):
    FLEET_SCALE_EVENTS.labels(action=_a)
del _a

#: provider preemption notices issued (SimulatedProvider draws +
#: explicit/injected preemptions)
FLEET_PREEMPTIONS = REGISTRY.counter(
    "swarm_fleet_preemptions_total",
    "Preemption notices issued against fleet nodes",
)

#: node cold-start wall seconds (spin-up to servable) — the AOT-warm
#: vs cold-compile gap is the scale-to-zero SLO story (docs/AOT.md)
FLEET_COLDSTART = REGISTRY.histogram(
    "swarm_fleet_coldstart_seconds",
    "Node cold-start latency: spin-up to servable",
    buckets=(0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 30.0, 60.0),
)

#: worker drains by outcome: ``completed`` = finished its lease and
#: uploaded before exit, ``spooled`` = output persisted to the disk
#: spool for replay, ``idle`` = nothing in flight, ``aborted`` = the
#: drain itself failed (injected worker.drain fault / hard kill)
WORKER_DRAIN = REGISTRY.counter(
    "swarm_worker_drain_total",
    "Graceful worker drains by outcome",
    ("outcome",),
)
for _o in ("completed", "spooled", "idle", "aborted"):
    WORKER_DRAIN.labels(outcome=_o)
del _o

#: drain-signal-to-exit wall seconds (finish lease + upload/spool +
#: deregister)
WORKER_DRAIN_SECONDS = REGISTRY.histogram(
    "swarm_worker_drain_seconds",
    "Wall seconds from drain signal to worker exit",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
)
