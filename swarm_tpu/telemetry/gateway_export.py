"""Always-registered ``swarm_gateway_*`` metric families (docs/GATEWAY.md).

The multi-tenant gateway (``swarm_tpu/gateway``) fronts the job queue
with admission control: per-tenant token buckets, bounded per-tenant
queues, and composite-pressure load shedding. Every admission decision,
shed, queued-by-tenant depth and streamed result byte reports through
these families, registered at telemetry import time — not on first
gateway construction — so EVERY process's ``/metrics`` carries them
with rendered samples (``tools/check_metrics.py`` requires them on a
server that has not seen a single tenant yet). Label combinations for
the default tenant are pre-seeded for the same reason: a labeled family
with no observed combos renders no lines, which would read as "family
missing" to the exposition check.
"""

from __future__ import annotations

from swarm_tpu.telemetry.metrics import REGISTRY

#: admitted /queue submissions by tenant (one increment per accepted
#: POST, not per chunk — chunk fan-out is the queue's business)
GATEWAY_ADMITTED = REGISTRY.counter(
    "swarm_gateway_admitted_total",
    "Scan submissions admitted through the gateway, by tenant",
    ("tenant",),
)
GATEWAY_ADMITTED.labels(tenant="default")

#: shed /queue submissions by tenant and reason (``rate`` = token
#: bucket empty, ``queue_full`` = per-tenant queue bound, ``pressure``
#: = composite backpressure signal over the shed threshold,
#: ``tenant_limit`` = a NEW tenant id past the gateway_max_tenants
#: cardinality cap — attributed to the default row so client-minted
#: ids can't explode the label space)
GATEWAY_SHED = REGISTRY.counter(
    "swarm_gateway_shed_total",
    "Scan submissions shed (429) by the gateway, by tenant and reason",
    ("tenant", "reason"),
)
for _r in ("rate", "queue_full", "pressure", "tenant_limit"):
    GATEWAY_SHED.labels(tenant="default", reason=_r)
del _r

#: jobs currently waiting in each tenant's dispatch queue (scrape-time
#: collector on the server, like swarm_queue_depth)
GATEWAY_QUEUED = REGISTRY.gauge(
    "swarm_gateway_queued_by_tenant",
    "Jobs waiting in the dispatch queue, by tenant",
    ("tenant",),
)
GATEWAY_QUEUED.labels(tenant="default").set(0)

#: the composite admission pressure signal, 0 = idle, >= shed
#: threshold (default 1.0) = shedding. Deterministic function of the
#: queue/saturation/breaker snapshot (docs/GATEWAY.md)
GATEWAY_PRESSURE = REGISTRY.gauge(
    "swarm_gateway_pressure",
    "Composite gateway admission pressure (0 idle .. >=1 shedding)",
)
GATEWAY_PRESSURE.labels().set(0.0)

#: NDJSON result bytes pushed to /stream/<scan_id> clients
GATEWAY_STREAM_BYTES = REGISTRY.counter(
    "swarm_gateway_stream_bytes_total",
    "Result bytes pushed to /stream clients (NDJSON payload lines)",
)

#: admission-to-verdict latency per QoS class (docs/GATEWAY.md §QoS):
#: observed once per job at its COMPLETE transition (completed_at -
#: admitted_at), and once per gateway-cache short-circuit (the handler
#: elapsed time — the scan completed without a worker). Buckets span
#: the interactive SLO range through bulk batch times. Both class
#: combos pre-seeded so the families render before the first scan.
GATEWAY_LATENCY = REGISTRY.histogram(
    "swarm_gateway_latency_seconds",
    "Admission-to-verdict latency by QoS class",
    ("qos",),
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             10.0, 30.0, 60.0),
)
for _q in ("bulk", "interactive"):
    GATEWAY_LATENCY.labels(qos=_q)
del _q

#: gateway-tier cache short-circuit outcomes (docs/GATEWAY.md §QoS):
#: ``hit`` = every chunk of an interactive submission was fleet-known
#: and the scan completed at the gateway with zero worker dispatch;
#: ``miss`` = at least one chunk unknown, normal admission followed
GATEWAY_SHORT_CIRCUIT = REGISTRY.counter(
    "swarm_gateway_cache_short_circuit_total",
    "Interactive submissions answered (hit) or passed through (miss) "
    "by the gateway-tier result cache",
    ("outcome",),
)
for _o in ("hit", "miss"):
    GATEWAY_SHORT_CIRCUIT.labels(outcome=_o)
del _o
