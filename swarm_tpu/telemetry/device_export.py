"""Always-registered ``swarm_device_*`` staging/compaction families
(docs/DEVICE_MATCH.md).

The split-phase device dispatch's staging-pool and survivor-compaction
counters live on each :class:`~swarm_tpu.ops.match.DeviceDB`; these
are the scrape-time surface. They are created at telemetry import time
— not on first kernel dispatch — so EVERY process's ``/metrics``
carries the families with a rendered sample (``tools/check_metrics.py``
requires them on a server that has no engine at all). The compile-time
families (``swarm_device_compile_*``, ``swarm_device_phase_ms``)
remain lazily created in :mod:`swarm_tpu.ops.match` — they only exist
in processes that actually dispatch.
"""

from __future__ import annotations

from swarm_tpu.telemetry.metrics import REGISTRY

#: batches staged to the device through the dispatch staging pool
#: (every dispatch stages exactly once)
STAGED_BATCHES = REGISTRY.counter(
    "swarm_device_staged_batches_total",
    "Batches staged to the device through the dispatch staging pool",
)
STAGED_BYTES = REGISTRY.counter(
    "swarm_device_staged_bytes_total",
    "Host bytes staged to the device (streams + lengths + status)",
)
#: dispatches whose staged uploads were DONATED to the phase-B kernel
#: (XLA reuses the buffers for outputs); the complement went through
#: the non-donated variant (caller-owned device inputs, or donation
#: disabled via SWARM_DEVICE_DONATE=0)
DONATED_DISPATCHES = REGISTRY.counter(
    "swarm_device_donated_dispatches_total",
    "Dispatches whose staged per-batch buffers were donated to the "
    "kernel",
)
#: dispatches through the survivor-compacted split-phase path (the
#: complement ran the fused legacy arm: SWARM_DEVICE_COMPACT=0, or a
#: corpus with no word tables)
COMPACTED_DISPATCHES = REGISTRY.counter(
    "swarm_device_compacted_dispatches_total",
    "Dispatches through the survivor-compacted split-phase kernel",
)
#: the most recent compacted batch's max per-row survivor count — what
#: the ladder rounded up to pick the phase-B width
SURVIVOR_MAX = REGISTRY.gauge(
    "swarm_device_survivor_max",
    "Max per-row prefilter survivors in the most recent compacted "
    "batch",
)
#: the most recent compacted batch's phase-B candidate width (ladder
#: rung); compare against the global candidate budget to see the
#: compaction win
VERIFY_K = REGISTRY.gauge(
    "swarm_device_verify_k",
    "Phase-B candidate width (survivor ladder rung) of the most "
    "recent compacted batch",
)
