"""Always-registered ``swarm_sched_*`` QoS families (docs/PIPELINE.md).

The scheduler's own feed metrics (``swarm_sched_batches_total`` etc.)
register when ``swarm_tpu.sched`` first imports — fine for per-worker
scrapes, but the latency-tier contract (docs/GATEWAY.md §QoS) gates
preflight on the deadline-flush families being VISIBLE on every
process's ``/metrics``, scheduler imported or not. These two register
at telemetry import time with both class combos pre-seeded, exactly
like ``gateway_export``; ``sched/scheduler.py`` imports them from here
instead of minting its own.
"""

from __future__ import annotations

from swarm_tpu.telemetry.metrics import REGISTRY

#: deadline-forced partial-bucket flushes by class: ``interactive`` =
#: a row older than ``qos_deadline_ms`` pre-empted coalescing into an
#: early express batch; ``bulk`` = the optional ``sched_max_age_ms``
#: knob bounded a trickling scan's tail wait
SCHED_FLUSH_DEADLINE = REGISTRY.counter(
    "swarm_sched_flush_deadline_total",
    "Partial-bucket flushes forced by a lapsed deadline, by QoS class",
    ("qos",),
)
for _q in ("bulk", "interactive"):
    SCHED_FLUSH_DEADLINE.labels(qos=_q)
del _q

#: per-batch coalescing wait by class: the OLDEST row's planner-queue
#: age at submit time (the scheduler-side half of the admission-to-
#: verdict story — what the deadline flush actually bounds)
SCHED_BATCH_AGE = REGISTRY.histogram(
    "swarm_sched_batch_age_seconds",
    "Oldest-row planner wait per submitted batch, by QoS class",
    ("qos",),
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0),
)
for _q in ("bulk", "interactive"):
    SCHED_BATCH_AGE.labels(qos=_q)
del _q
