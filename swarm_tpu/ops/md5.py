"""MD5 on device: batched digests over padded byte streams.

The corpus uses ``md5(body) == "<hex>"`` dsl matchers (e.g.
``technologies/adobe/adobe-coldfusion-detect.yaml``) which previously
forced a host confirmation on every fired row. MD5's block chain is
sequential, but across the batch it vectorizes perfectly: one
``lax.scan`` over 64-byte blocks, 64 unrolled rounds of uint32 ops per
block, every lane a row. Cost is O(W/64) scan steps regardless of how
many templates compare digests.

All arithmetic is uint32 with natural wraparound; no x64 mode needed
(bit lengths fit u32 for any stream the engine encodes).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

# RFC 1321 tables
_K = np.array(
    [int(abs(math.sin(i + 1)) * (1 << 32)) & 0xFFFFFFFF for i in range(64)],
    dtype=np.uint32,
)
_S = np.array(
    [7, 12, 17, 22] * 4 + [5, 9, 14, 20] * 4
    + [4, 11, 16, 23] * 4 + [6, 10, 15, 21] * 4,
    dtype=np.int32,
)
_INIT = np.array(
    [0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476], dtype=np.uint32
)


def _rotl(x, s: int):
    return (x << s) | (x >> (32 - s))


def md5_words(stream, lengths):
    """uint8 [B, W] (zero-padded past each row's length) + int32 [B]
    → digest as uint32 [B, 4], little-endian words (word 0's LE bytes
    are the first 8 hex chars of the usual digest string)."""
    stream = jnp.asarray(stream, dtype=jnp.uint8)
    lengths = jnp.asarray(lengths, dtype=jnp.int32)
    B, W = stream.shape
    # room for the 0x80 marker + 8 length bytes past a full-width row
    ext = jnp.pad(stream, ((0, 0), (0, 64)))
    Wp = W + 64
    idx = jnp.arange(Wp, dtype=jnp.int32)
    L = lengths[:, None]
    msg = jnp.where(idx[None, :] < L, ext, jnp.uint8(0))
    msg = jnp.where(idx[None, :] == L, jnp.uint8(0x80), msg)
    # message bit length, little-endian, in the final 8 bytes of the
    # last block (bit counts fit u32: upper four bytes stay zero)
    pad_end = ((lengths + 9 + 63) // 64) * 64  # [B]
    bitlen = (lengths.astype(jnp.uint32) * 8)[:, None]
    off = idx[None, :] - (pad_end[:, None] - 8)
    len_byte = (
        (bitlen >> (8 * jnp.clip(off, 0, 3))) & 0xFF
    ).astype(jnp.uint8)
    msg = jnp.where((off >= 0) & (off < 4), len_byte, msg)

    # 64-byte blocks → 16 little-endian u32 words each
    nb = Wp // 64
    blocks = msg.reshape(B, nb, 16, 4).astype(jnp.uint32)
    words = (
        blocks[..., 0]
        | (blocks[..., 1] << 8)
        | (blocks[..., 2] << 16)
        | (blocks[..., 3] << 24)
    )  # [B, nb, 16]
    n_blocks = pad_end // 64  # [B]

    k_j = jnp.asarray(_K)

    def per_block(state, inp):
        m, block_i = inp  # m: [B, 16]
        a, b, c, d = state[:, 0], state[:, 1], state[:, 2], state[:, 3]
        for i in range(64):
            if i < 16:
                f = (b & c) | (~b & d)
                g = i
            elif i < 32:
                f = (d & b) | (~d & c)
                g = (5 * i + 1) & 15
            elif i < 48:
                f = b ^ c ^ d
                g = (3 * i + 5) & 15
            else:
                f = c ^ (b | ~d)
                g = (7 * i) & 15
            tmp = d
            d = c
            c = b
            b = b + _rotl(a + f + k_j[i] + m[:, g], int(_S[i]))
            a = tmp
        new = state + jnp.stack([a, b, c, d], axis=1)
        # rows whose padded message ended earlier skip this block
        live = (block_i < n_blocks)[:, None]
        return jnp.where(live, new, state), None

    init = jnp.broadcast_to(jnp.asarray(_INIT), (B, 4)).astype(jnp.uint32)
    state, _ = jax.lax.scan(
        per_block,
        init,
        (jnp.moveaxis(words, 1, 0), jnp.arange(nb, dtype=jnp.int32)),
    )
    # digest convention: the 4 state words little-endian — the compiler
    # prepares m_md5 the same way (np.frombuffer(digest, "<u4"))
    return state
