"""Response rows → fixed-shape uint8 device batches.

XLA needs static shapes; scan responses are ragged byte strings. The
strategy (SURVEY.md §5 "long-context"): pad each part stream (body /
header / all) to a per-batch width, bucket batches by length class to
bound padding waste, and flag rows whose parts were truncated — those
rows are re-checked on the host so truncation can never cost a match
(parity invariant).

Part canonicalization: matcher ``part`` names map onto the three
physical streams; unknown / out-of-band parts (``interactsh_protocol``
etc.) map to None and their matchers evaluate constant-False on both
engines, which keeps device and oracle agreeing exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from swarm_tpu.fingerprints.model import Response

# Physical streams materialized per batch.
STREAMS = ("body", "header", "all")

# matcher part name -> physical stream. Must agree with
# model.Response.part(): every alias here returns exactly that stream's
# bytes from the oracle. Parts absent here return b"" from the oracle
# (interactsh_* …), so their matchers lower to compile-time constants
# (word → False, size → 0∈sizes, regex → matches-empty; negation folded
# in — see compile.lower_matcher). 'host' is oracle-only (real bytes, no
# stream): matchers on it are not device-loweable and force host-always.
PART_TO_STREAM = {
    "body": "body",
    "data": "body",
    "body_1": "body",
    "body_2": "body",
    "header": "header",
    "all_headers": "header",
    "all": "all",
    "raw": "all",
    "response": "all",
}

HOST_ONLY_PARTS = frozenset({"host"})


def stream_for_part(part: str) -> Optional[str]:
    return PART_TO_STREAM.get(part)


def lower_bytes_np(a: np.ndarray) -> np.ndarray:
    """ASCII-lowercase a uint8 array (matches bytes.lower() for ASCII)."""
    is_upper = (a >= 65) & (a <= 90)
    return np.where(is_upper, a + 32, a)


@dataclasses.dataclass
class ResponseBatch:
    """Fixed-shape encoding of B response rows.

    streams: dict stream -> uint8 [B, W_stream]
    lengths: dict stream -> int32 [B] — post-truncation byte length (the
             length of what's actually in the stream array)
    status:  int32 [B]
    truncated: bool [B] — any stream lost bytes to the width cap; these
             rows are host-re-evaluated wholesale, which is what keeps
             size/len semantics exact for them.
    """

    streams: dict
    lengths: dict
    status: np.ndarray
    truncated: np.ndarray
    rows: list  # original Response objects (host fallback + reporting)

    @property
    def batch_size(self) -> int:
        return int(self.status.shape[0])


def _encode_stream(
    parts: Sequence[bytes], width: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    n = len(parts)
    out = np.zeros((n, width), dtype=np.uint8)
    lens = np.zeros((n,), dtype=np.int32)
    trunc = np.zeros((n,), dtype=bool)
    for i, blob in enumerate(parts):
        if len(blob) > width:
            trunc[i] = True
            blob = blob[:width]
        lens[i] = len(blob)
        if blob:
            out[i, : len(blob)] = np.frombuffer(blob, dtype=np.uint8)
    return out, lens, trunc


def round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def pick_width(parts: Sequence[bytes], max_width: int, multiple: int = 128) -> int:
    """Bucket width: smallest lane-aligned width covering the batch,
    capped at ``max_width`` (beyond which rows are truncated + host-flagged)."""
    longest = max((len(p) for p in parts), default=0)
    return max(multiple, min(max_width, round_up(max(longest, 1), multiple)))


def encode_batch(
    rows: Sequence[Response],
    max_body: int = 4096,
    max_header: int = 1024,
    pad_rows_to: Optional[int] = None,
) -> ResponseBatch:
    """Encode responses into the three padded streams.

    ``pad_rows_to`` pads the batch dimension (with empty rows) so the
    jitted kernel sees a small set of static batch shapes.
    """
    rows = list(rows)
    n_real = len(rows)
    if pad_rows_to is not None and pad_rows_to > n_real:
        rows = rows + [Response()] * (pad_rows_to - n_real)

    bodies = [r.part("body") for r in rows]
    headers = [r.part("header") for r in rows]
    alls = [r.part("all") for r in rows]

    streams: dict[str, np.ndarray] = {}
    lengths: dict[str, np.ndarray] = {}
    trunc_any = np.zeros((len(rows),), dtype=bool)
    for name, parts, cap in (
        ("body", bodies, max_body),
        ("header", headers, max_header),
        ("all", alls, max_body + max_header),
    ):
        width = pick_width(parts, cap)
        arr, lens, trunc = _encode_stream(parts, width)
        streams[name] = arr
        lengths[name] = lens
        trunc_any |= trunc

    status = np.array([r.status for r in rows], dtype=np.int32)
    return ResponseBatch(
        streams=streams,
        lengths=lengths,
        status=status,
        truncated=trunc_any,
        rows=rows[:n_real],
    )
