"""Response rows → fixed-shape uint8 device batches.

XLA needs static shapes; scan responses are ragged byte strings. The
strategy (SURVEY.md §5 "long-context"): pad each part stream (body /
header / all) to a per-batch width, bucket batches by length class to
bound padding waste, and flag rows whose parts were truncated — those
rows are re-checked on the host so truncation can never cost a match
(parity invariant).

Part canonicalization: matcher ``part`` names map onto the physical
streams — body/header/all plus the out-of-band interaction streams
(``interactsh_protocol`` → oobp, ``interactsh_request`` → oobr, filled
from Response.oob_* by worker/oob.py's listener). Unknown parts map to
None and their matchers evaluate constant-False on both engines, which
keeps device and oracle agreeing exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from swarm_tpu.fingerprints.model import Response

# Physical streams materialized per batch. Order is load-bearing:
# compiled DBs store indices into this tuple (tiny_stream /
# rx_seq_stream / size_stream) — append only, never reorder.
# oobp/oobr carry the out-of-band interaction data (worker/oob.py):
# observed callback protocols ("http dns") and the raw callback
# requests. They are tiny next to body/all and zero for rows without
# interactions, so bulk passive scans pay almost nothing for them.
STREAMS = ("body", "header", "all", "oobp", "oobr")

# matcher part name -> physical stream. Must agree with
# model.Response.part(): every alias here returns exactly that stream's
# bytes from the oracle. Parts absent here return b"" from the oracle,
# so their matchers lower to compile-time constants (word → False,
# size → 0∈sizes, regex → matches-empty; negation folded in — see
# compile.lower_matcher). 'host' is oracle-only (real bytes, no
# stream): matchers on it are not device-loweable and force host-always.
PART_TO_STREAM = {
    "body": "body",
    "data": "body",
    "body_1": "body",
    "body_2": "body",
    "header": "header",
    "all_headers": "header",
    "all": "all",
    "raw": "all",
    "response": "all",
    "interactsh_protocol": "oobp",
    "interactsh_request": "oobr",
}

HOST_ONLY_PARTS = frozenset({"host"})


def stream_for_part(part: str) -> Optional[str]:
    return PART_TO_STREAM.get(part)


def lower_bytes_np(a: np.ndarray) -> np.ndarray:
    """ASCII-lowercase a uint8 array (matches bytes.lower() for ASCII)."""
    is_upper = (a >= 65) & (a <= 90)
    return np.where(is_upper, a + 32, a)


@dataclasses.dataclass
class ResponseBatch:
    """Fixed-shape encoding of B response rows.

    streams: dict stream -> uint8 [B, W_stream]
    lengths: dict stream -> int32 [B] — post-truncation byte length (the
             length of what's actually in the stream array)
    status:  int32 [B]
    truncated: bool [B] — any stream lost bytes to the width cap; these
             rows are host-re-evaluated wholesale, which is what keeps
             size/len semantics exact for them.
    """

    streams: dict
    lengths: dict
    status: np.ndarray
    truncated: np.ndarray
    rows: list  # original Response objects (host fallback + reporting)
    #: sharded-placement map (docs/SHARDING.md): position of the i-th
    #: REAL row in the encoded batch, when real rows were interleaved
    #: into per-data-rank blocks so every mesh rank gets its share of
    #: live work. None = real rows occupy the leading positions (the
    #: single-device layout).
    row_index: Optional[np.ndarray] = None

    @property
    def batch_size(self) -> int:
        return int(self.status.shape[0])


def round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


class _RotatingPool:
    """Recycled encode buffers for the engine's pipelined feed.

    Zero-allocating a fresh (B, W) matrix per batch was ~40% of the
    encode cost (page faults on first touch); the native pack writes
    every byte of every row (payload + zero tail), so dirty buffers are
    safe to hand back. The rotation depth outlives the pipeline
    window with margin: the scheduler's accounting holds at most
    queue_depth (2) + in-flight device batches (3) + the offloaded
    walk (1) + the encode in progress (1) = 7 encoded batches alive at
    once (sched/scheduler.py), so the earliest reuse at i+depth (8)
    can never alias an in-flight transfer. That accounting is PER
    ENGINE: the pool is module-global, and the margin only covers one
    engine's pipeline at a time — the worker satisfies this by running
    one scheduler pass to completion per chunk (an engine's batches
    drain before another engine dispatches); concurrent same-shape
    pipelining from two engines is outside the reuse contract.

    ONLY the engine's hot path opts in (``encode_batch(...,
    reuse_buffers=True)``): a recycled batch's arrays are OVERWRITTEN
    ``depth`` same-shape encodes later, so callers that retain batches
    must use the default allocating path.
    """

    #: retained-bytes ceiling: production batch shapes vary (last
    #: partial chunk, alive-subset recursion, active-scan waves), so
    #: unbounded per-key caching would grow worker RSS forever. LRU
    #: keys are dropped past the cap — dropping only releases the
    #: POOL's references; in-flight batches keep their arrays alive
    #: through their own refs.
    MAX_BYTES = 256 * 1024 * 1024

    def __init__(self, depth: int = 8):
        self._depth = depth
        # key -> [bufs, next_idx]; dict order = LRU
        self._slots: dict = {}  # guarded-by: _lock (reads)
        self._bytes = 0  # guarded-by: _lock (reads)
        import threading

        self._lock = threading.Lock()

    def get(self, n: int, w: int, role: str) -> np.ndarray:
        # keyed per stream ROLE: one encode draws several same-width
        # buffers (wb == wh == wa is common at small widths), and a
        # shared rotation would hand batch i+1 a buffer batch i is
        # still feeding to the device
        key = (n, w, role)
        with self._lock:
            slot = self._slots.pop(key, None)
            if slot is None:
                slot = [
                    [np.empty((n, w), dtype=np.uint8) for _ in range(self._depth)],
                    0,
                ]
                self._bytes += n * w * self._depth
            self._slots[key] = slot  # re-insert: most-recently-used last
            while self._bytes > self.MAX_BYTES and len(self._slots) > 1:
                old_key, old_slot = next(iter(self._slots.items()))
                if old_key == key:
                    break  # never evict the slot we are handing out
                del self._slots[old_key]
                self._bytes -= (
                    old_key[0] * old_key[1] * len(old_slot[0])
                )
            bufs, i = slot
            slot[1] = (i + 1) % self._depth
            return bufs[i]


_POOL = _RotatingPool()


_NATIVE_ENCODER: Optional[bool] = None


def _native_encoder_available() -> bool:
    """One-time decision: a host without the native lib must not pay a
    failing make-subprocess per batch, and a real binding bug must not
    silently demote the hot path — the failure is logged once."""
    global _NATIVE_ENCODER
    if _NATIVE_ENCODER is None:
        try:
            from swarm_tpu.native import scanio as _nat

            _nat.ensure_fastpack()
            _NATIVE_ENCODER = True
        except Exception as e:
            import sys

            print(
                f"native encoder unavailable ({e!r}); "
                "falling back to Python row packing",
                file=sys.stderr,
            )
            _NATIVE_ENCODER = False
    return _NATIVE_ENCODER


def _width_for(lens: np.ndarray, cap: int, multiple: int = 128) -> int:
    longest = int(lens.max()) if lens.size else 0
    return max(multiple, min(cap, round_up(max(longest, 1), multiple)))


def encode_batch(
    rows: Sequence[Response],
    max_body: int = 4096,
    max_header: int = 1024,
    pad_rows_to: Optional[int] = None,
    reuse_buffers: bool = False,
    build_all: bool = True,
    width_multiple: int = 128,
) -> ResponseBatch:
    """Encode responses into the three padded streams.

    ``pad_rows_to`` pads the batch dimension (with empty rows) so the
    jitted kernel sees a small set of static batch shapes.

    Hot path: TWO C passes straight over the Response objects — one
    metadata pass (lengths/status/concat/OOB flags), one packing pass
    that writes every byte of every row (payload + zero tail) so the
    matrices come from the recycled buffer pool instead of a fresh
    zero-fill (``reuse_buffers``; see _RotatingPool for the aliasing
    contract — engine hot path only). At TPU device rates this host
    encode IS the end-to-end ceiling.

    Part semantics MUST stay in lockstep with model.Response.part():
    "body" is the banner when one is set; "all" is header + CRLF + body
    except for banner rows (aliases the banner) and headerless rows
    (body alone).

    ``build_all=False`` skips materializing (and shipping) the "all"
    stream — a width-1 placeholder goes in its place and the device
    kernel synthesizes the concatenation from the body/header streams
    plus ``lengths["all_hdr"]`` (ops/match.py ``ensure_all_stream``).
    The "all" stream is ~half the encode bytes, so the single-device
    engine path always does this; the seq-sharded path can't (the
    concatenation would cross shard boundaries), so it keeps host
    assembly.
    """
    rows = list(rows)
    n_real = len(rows)
    if pad_rows_to is not None and pad_rows_to > n_real:
        rows = rows + [Response()] * (pad_rows_to - n_real)
    n = len(rows)

    native = _native_encoder_available()
    if native:
        from swarm_tpu.native import scanio as _nat

        blens = np.empty(n, dtype=np.int64)
        hlens = np.empty(n, dtype=np.int64)
        status = np.empty(n, dtype=np.int32)
        concat = np.empty(n, dtype=np.uint8)
        bptr = np.empty(n, dtype=np.uintp)
        hptr = np.empty(n, dtype=np.uintp)
        has_oob = _nat.rows_meta(
            rows, blens, hlens, status, concat, bptr, hptr
        )
        alens = np.where(concat.astype(bool), hlens + 2 + blens, blens)
        wb = _width_for(blens, max_body, width_multiple)
        wh = _width_for(hlens, max_header, width_multiple)
        wa = (
            _width_for(alens, max_body + max_header, width_multiple)
            if build_all
            else 1
        )
        if reuse_buffers:
            body_arr = _POOL.get(n, wb, "body")
            header_arr = _POOL.get(n, wh, "header")
            all_arr = _POOL.get(n, wa, "all") if build_all else None
        else:
            body_arr = np.empty((n, wb), dtype=np.uint8)
            header_arr = np.empty((n, wh), dtype=np.uint8)
            all_arr = np.empty((n, wa), dtype=np.uint8) if build_all else None
        if all_arr is None:
            all_arr = np.zeros((n, 1), dtype=np.uint8)
        _nat.rows_pack(
            n, bptr, blens, hptr, hlens, concat, wb, body_arr,
            wh, header_arr, wa if build_all else 0, all_arr,
        )
    else:
        # toolchain-less deployment: same content, Python loops
        bodies = [r.body if r.banner is None else r.banner for r in rows]
        headers = [r.header for r in rows]
        blens = np.fromiter(
            (len(b) for b in bodies), dtype=np.int64, count=n
        )
        hlens = np.fromiter(
            (len(h) for h in headers), dtype=np.int64, count=n
        )
        status = np.fromiter(
            (r.status for r in rows), dtype=np.int32, count=n
        )
        concat = (
            np.fromiter(
                (r.banner is None for r in rows), dtype=np.bool_, count=n
            )
            & (hlens > 0)
        ).astype(np.uint8)
        alens = np.where(concat.astype(bool), hlens + 2 + blens, blens)
        wb = _width_for(blens, max_body, width_multiple)
        wh = _width_for(hlens, max_header, width_multiple)
        wa = (
            _width_for(alens, max_body + max_header, width_multiple)
            if build_all
            else 1
        )
        body_arr = np.zeros((n, wb), dtype=np.uint8)
        header_arr = np.zeros((n, wh), dtype=np.uint8)
        all_arr = np.zeros((n, wa), dtype=np.uint8)
        for i, blob in enumerate(bodies):
            if blob:
                c = blob[:wb]
                body_arr[i, : len(c)] = np.frombuffer(c, dtype=np.uint8)
        for i, blob in enumerate(headers):
            if blob:
                c = blob[:wh]
                header_arr[i, : len(c)] = np.frombuffer(c, dtype=np.uint8)
        if build_all:
            for i in range(n):
                blob = (
                    headers[i] + b"\r\n" + bodies[i] if concat[i] else bodies[i]
                )[:wa]
                if blob:
                    all_arr[i, : len(blob)] = np.frombuffer(blob, dtype=np.uint8)
        has_oob = any(r.oob_protocols or r.oob_requests for r in rows)

    # OOB streams. Bulk scans never carry interactions, so the common
    # case is two width-1 zero placeholders — no packing, no per-row
    # bookkeeping, ~nothing shipped to device (the kernel's oob word
    # tables then simply can't hit).
    if not has_oob:
        wp = wr = 1
        plens = rlens = np.zeros((n,), dtype=np.int64)
        oobp_arr = np.zeros((n, 1), dtype=np.uint8)
        oobr_arr = np.zeros((n, 1), dtype=np.uint8)
    else:
        oobps = [
            " ".join(r.oob_protocols).encode() if r.oob_protocols else b""
            for r in rows
        ]
        oobrs = [r.oob_requests for r in rows]
        plens = np.fromiter((len(p) for p in oobps), dtype=np.int64, count=n)
        rlens = np.fromiter((len(q) for q in oobrs), dtype=np.int64, count=n)
        wp = _width_for(plens, 128)
        wr = _width_for(rlens, max_body)
        oobp_arr = np.zeros((n, wp), dtype=np.uint8)
        oobr_arr = np.zeros((n, wr), dtype=np.uint8)
        if native:
            _nat.pack_list(oobps, wp, oobp_arr, lens=plens)
            _nat.pack_list(oobrs, wr, oobr_arr, lens=rlens)
        else:
            for i, blob in enumerate(oobps):
                if blob:
                    c = blob[:wp]
                    oobp_arr[i, : len(c)] = np.frombuffer(c, dtype=np.uint8)
            for i, blob in enumerate(oobrs):
                if blob:
                    c = blob[:wr]
                    oobr_arr[i, : len(c)] = np.frombuffer(c, dtype=np.uint8)

    streams = {
        "body": body_arr,
        "header": header_arr,
        "all": all_arr,
        "oobp": oobp_arr,
        "oobr": oobr_arr,
    }
    minb = np.minimum(blens, wb)
    minh = np.minimum(hlens, wh)
    cat = concat.astype(bool)
    if build_all:
        all_len = np.minimum(alens, wa)
    else:
        # synthesized layout: clipped header (+CRLF) + clipped body —
        # the device rebuilds exactly these bytes, so the length must
        # describe the synthesized stream, not the untruncated original
        all_len = np.where(cat, minh + 2 + minb, minb)
    lengths = {
        "body": minb.astype(np.int32),
        "header": minh.astype(np.int32),
        "all": all_len.astype(np.int32),
        # header-prefix length of the synthesized "all" (0 = body-only:
        # banner rows and headerless rows) — ops/match.ensure_all_stream
        "all_hdr": np.where(cat, minh, 0).astype(np.int32),
        "oobp": np.minimum(plens, wp).astype(np.int32),
        "oobr": np.minimum(rlens, wr).astype(np.int32),
    }
    trunc_any = (
        (blens > wb) | (hlens > wh)
        | ((alens > wa) if build_all else False)
        | (plens > wp) | (rlens > wr)
    )
    return ResponseBatch(
        streams=streams,
        lengths=lengths,
        status=status,
        truncated=trunc_any,
        rows=rows[:n_real],
    )
