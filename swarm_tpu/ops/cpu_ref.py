"""Exact CPU reference matcher — the parity oracle.

Implements nuclei matcher semantics faithfully and readably, with zero
vectorization. The device engine (``ops/match.py``) is correct iff it
agrees with this module on every corpus/response pair — that's the
"100% match parity" metric from BASELINE.md, and the backbone of the
test suite (SURVEY.md §4e).

Semantics notes (verified against nuclei's matcher behavior):
- word: substring on the selected part; ``condition`` and/or across the
  word list; ``case-insensitive`` lowercases both sides.
- regex: Go-style RE2 search; evaluated here with Python ``re`` over a
  latin-1 decode so byte values map 1:1 to code points.
- status: response status ∈ list (condition across list entries).
- size: ``len(part)`` ∈ list.
- binary: hex-decoded byte-string substring search on the part.
- dsl: expression list via :mod:`swarm_tpu.fingerprints.dslc`.
- kval: header key lookup (dashes normalized to underscores).
- negative: inverts the matcher verdict.
- operation verdict: ``matchers-condition`` and/or across matchers;
  template verdict: OR across operations.
"""

from __future__ import annotations

import binascii
import dataclasses
import re
from typing import Optional

from swarm_tpu.fingerprints import dslc
from swarm_tpu.fingerprints.model import Matcher, Operation, Response, Template


@dataclasses.dataclass
class MatchResult:
    template_id: str
    matched: bool
    matcher_names: list[str] = dataclasses.field(default_factory=list)
    extractions: list[str] = dataclasses.field(default_factory=list)
    unsupported: bool = False  # hit a matcher type the oracle can't evaluate


def _decode(part: bytes) -> str:
    # latin-1: every byte maps to the same code point, so byte-regexes
    # behave identically to matching over raw bytes.
    return part.decode("latin-1")


# shared compile cache (see dslc.compile_cached): the corpus has ~1.8k
# distinct regexes, which overflows re's internal 512-entry cache
_compile_cached = dslc.compile_cached


def _parse_headers(header_blob: bytes) -> dict[str, str]:
    # single implementation shared with the kval extractor so matcher
    # and extractor normalization can never diverge
    from swarm_tpu.fingerprints import extractors

    return extractors.parse_header_blob(header_blob)


def match_matcher(matcher: Matcher, response: Response) -> Optional[bool]:
    """Evaluate one matcher. Returns None for unsupported types."""
    part = response.part(matcher.part)
    results: list[bool] = []

    if matcher.type == "word":
        hay = part.lower() if matcher.case_insensitive else part
        for word in matcher.words:
            needle = word.encode("utf-8", "surrogateescape")
            if matcher.case_insensitive:
                needle = needle.lower()
            results.append(needle in hay)
    elif matcher.type == "regex":
        text = _decode(part)
        for pattern in matcher.regex:
            try:
                results.append(_compile_cached(pattern).search(text) is not None)
            except re.error:
                return None
    elif matcher.type == "status":
        results = [response.status == s for s in matcher.status]
    elif matcher.type == "size":
        results = [len(part) == s for s in matcher.size]
    elif matcher.type == "binary":
        for hexstr in matcher.binary:
            try:
                needle = binascii.unhexlify(re.sub(r"\s", "", hexstr))
            except (binascii.Error, ValueError):
                return None
            results.append(needle in part)
    elif matcher.type == "dsl":
        env = dslc.build_env(response)
        for expr in matcher.dsl:
            ast = dslc.try_parse(expr)
            if ast is None:
                return None
            try:
                results.append(bool(dslc.evaluate(ast, env)))
            except Exception:
                # one exotic corpus expression (RE2-only regex syntax,
                # mixed-type arithmetic, bad base64…) must degrade to
                # "unsupported", never abort a whole scan
                return None
    elif matcher.type == "kval":
        headers = _parse_headers(response.part("header"))
        results = [k.lower().replace("-", "_") in headers for k in matcher.kval]
    else:
        # json/xpath appear only as *extractors* in the corpus (measured
        # §2.3: matchers are word/regex/status/size/binary/dsl/kval);
        # a matcher of an unknown type degrades to "unsupported"
        return None

    if not results:
        verdict = False
    elif matcher.condition == "and":
        verdict = all(results)
    else:
        verdict = any(results)
    return (not verdict) if matcher.negative else verdict


def extract_one(ex, response: Response) -> list[str]:
    """One extractor's values for one response row."""
    from swarm_tpu.fingerprints import extractors as ext

    if ex.type != "regex":
        return ext.extract_structured(ex, response)
    out: list[str] = []
    text = _decode(response.part(ex.part))
    for pattern in ex.regex:
        try:
            for m in _compile_cached(pattern).finditer(text):
                try:
                    out.append(m.group(ex.group))
                except IndexError:
                    out.append(m.group(0))
        except re.error:
            continue
    return out


def _extract(op: Operation, response: Response) -> list[str]:
    out: list[str] = []
    for ex in op.extractors:
        out.extend(extract_one(ex, response))
    return out


def match_operation(
    op: Operation, response: Response
) -> tuple[bool, list[str], bool]:
    """Returns (matched, hit_matcher_names, any_unsupported)."""
    unsupported = False
    verdicts: list[bool] = []
    names: list[str] = []
    for matcher in op.matchers:
        v = match_matcher(matcher, response)
        if v is None:
            unsupported = True
            v = False
        verdicts.append(v)
        if v and matcher.name:
            names.append(matcher.name)
    if not verdicts:
        # extractor-only operation: nuclei reports such templates iff
        # any extractor extracts — the whole mechanism of the
        # exposures/tokens family (reference worker/artifacts/templates/
        # exposures/tokens/generic/credentials-disclosure.yaml:20-24,
        # ~600 regexes and no matchers). An op with neither matchers
        # nor extractors still never matches.
        matched = bool(op.extractors) and bool(_extract(op, response))
    elif op.matchers_condition == "and":
        matched = all(verdicts)
    else:
        matched = any(verdicts)
    return matched, names, unsupported


def match_template(template: Template, response: Response) -> MatchResult:
    if not response.alive:
        # no response was ever observed — nuclei produces no output for
        # failed requests, and negative matchers must not fire on a
        # phantom empty response (same gate as MatchEngine)
        return MatchResult(template_id=template.id, matched=False)
    matched = False
    names: list[str] = []
    extractions: list[str] = []
    unsupported = False
    for op in template.operations:
        op_hit, op_names, op_unsup = match_operation(op, response)
        unsupported = unsupported or op_unsup
        if op_hit:
            matched = True
            names.extend(op_names)
            extractions.extend(_extract(op, response))
    return MatchResult(
        template_id=template.id,
        matched=matched,
        matcher_names=names,
        extractions=extractions,
        unsupported=unsupported,
    )


def match_corpus(
    templates: list[Template], responses: list[Response]
) -> list[list[MatchResult]]:
    """[row][template] results — the oracle for parity tests."""
    return [[match_template(t, r) for t in templates] for r in responses]
