"""Match-engine compute: exact CPU oracle and XLA device kernels."""
