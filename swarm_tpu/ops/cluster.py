"""Fingerprint clustering: bit-packed pairwise Hamming structure on the MXU.

The TLS-fingerprint clustering workload (BASELINE.json config #5 —
"Internet-wide TLS JA3/JARM hash + fingerprint clustering") needs, for
N fingerprints, the pairwise Hamming-distance structure of their
bit-packed encodings. The N×N distance matrix is O(N²) HBM — 17 GB of
f32 at N=64k — and must never materialize. These kernels tile the
computation so only O(N) ever leaves the chip:

* Each (i, j) tile of the implicit distance matrix is computed in VMEM
  from 0/1 bf16 bit rows via one MXU ``dot_general``:
  ``hamming = popcount_i + popcount_j − 2·(a_i · a_j)``.
* Thresholding and the per-row reductions (neighbor counts; masked
  arg-min for density-peaks parents) fuse into the same kernel, so the
  tile dies in VMEM.

Two reduction kernels + a host-side O(N) label pass give full
density-peaks clustering (Rodriguez & Laio style): ``rho`` = neighbor
count within ``radius``; ``delta``/``parent`` = distance/index of the
nearest strictly-denser row; points with ``delta > radius`` seed
clusters, everything else follows its parent. The Pallas path runs on
TPU; a jit'd XLA fallback with identical semantics (row-tile ``lax.map``
so it also never materializes N²) covers CPU meshes and tests.

This is new capability relative to the reference (Jec00/swarm has no
TLS stack at all — SURVEY.md §2.2 lists only nmap/dnsx/httpx/httprobe/
nuclei); it exists to serve the north-star benchmark configs, not for
behavior parity.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# One fingerprint row = FP_BITS bits = FP_WORDS uint32 words.
FP_BITS = 512
FP_WORDS = FP_BITS // 32

_TILE = 256  # rows per grid tile; VMEM ≈ 3 × 256×512×2B + 256² f32 ≈ 1 MB


# ---------------------------------------------------------------------------
# Host-side packing


def pack_strings(strings: list[bytes | str], n_bits: int = FP_BITS) -> np.ndarray:
    """Fingerprint strings → uint32 [N, n_bits/32] bit rows.

    Each byte contributes its 8 bits, truncated/zero-padded to
    ``n_bits``; two strings differing in one character differ in 1–8
    bits, so Hamming radius in bit units bounds character edits.
    """
    n = len(strings)
    words = n_bits // 32
    out = np.zeros((n, words), dtype=np.uint32)
    for i, s in enumerate(strings):
        raw = s.encode() if isinstance(s, str) else bytes(s)
        raw = raw[: n_bits // 8]
        arr = np.frombuffer(raw, dtype=np.uint8)
        bits = np.unpackbits(arr, bitorder="little")
        pad = np.zeros(n_bits, dtype=np.uint8)
        pad[: bits.shape[0]] = bits
        out[i] = np.packbits(pad, bitorder="little").view(np.uint32)
    return out


def unpack_bits_jnp(packed) -> jnp.ndarray:
    """uint32 [N, W] → 0/1 bf16 [N, W*32] (O(N), stays tiny in HBM)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (packed[:, :, None] >> shifts[None, None, :]) & 1
    return bits.reshape(packed.shape[0], -1).astype(jnp.bfloat16)


def _pad_rows(bits: jnp.ndarray, tile: int) -> jnp.ndarray:
    n = bits.shape[0]
    pad = (-n) % tile
    if pad:
        bits = jnp.pad(bits, ((0, pad), (0, 0)))
    return bits


# ---------------------------------------------------------------------------
# Pallas kernels (TPU)


def _counts_kernel(n_ref, radius_ref, a_ref, b_ref, out_ref):
    """Neighbor counts within radius for one (i, j) tile pair.

    a_ref: [T, FP_BITS] bf16 rows i·T..; b_ref: same for j; out [T, 1]
    int32 accumulated across the j grid axis (self-pair included).
    """
    import jax.experimental.pallas as pl

    j = pl.program_id(1)
    a = a_ref[:]
    b = b_ref[:]
    dot = jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    pa = jnp.sum(a.astype(jnp.float32), axis=1, keepdims=True)
    pb = jnp.sum(b.astype(jnp.float32), axis=1, keepdims=True)
    dist = pa + pb.T - 2.0 * dot  # [T, T] hamming, in VMEM only
    t = a.shape[0]
    n = n_ref[0]
    col = j * t + jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    valid = col < n
    near = (dist <= radius_ref[0]) & valid
    counts = jnp.sum(near.astype(jnp.int32), axis=1, keepdims=True)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = counts

    @pl.when(j > 0)
    def _acc():
        out_ref[:] = out_ref[:] + counts


def _parent_kernel(n_ref, a_ref, b_ref, rho_a_ref, rho_b_ref, dmin_ref, pidx_ref):
    """Masked arg-min: nearest row with strictly higher density.

    Ties in rho break toward the lower index (a total order, so every
    non-peak row has a parent). Accumulates (min dist, arg) over j.
    """
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    j = pl.program_id(1)
    a = a_ref[:]
    b = b_ref[:]
    dot = jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    pa = jnp.sum(a.astype(jnp.float32), axis=1, keepdims=True)
    pb = jnp.sum(b.astype(jnp.float32), axis=1, keepdims=True)
    dist = pa + pb.T - 2.0 * dot
    t = a.shape[0]
    n = n_ref[0]
    row = i * t + jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    col = j * t + jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    rho_a = rho_a_ref[:]  # [T, 1]
    rho_b = rho_b_ref[:]
    denser = (rho_b.T > rho_a) | ((rho_b.T == rho_a) & (col < row))
    ok = denser & (col < n) & (col != row)
    big = jnp.float32(3.0e38)
    masked = jnp.where(ok, dist, big)
    dmin = jnp.min(masked, axis=1, keepdims=True)
    amin = jnp.argmin(masked, axis=1).astype(jnp.int32)[:, None] + j * t

    @pl.when(j == 0)
    def _init():
        dmin_ref[:] = dmin
        pidx_ref[:] = jnp.where(dmin < big, amin, -1)

    @pl.when(j > 0)
    def _acc():
        better = dmin < dmin_ref[:]
        pidx_ref[:] = jnp.where(
            better & (dmin < big), amin, pidx_ref[:]
        )
        dmin_ref[:] = jnp.minimum(dmin_ref[:], dmin)


def _pallas_counts(bits, n, radius, tile: int):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    npad = bits.shape[0]
    grid = (npad // tile, npad // tile)
    return pl.pallas_call(
        _counts_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((tile, FP_BITS), lambda i, j: (i, 0)),
            pl.BlockSpec((tile, FP_BITS), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((tile, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((npad, 1), jnp.int32),
    )(
        jnp.asarray(n, jnp.int32).reshape(1),
        jnp.asarray(radius, jnp.float32).reshape(1),
        bits,
        bits,
    )[:, 0]


def _pallas_parent(bits, rho, n, tile: int):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    npad = bits.shape[0]
    grid = (npad // tile, npad // tile)
    rho_col = rho.astype(jnp.float32)[:, None]
    dmin, pidx = pl.pallas_call(
        _parent_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((tile, FP_BITS), lambda i, j: (i, 0)),
            pl.BlockSpec((tile, FP_BITS), lambda i, j: (j, 0)),
            pl.BlockSpec((tile, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i, j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tile, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((npad, 1), jnp.float32),
            jax.ShapeDtypeStruct((npad, 1), jnp.int32),
        ],
    )(jnp.asarray(n, jnp.int32).reshape(1), bits, bits, rho_col, rho_col)
    return dmin[:, 0], pidx[:, 0]


# ---------------------------------------------------------------------------
# XLA fallback (CPU meshes, tests) — same tile math via lax.map


def _xla_counts_inner(bits, n, radius, tile: int):
    npad = bits.shape[0]
    pop = jnp.sum(bits.astype(jnp.float32), axis=1)
    col_valid = jnp.arange(npad) < n

    def one_tile(i):
        a = jax.lax.dynamic_slice(bits, (i * tile, 0), (tile, FP_BITS))
        pa = jax.lax.dynamic_slice(pop, (i * tile,), (tile,))
        dot = a.astype(jnp.float32) @ bits.astype(jnp.float32).T
        dist = pa[:, None] + pop[None, :] - 2.0 * dot
        near = (dist <= radius) & col_valid[None, :]
        return jnp.sum(near.astype(jnp.int32), axis=1)

    return jax.lax.map(one_tile, jnp.arange(npad // tile)).reshape(-1)


def _xla_parent_inner(bits, rho, n, tile: int):
    npad = bits.shape[0]
    pop = jnp.sum(bits.astype(jnp.float32), axis=1)
    col = jnp.arange(npad)
    big = jnp.float32(3.0e38)

    def one_tile(i):
        a = jax.lax.dynamic_slice(bits, (i * tile, 0), (tile, FP_BITS))
        pa = jax.lax.dynamic_slice(pop, (i * tile,), (tile,))
        rho_a = jax.lax.dynamic_slice(rho, (i * tile,), (tile,))
        row = i * tile + jnp.arange(tile)
        dot = a.astype(jnp.float32) @ bits.astype(jnp.float32).T
        dist = pa[:, None] + pop[None, :] - 2.0 * dot
        denser = (rho[None, :] > rho_a[:, None]) | (
            (rho[None, :] == rho_a[:, None]) & (col[None, :] < row[:, None])
        )
        ok = denser & (col[None, :] < n) & (col[None, :] != row[:, None])
        masked = jnp.where(ok, dist, big)
        dmin = jnp.min(masked, axis=1)
        pidx = jnp.where(dmin < big, jnp.argmin(masked, axis=1), -1)
        return dmin, pidx.astype(jnp.int32)

    dmin, pidx = jax.lax.map(one_tile, jnp.arange(npad // tile))
    return dmin.reshape(-1), pidx.reshape(-1)


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# Fused, jit-cached device entry points. Uncached, every density_cluster
# call re-lowered the Pallas kernels from scratch (~seconds per call —
# the round-2 bench's 1.7-3.1k fp/s was lowering overhead, not compute)
# and made three dispatch+read round trips; the fused form compiles once
# per (shape, tile) and reads back one O(N) result set.


@functools.partial(jax.jit, static_argnames=("tile", "pallas"))
def _cluster_device(packed, n, radius, tile: int, pallas: bool):
    bits = _pad_rows(unpack_bits_jnp(packed), tile)
    npad = bits.shape[0]
    if pallas:
        rho = _pallas_counts(bits, n, radius, tile)
    else:
        rho = _xla_counts_inner(bits, n, radius, tile)
    rho_j = jnp.where(
        jnp.arange(npad) < n, rho.astype(jnp.float32), -1.0
    )
    if pallas:
        dmin, pidx = _pallas_parent(bits, rho_j, n, tile)
    else:
        dmin, pidx = _xla_parent_inner(bits, rho_j, n, tile)
    return rho, dmin, pidx


@functools.partial(jax.jit, static_argnames=("tile", "pallas"))
def _counts_device(packed, n, radius, tile: int, pallas: bool):
    bits = _pad_rows(unpack_bits_jnp(packed), tile)
    if pallas:
        return _pallas_counts(bits, n, radius, tile)
    return _xla_counts_inner(bits, n, radius, tile)


@functools.partial(jax.jit, static_argnames=("tile", "pallas"))
def _parent_device(packed, rho, n, tile: int, pallas: bool):
    bits = _pad_rows(unpack_bits_jnp(packed), tile)
    npad = bits.shape[0]
    rho_j = jnp.where(
        jnp.arange(npad) < n,
        jnp.pad(rho.astype(jnp.float32), (0, npad - rho.shape[0])),
        -1.0,
    )
    if pallas:
        return _pallas_parent(bits, rho_j, n, tile)
    return _xla_parent_inner(bits, rho_j, n, tile)


# ---------------------------------------------------------------------------
# Public API


def neighbor_counts(
    packed: np.ndarray, radius: float, tile: int = _TILE
) -> np.ndarray:
    """rho[i] = #{j : hamming(i, j) ≤ radius} (includes i itself)."""
    n = packed.shape[0]
    if n == 0:
        return np.zeros(0, dtype=np.int32)
    tile = min(tile, max(8, 1 << (n - 1).bit_length()))
    rho = _counts_device(
        jnp.asarray(packed), jnp.int32(n), jnp.float32(radius), tile,
        _use_pallas(),
    )
    return np.asarray(rho[:n])


def nearest_denser(
    packed: np.ndarray, rho: np.ndarray, tile: int = _TILE
) -> tuple[np.ndarray, np.ndarray]:
    """(delta, parent): distance/index of nearest strictly-denser row.

    The unique global density maximum gets parent −1 and delta +inf-ish.
    """
    n = packed.shape[0]
    if n == 0:
        return np.zeros(0, np.float32), np.zeros(0, np.int32)
    tile = min(tile, max(8, 1 << (n - 1).bit_length()))
    dmin, pidx = _parent_device(
        jnp.asarray(packed), jnp.asarray(rho, jnp.float32), jnp.int32(n),
        tile, _use_pallas(),
    )
    return np.asarray(dmin[:n]), np.asarray(pidx[:n])


def density_cluster(
    packed: np.ndarray, radius: float, tile: int = _TILE
) -> tuple[np.ndarray, np.ndarray]:
    """Full density-peaks clustering → (labels [N] int32, rho [N] int32).

    Rows whose nearest-denser neighbor is farther than ``radius`` seed
    clusters; every other row joins its parent's cluster. Two device
    passes (O(N²) compute, O(N) memory) + one O(N) host pass.
    """
    n = packed.shape[0]
    if n == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    tile = min(tile, max(8, 1 << (n - 1).bit_length()))
    # one fused dispatch, one device->host read for all three arrays
    rho_d, dmin_d, pidx_d = _cluster_device(
        jnp.asarray(packed), jnp.int32(n), jnp.float32(radius), tile,
        _use_pallas(),
    )
    rho = np.asarray(rho_d[:n])
    delta = np.asarray(dmin_d[:n])
    parent = np.asarray(pidx_d[:n])
    # vectorized label pass (the per-row Python loop was ~2-5 ms per
    # call — visible at bench rates). Peaks seed clusters numbered in
    # densest-first stable order (same ids as the loop produced);
    # everyone else resolves to its chain's first peak by pointer
    # jumping — parents are strictly (denser | equal-rho-lower-index),
    # so chains are acyclic and terminate at a peak in <= log2(n) hops.
    peaks = (parent < 0) | (delta > radius)
    order = np.argsort(-rho, kind="stable")  # densest first
    peak_ids = order[peaks[order]]
    label_of = np.full(n, -1, dtype=np.int32)
    label_of[peak_ids] = np.arange(len(peak_ids), dtype=np.int32)
    anchor = np.where(peaks, np.arange(n), parent)
    while True:
        nxt = anchor[anchor]
        if np.array_equal(nxt, anchor):
            break
        anchor = nxt
    labels = label_of[anchor]
    assert (labels >= 0).all(), "chain did not terminate at a peak"
    return labels, rho


def pairwise_hamming(packed_a: np.ndarray, packed_b: np.ndarray) -> np.ndarray:
    """Small-N explicit distance matrix (diagnostics / tests only)."""
    a = np.unpackbits(packed_a.view(np.uint8), axis=1, bitorder="little")
    b = np.unpackbits(packed_b.view(np.uint8), axis=1, bitorder="little")
    return (
        a.sum(1)[:, None] + b.sum(1)[None, :] - 2 * (a.astype(np.int32) @ b.T)
    ).astype(np.int32)
