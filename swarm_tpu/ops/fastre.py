"""Candidate-anchored regex execution for the host walk.

The fresh-content host walk's cost is dominated by Python ``re`` scans
over response bytes: extraction regexes on every hit row and the rare
slow confirm regexes (e.g. waf-detect's ``[a-zA-Z0-9]{,60}.cloudfront
.net`` at ~2 ms per scan). Both are accelerated *exactly* — never
approximately — by two pattern facts derived from the sre parse tree:

1. **Required literals** (``compile.required_literal_set``): every
   match contains one of a small set of lowered literals. If none is
   present (one C-speed ``bytes.find`` per literal over the lowered
   part), there is no match — skip the regex entirely.
2. **Mandatory prefix byte classes**: the set of bytes a match's
   first (and second) character can be. Every match start sits at a
   *candidate* position whose bytes satisfy these classes; candidates
   are found at C speed (``bytes.find`` loops for narrow classes,
   a table-translate scan otherwise) and the regex runs as anchored
   ``rex.match`` attempts only there.

``finditer_values`` reproduces ``re.finditer`` semantics exactly
(leftmost, non-overlapping, continue at ``m.end()``) because every
possible match start is a candidate and candidates are tried in
order; patterns whose first position is optional or anchored simply
don't qualify and fall back to plain ``re``. Equivalence is pinned by
a randomized fuzz suite (tests/test_fastre.py) over the full
reference-corpus regex population.

Reference workload: /root/reference/worker/artifacts/templates —
e.g. miscellaneous/robots-txt-endpoint.yaml's ``(?m:\\s(/[[:alpha:]]+
[[:graph:]]+))`` runs on every 200-status row in a scan.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import numpy as np

from swarm_tpu.fingerprints import dslc, regexlin
from swarm_tpu.fingerprints.compile import (
    required_literal_cnf,
    required_literal_ladder,
)

try:  # py3.11+
    import re._parser as sre_parse
except ImportError:  # pragma: no cover
    import sre_parse  # type: ignore


#: classes with at most this many member bytes use a bytes.find loop
#: (C speed, zero numpy overhead); wider classes use one translate scan
_NARROW = 4

#: candidate scans bail to plain re when the narrower prefix class is
#: denser than this fraction of the haystack (no pruning to be had)
_DENSITY_BAIL = 0.25

#: more candidates than this and per-candidate anchored match attempts
#: lose to re's own scan loop — fall back
_MAX_CANDS = 96


@dataclasses.dataclass
class PatternInfo:
    """Host-side acceleration facts for one regex pattern."""

    ok: bool  # pattern compiled under Python re
    rex: Optional[re.Pattern]
    # lowered required literals: every match contains >= 1 of them
    literals: Optional[list[bytes]]
    # mandatory prefix byte classes (bool[256] each), len 0..2; the
    # EMPTY list means "no usable prefix" -> no candidate scan
    prefix: list
    # CNF of required-literal groups: every match contains >= 1 member
    # of EVERY group (strictly stronger absent-proof than `literals`;
    # None when the walk yields no mandatory groups)
    cnf: Optional[list] = None
    # index (0 or 1) of the narrower prefix class, its member bytes
    # (when narrow enough for find loops), and the partner class
    scan_pos: int = 0
    scan_bytes: Optional[bytes] = None
    # translate table mapping member bytes -> 0x01 for the wide path
    scan_table: Optional[bytes] = None
    # multi-byte literal prefix (every prefix class a single byte):
    # candidates come from one substring-find loop — as fast as re's
    # own literal-prefix optimizer, but it composes with our anchored
    # non-overlap walk
    needle: Optional[bytes] = None
    # partner class as a 256-byte membership table (bytes indexing is
    # ~5x cheaper than a numpy bool-mask scalar lookup per candidate)
    partner_table: Optional[bytes] = None
    # native VM program (ops/crexc + native/crex.cpp) — when set, the
    # whole finditer/search runs in one GIL-released C call; None keeps
    # the candidate-scan + anchored re.match path
    cprog: Optional[object] = None
    # counter-free program for the linear-time NFA existence scan
    # (crexc.compile_crex_nfa); None when out of subset / oversized
    nfa: Optional[object] = None


def _prefix_classes(pattern: str) -> list:
    """Mandatory first/second byte-class masks of ``pattern``.

    Walks the top of the parse tree collecting positions every match
    must consume, stopping at anything optional, anchored, or too
    complex. Returns [] when no mandatory prefix is derivable.
    """
    try:
        tree = regexlin.parse_quiet(pattern)
    except re.error:
        return []
    if tree.state.flags & re.MULTILINE:
        # MULTILINE only changes ^/$ semantics; AT tokens stop the
        # walk anyway, so masks stay valid — no special handling
        pass
    if tree.state.flags & re.ASCII:
        # class/category masks below are computed under Unicode
        # semantics; (?a) flips what \w/\s/[^...] match for bytes
        # >= 0x80, so a mask-driven scan would silently drop matches.
        # No corpus pattern uses (?a) today — force the exact fallback.
        return []
    ci = bool(tree.state.flags & re.IGNORECASE)
    dotall = bool(tree.state.flags & re.DOTALL)

    def walk(seq, ci: bool, dotall: bool, depth: int = 0) -> list:
        if depth > 8:
            return []
        masks: list = []
        for op, arg in seq:
            if len(masks) >= 2:
                break
            name = str(op)
            try:
                if name == "LITERAL":
                    if arg > 255:
                        return masks  # can't match latin-1 text anyway
                    m = np.zeros(256, dtype=bool)
                    m[arg] = True
                    if ci:
                        c = chr(arg)
                        for o in (c.lower(), c.upper()):
                            if len(o) == 1 and ord(o) < 256:
                                m[ord(o)] = True
                    masks.append(m)
                elif name == "NOT_LITERAL":
                    m = np.ones(256, dtype=bool)
                    if 0 <= arg <= 255:
                        m[arg] = False
                        if ci:
                            c = chr(arg)
                            for o in (c.lower(), c.upper()):
                                if len(o) == 1 and ord(o) < 256:
                                    m[ord(o)] = False
                    masks.append(m)
                elif name == "IN":
                    masks.append(regexlin._class_mask(arg, ci))
                elif name == "ANY":
                    m = np.ones(256, dtype=bool)
                    if not dotall:
                        m[ord("\n")] = False
                    masks.append(m)
                elif name == "SUBPATTERN":
                    _gid, add_f, del_f, sub = arg
                    if add_f & re.ASCII:
                        break  # scoped (?a:) — same mask hazard as above
                    sub_ci = (ci or bool(add_f & re.IGNORECASE)) and not bool(
                        del_f & re.IGNORECASE
                    )
                    # scoped (?s:)/(?-s:) changes what '.' matches
                    # INSIDE the group — propagate, or '.' candidates
                    # would silently exclude newlines
                    sub_dotall = (
                        dotall or bool(add_f & re.DOTALL)
                    ) and not bool(del_f & re.DOTALL)
                    masks.extend(
                        walk(sub, sub_ci, sub_dotall, depth + 1)
                        [: 2 - len(masks)]
                    )
                    break  # offset past the group is not tracked
                elif name == "BRANCH":
                    buckets: list = []
                    for branch in arg[1]:
                        bm = walk(branch, ci, dotall, depth + 1)
                        if not bm:
                            return masks  # one branch unconstrained
                        buckets.append(bm)
                    depth_n = min(len(b) for b in buckets)
                    for i in range(min(depth_n, 2 - len(masks))):
                        u = np.zeros(256, dtype=bool)
                        for b in buckets:
                            u |= b[i]
                        masks.append(u)
                    break
                elif name in ("MAX_REPEAT", "MIN_REPEAT"):
                    lo, _hi, sub = arg
                    if lo == 0:
                        break  # optional: nothing mandatory from here
                    masks.extend(
                        walk(sub, ci, dotall, depth + 1)[: 2 - len(masks)]
                    )
                    break  # repeat tail offset unknown
                else:
                    break  # AT (anchors), GROUPREF, assertions, ...
            except regexlin._Unsupported:
                break
        return masks

    return walk(list(tree), ci, dotall)[:2]


_INFO_CACHE: dict = {}
_INFO_CACHE_MAX = 8192


def analyze(pattern: str) -> PatternInfo:
    info = _INFO_CACHE.get(pattern)
    if info is not None:
        return info
    try:
        # dslc.compile_cached: one warning-suppressed compile + one
        # shared pattern cache with the DSL evaluator / CPU oracle
        rex = dslc.compile_cached(pattern)
        ok = True
    except re.error:
        rex, ok = None, False
    # necessary-literal ladder: prefer 4-byte grams, relax to 3/2 for
    # patterns without one (email-style classes) — a necessary set at
    # ANY length is sound, and extraction gating (engine
    # _accel_extract_regex/_extract_pending) needs SOME set to skip
    # non-matching patterns of multi-hundred-pattern extractors
    literals = required_literal_ladder(pattern) if ok else None
    cnf = required_literal_cnf(pattern) if ok else None
    if cnf and literals:
        # the ladder's set usually reappears among the CNF groups —
        # drop the value-equal duplicate so literals_absent never
        # re-scans the same group
        cnf = [g for g in cnf if g != literals] or None
    prefix = _prefix_classes(pattern) if ok else []
    cprog = None
    nfa = None
    if ok:
        from swarm_tpu.ops.crexc import compile_crex, compile_crex_nfa

        cprog = compile_crex(pattern)
        nfa = compile_crex_nfa(pattern)
    info = PatternInfo(
        ok=ok, rex=rex, literals=literals, prefix=prefix, cprog=cprog,
        nfa=nfa, cnf=cnf,
    )
    if prefix:
        counts = [int(m.sum()) for m in prefix]
        if len(prefix) == 2 and counts[0] == 1 and counts[1] == 1:
            info.needle = bytes(
                [int(np.flatnonzero(prefix[0])[0]),
                 int(np.flatnonzero(prefix[1])[0])]
            )
        else:
            info.scan_pos = int(np.argmin(counts))
            scan_mask = prefix[info.scan_pos]
            if counts[info.scan_pos] <= _NARROW:
                info.scan_bytes = bytes(
                    int(b) for b in np.flatnonzero(scan_mask)
                )
            else:
                info.scan_table = scan_mask.astype(np.uint8).tobytes()
            if len(prefix) > 1:
                info.partner_table = (
                    prefix[1 - info.scan_pos].astype(np.uint8).tobytes()
                )
    if len(_INFO_CACHE) >= _INFO_CACHE_MAX:
        for k in list(_INFO_CACHE)[: _INFO_CACHE_MAX // 2]:
            del _INFO_CACHE[k]
    _INFO_CACHE[pattern] = info
    return info


def literals_absent(info: PatternInfo, lowered: bytes) -> bool:
    """True when the pattern CERTAINLY has no match in the part whose
    ASCII-lowered bytes are ``lowered``: some required-literal group
    (every match must contain one of its members) is fully absent.
    Groups are rarity-ordered, so the first check is the most likely
    proof; the single `literals` set rides first for continuity."""
    lits = info.literals
    if lits and all(lowered.find(lit) < 0 for lit in lits):
        return True
    if info.cnf:
        for group in info.cnf:
            if all(lowered.find(lit) < 0 for lit in group):
                return True
    return False


def _candidates(info: PatternInfo, data: bytes) -> Optional[list]:
    """Sorted possible match-start positions, or None to fall back.

    Pure Python on purpose: a native twin was measured SLOWER — the
    scan is a few bytes.find calls (already C inside CPython), and a
    ctypes dispatch with marshalled nullable buffers costs ~7 µs/call
    vs ~3 µs for this loop on realistic parts."""
    n = len(data)
    if n == 0:
        return []
    if info.needle is not None:
        # both prefix positions are fixed bytes: one substring-find
        # loop yields the candidates directly
        out = []
        i = data.find(info.needle)
        while i >= 0:
            out.append(i)
            if len(out) > _MAX_CANDS:
                return None
            i = data.find(info.needle, i + 1)
        return out
    pos_off = info.scan_pos  # candidate start = scan hit - pos_off
    if info.scan_bytes is not None:
        hits: list = []
        for byte in info.scan_bytes:
            needle = bytes((byte,))
            i = data.find(needle)
            while i >= 0:
                hits.append(i)
                if len(hits) > _MAX_CANDS * 4:
                    return None
                i = data.find(needle, i + 1)
        if len(info.scan_bytes) > 1:
            hits.sort()
    elif info.scan_table is not None:
        marked = data.translate(info.scan_table)
        if len(marked) * _DENSITY_BAIL < marked.count(1):
            return None
        hits = []
        i = marked.find(1)
        while i >= 0:
            hits.append(i)
            if len(hits) > _MAX_CANDS * 4:
                return None
            i = marked.find(1, i + 1)
    else:
        return None
    if not hits:
        return []
    other = 1 - pos_off
    partner = info.partner_table
    out = []
    for h in hits:
        start = h - pos_off
        if start < 0:
            continue
        if partner is not None:
            j = start + other
            if j >= n or not partner[data[j]]:
                continue
        out.append(start)
        if len(out) > _MAX_CANDS:
            return None
    return out


def finditer_values(
    pattern: str, data: bytes, text: str, group
) -> Optional[list]:
    """Exactly ``[m.group(group) or m.group(0) for m in finditer]`` —
    the extraction loop's semantics (cpu_ref.extract_one) — or None
    when the pattern can't be accelerated (caller falls back)."""
    info = analyze(pattern)
    if not info.ok:
        return None
    if isinstance(group, int):
        from swarm_tpu.native import crex as ncrex

        if ncrex.usable(info.cprog):
            spans = ncrex.finditer_spans(info.cprog, data, group)
            if spans is not None:
                return [None if s < 0 else text[s:e] for s, e in spans]
            # resource fallback: keep going on the candidate path below
    if not info.prefix:
        return None
    cands = _candidates(info, data)
    if cands is None:
        return None
    out: list = []
    if not cands:
        return out
    rex = info.rex
    pos = 0
    for c in cands:
        if c < pos:
            continue
        m = rex.match(text, c)
        if m is None:
            continue
        try:
            out.append(m.group(group))
        except IndexError:
            out.append(m.group(0))
        # a mandatory first position means matches are never empty, so
        # finditer's next scan resumes exactly at m.end()
        pos = m.end()
    return out


def search_bool(pattern: str, data: bytes, text: str) -> Optional[bool]:
    """Exactly ``re.search(pattern, text) is not None``, or None when
    not acceleratable."""
    info = analyze(pattern)
    if not info.ok:
        return None
    from swarm_tpu.native import crex as ncrex

    # linear-time NFA existence first: worst-case-bounded (no budget,
    # no backtracking) — the leading-unbounded-repeat shapes that send
    # the backtracker O(n^2) (email-extractor: 19 ms/row) answer in
    # tens of microseconds here, and existence IS search's verdict
    if info.nfa is not None:
        got = ncrex.exists(info.nfa, data)
        if got is not None:
            return got
    if ncrex.usable(info.cprog):
        got = ncrex.search(info.cprog, data)
        if got is not None:
            return got
    if not info.prefix:
        return None
    cands = _candidates(info, data)
    if cands is None:
        return None
    rex = info.rex
    return any(rex.match(text, c) is not None for c in cands)
