"""MatchEngine: the user-facing exact fingerprint engine.

Composes the pieces: template corpus → CompiledDB (once), responses →
padded batches → device kernel → sparse host confirmation with the
exact CPU oracle. The result is bit-identical to running the oracle on
every (row, template) pair — the device does ~all the work, the host
touches only uncertain pairs that actually fired and the (small,
reported) host-always template tail.

This replaces the reference worker's subprocess shell-outs to
nmap/-sV//nuclei (``worker/worker.py:79-84``) as the compute engine.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence

import numpy as np

from swarm_tpu.fingerprints.compile import CompiledDB, compile_corpus
from swarm_tpu.fingerprints.model import Response, Template
from swarm_tpu.ops import cpu_ref
from swarm_tpu.ops.encoding import encode_batch, round_up
from swarm_tpu.ops.match import DeviceDB


@dataclasses.dataclass
class RowMatches:
    """Exact match set for one response row."""

    template_ids: list
    extractions: dict  # template_id -> list[str]
    confirmed_on_host: int = 0  # uncertain pairs the host re-checked


@dataclasses.dataclass
class EngineStats:
    rows: int = 0
    batches: int = 0
    device_seconds: float = 0.0
    host_confirm_seconds: float = 0.0
    host_confirm_pairs: int = 0
    host_always_pairs: int = 0
    overflow_rows: int = 0


class MatchEngine:
    def __init__(
        self,
        templates: Sequence[Template],
        max_body: int = 4096,
        max_header: int = 1024,
        batch_rows: int = 1024,
        candidate_k: int = 128,
        host_always: str = "full",  # "full" (exact) | "skip" (device-only)
        mesh="auto",  # "auto" | None | jax.sharding.Mesh
    ):
        self.templates = list(templates)
        self.db: CompiledDB = compile_corpus(self.templates)
        self.device = DeviceDB(self.db, candidate_k=candidate_k)
        self.max_body = max_body
        self.max_header = max_header
        self.batch_rows = batch_rows
        self.host_always_mode = host_always
        self.stats = EngineStats()
        # Multi-chip: shard each batch dp×tp×sp across the local mesh
        # (the production analog of the reference's chunk-per-worker
        # scale-out, server/server.py:465-515 — here one worker drives a
        # whole slice). "auto" shards whenever >1 device is visible;
        # sharding never changes results (tests/test_sharding.py).
        # Resolution is lazy: construction must stay JAX-free (the
        # oracle-only and pre-fork users never touch a device).
        self._mesh_arg = mesh
        self._backend_ready = mesh is None
        self.sharded = None
        self.mesh = None
        self._candidate_k = candidate_k
        # templates with extractors need a host pass on *hits* even when
        # the verdict itself was device-certain, so extraction output
        # stays bit-identical to the oracle
        self._has_extractors = [
            any(
                ex.type in ("regex", "kval", "json", "xpath")
                for op in t.operations
                for ex in op.extractors
            )
            for t in self.db.templates
        ]

    # ------------------------------------------------------------------
    def match(self, responses: Sequence[Response]) -> list[RowMatches]:
        out: list[RowMatches] = []
        for start in range(0, len(responses), self.batch_rows):
            out.extend(self._match_batch(responses[start : start + self.batch_rows]))
        return out

    # ------------------------------------------------------------------
    def _resolve_backend(self) -> None:
        """First-match mesh resolution (kept out of __init__ so engine
        construction never initializes the JAX backend)."""
        mesh = self._mesh_arg
        if mesh == "auto":
            import jax

            mesh = None
            if len(jax.devices()) > 1:
                from swarm_tpu.parallel.mesh import make_mesh

                mesh = make_mesh()
        if mesh is not None:
            from swarm_tpu.parallel.sharded import ShardedMatcher

            self.sharded = ShardedMatcher(self.db, mesh, candidate_k=self._candidate_k)
            self.mesh = mesh
        self._backend_ready = True

    # ------------------------------------------------------------------
    def _encode_for_backend(self, rows: Sequence[Response]):
        """Encode rows for whichever device backend is active.

        The sharded backend needs the batch row count divisible by the
        'data' axis and every stream width divisible by 'seq' with each
        per-rank slice at least one halo wide (parallel/sharded.py
        raises otherwise); padding is zeros, which the length masks
        already ignore, and padded rows are sliced off the verdicts.
        """
        if not self._backend_ready:
            self._resolve_backend()
        if self.sharded is None:
            return (
                encode_batch(rows, max_body=self.max_body, max_header=self.max_header),
                self.device,
            )
        data_ranks = self.sharded.ranks.get("data", 1)
        seq_ranks = self.sharded.ranks.get("seq", 1)
        batch = encode_batch(
            rows,
            max_body=self.max_body,
            max_header=self.max_header,
            pad_rows_to=round_up(len(rows), data_ranks),
        )
        if seq_ranks > 1:
            halo = self.sharded.halo
            for name, arr in batch.streams.items():
                per_rank = max(
                    round_up(arr.shape[1], seq_ranks) // seq_ranks, halo
                )
                target = round_up(per_rank, 128) * seq_ranks
                if target > arr.shape[1]:
                    batch.streams[name] = np.pad(
                        arr, ((0, 0), (0, target - arr.shape[1]))
                    )
        return batch, self.sharded

    # ------------------------------------------------------------------
    def _match_batch(self, all_rows: Sequence[Response]) -> list[RowMatches]:
        # dead rows (no response observed) match nothing by contract —
        # drop them before encoding so the device never pays for them
        alive_idx = [i for i, r in enumerate(all_rows) if r.alive]
        if len(alive_idx) < len(all_rows):
            out = [RowMatches(template_ids=[], extractions={}) for _ in all_rows]
            if alive_idx:
                live = self._match_batch([all_rows[i] for i in alive_idx])
                for j, i in enumerate(alive_idx):
                    out[i] = live[j]
            self.stats.rows += len(all_rows) - len(alive_idx)
            return out
        rows = all_rows
        batch, matcher = self._encode_for_backend(rows)
        t0 = time.perf_counter()
        t_value, t_unc, overflow = matcher.match(
            batch.streams, batch.lengths, batch.status
        )
        # slice off mesh row padding before the host walk
        t_value = np.asarray(t_value)[: len(rows)]
        t_unc = np.asarray(t_unc)[: len(rows)]
        overflow = np.asarray(overflow)[: len(rows)]
        self.stats.device_seconds += time.perf_counter() - t0
        self.stats.rows += len(rows)
        self.stats.batches += 1

        # rows needing whole-row reconfirmation (candidate overflow or
        # stream truncation made word bits unsound for the row)
        row_redo = overflow | batch.truncated[: len(rows)]
        self.stats.overflow_rows += int(row_redo.sum())

        t1 = time.perf_counter()
        results: list[RowMatches] = []
        for b, row in enumerate(rows):
            matched: list[str] = []
            extractions: dict = {}
            confirmed = 0
            for t_idx, template in enumerate(self.db.templates):
                if row_redo[b] or t_unc[b, t_idx]:
                    res = cpu_ref.match_template(template, row)
                    confirmed += 1
                    hit = res.matched
                    if hit and res.extractions:
                        extractions[template.id] = res.extractions
                else:
                    hit = bool(t_value[b, t_idx])
                    if hit and self._has_extractors[t_idx]:
                        res = cpu_ref.match_template(template, row)
                        confirmed += 1
                        if res.extractions:
                            extractions[template.id] = res.extractions
                if hit:
                    matched.append(template.id)
            self.stats.host_confirm_pairs += confirmed
            # host-always tail: templates the compiler couldn't lower
            if self.host_always_mode == "full":
                for template in self.db.host_always:
                    res = cpu_ref.match_template(template, row)
                    self.stats.host_always_pairs += 1
                    if res.matched:
                        matched.append(template.id)
                        if res.extractions:
                            extractions[template.id] = res.extractions
            results.append(
                RowMatches(
                    template_ids=matched,
                    extractions=extractions,
                    confirmed_on_host=confirmed,
                )
            )
        self.stats.host_confirm_seconds += time.perf_counter() - t1
        return results
