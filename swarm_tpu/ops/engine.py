"""MatchEngine: the user-facing exact fingerprint engine.

Composes the pieces: template corpus → CompiledDB (once), responses →
padded batches → device kernel → sparse host confirmation with the
exact CPU oracle. The result is bit-identical to running the oracle on
every (row, template) pair — the device does ~all the work, the host
touches only the specific uncertain *matchers* that actually fired
(plus the small, reported host-always template tail, empty for the
reference corpus).

Throughput contract: the packed path (:meth:`MatchEngine.match_packed`)
never does per-row Python work for certain rows — verdicts stay bitset
matrices end to end, uncertainty is resolved pair-sparsely, and the
three-valued (Kleene) refinement in the kernel (ops/match.py
``eval_verdicts``) keeps the uncertain set small. A key consequence of
that refinement drives the sparse resolver here: an op that is still
*undecided* after its certain matchers are known has a neutral certain
part (all-false under OR, all-true under AND), so its exact value is
the combination of its *uncertain* matchers alone — the host never
needs the certain siblings' values.

This replaces the reference worker's subprocess shell-outs to
nmap/-sV//nuclei (``worker/worker.py:79-84``) as the compute engine.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
from typing import Optional, Sequence

import numpy as np

from swarm_tpu.fingerprints.compile import CompiledDB, compile_corpus
from swarm_tpu.fingerprints.model import Response, Template
from swarm_tpu.ops import cpu_ref, fastre
from swarm_tpu.ops.encoding import _RotatingPool, encode_batch, round_up
from swarm_tpu.ops.match import DeviceDB
from swarm_tpu.telemetry.memo_export import L1_HITS, L1_MISSES


@dataclasses.dataclass
class RowMatches:
    """Exact match set for one response row."""

    template_ids: list
    extractions: dict  # template_id -> list[str]
    # uncertain pairs the host re-checked. Under content dedup a
    # confirm happens once per DISTINCT content and is attributed to
    # the group's representative row — duplicate members report 0
    # (the work genuinely wasn't repeated for them).
    confirmed_on_host: int = 0
    # workflow gate planes for this row (docs/WORKFLOWS.md): packed
    # (cond_v, cond_u, emit_v, emit_u) uint8 rows from the device
    # gate-apply stage, or None when the row was memo-served, redone,
    # degraded, or the corpus lowered no workflow terms — the runner
    # then resolves every condition on the host (exact either way).
    wf: Optional[tuple] = None


@dataclasses.dataclass
class PackedMatches:
    """Exact verdicts for one batch in wire form.

    ``bits[b, t >> 3] & (0x80 >> (t & 7))`` is template ``t``'s verdict
    for row ``b`` (np.packbits MSB-first convention); ``template_ids``
    maps the column index to ids. ``extractions`` is sparse:
    ``(row, template_id) -> list[str]``. ``host_always_matches`` lists
    (row, template_id) hits from the host-only tail, if any.

    Buffer lifetime: when the batch was encoded with
    ``reuse_buffers=True`` (the pipelined feed), ``bits`` ALIASES a
    recycled per-shape plane that is overwritten 8 same-shape encodes
    later — consume (or ``.copy()``) it before encoding that many
    further batches. The default allocating encode path hands back a
    plane the caller owns indefinitely.
    """

    bits: np.ndarray  # uint8 [B, ceil(NT/8)]
    template_ids: list
    extractions: dict
    host_always_matches: list
    # row -> host confirmations spent on it. Confirms happen once per
    # DISTINCT content (dedup) and land on the representative row.
    confirms_per_row: dict
    # workflow gate planes (docs/WORKFLOWS.md): {"cond_v", "cond_u",
    # "emit_v", "emit_u"} packed uint8 [B, ...] + "valid" bool [B]
    # (False for memo-served / redone rows — their planes are stale or
    # absent); None when the corpus lowered no workflow terms
    wf: Optional[dict] = None


@dataclasses.dataclass
class EngineStats:
    rows: int = 0
    batches: int = 0
    device_seconds: float = 0.0
    # cumulative compile wall time of the device matcher (DeviceDB
    # compile_seconds passthrough — new batch shapes only; the
    # corpus-as-arguments kernel makes this corpus-size-free)
    device_compile_seconds: float = 0.0
    device_compiles: int = 0
    # AOT executable-cache fetch twin (docs/AOT.md): dispatches that
    # LOADED a published executable instead of compiling it
    device_fetch_seconds: float = 0.0
    device_fetches: int = 0
    # split-phase device attribution (ops/match.py dispatch): phase A is
    # the wall up to the survivor-scalar sync, the remainder of the
    # device wall is phase B + transfer. Populated on the single-device
    # compacted path only (0.0 elsewhere); both are included in
    # device_seconds. The worker folds these into device.phase_a /
    # device.phase_b child spans (docs/OBSERVABILITY.md §Tracing).
    phase_a_seconds: float = 0.0
    phase_b_seconds: float = 0.0
    host_confirm_seconds: float = 0.0
    host_confirm_pairs: int = 0
    host_always_pairs: int = 0
    overflow_rows: int = 0
    # memo-served ROW count, summed per batch (rows whose verdict came
    # from the cross-batch memo without device or walk work)
    memo_slots: int = 0
    # device-degraded mode (docs/RESILIENCE.md): device-path failures
    # observed, and batches that ran on the exact CPU-oracle fallback
    # (results stay bit-identical — only throughput degrades)
    device_faults: int = 0
    degraded_batches: int = 0
    # host-walk sub-phases (all included in host_confirm_seconds):
    # uncertainty resolution, the extraction pass, memo inserts, and
    # the member fan-out/fixup assembly — the levers the fresh-content
    # optimization work tracks individually
    unc_seconds: float = 0.0
    ext_seconds: float = 0.0
    insert_seconds: float = 0.0
    fixup_seconds: float = 0.0
    # ext-phase sub-splits (all included in ext_seconds): the native
    # hit-enumeration C pass, undecided-op host confirms, and the
    # extraction proper (batched crex + oracle fallbacks)
    ext_enum_seconds: float = 0.0
    ext_resolve_seconds: float = 0.0
    ext_extract_seconds: float = 0.0
    # batched-confirm walk (docs/HOST_WALK.md): (row, matcher)/(row, op)
    # pairs whose verdict was precomputed by the row-parallel native
    # passes, the dispatch rounds that ran, the plan+dispatch wall
    # (included in host_confirm_seconds via unc/ext), and the worker
    # pool width (0 = batching inline or disabled)
    walk_batched_pairs: int = 0
    walk_batch_rounds: int = 0
    walk_precompute_seconds: float = 0.0
    walk_pool_threads: int = 0


def _bit(packed: np.ndarray, b: int, i: int) -> bool:
    return bool((packed[b, i >> 3] >> (7 - (i & 7))) & 1)


def _iter_set_bits(row_bytes: np.ndarray, limit: int) -> np.ndarray:
    """Indices of set bits in one packed row (MSB-first), < limit."""
    if limit <= 0:
        return np.empty((0,), dtype=np.int64)
    return np.flatnonzero(np.unpackbits(row_bytes, count=limit))


_ROWDEP_VAR_RE = None


def _is_row_dependent(t: Template) -> bool:
    """Whether any matcher/extractor reads beyond response content
    (host/hostname/port/duration/ip dsl vars or the "host" part)."""
    global _ROWDEP_VAR_RE
    if _ROWDEP_VAR_RE is None:
        import re

        _ROWDEP_VAR_RE = re.compile(r"\b(host|hostname|port|duration|ip)\b")
    for op in t.operations:
        for m in op.matchers:
            if (m.part or "") == "host":
                return True
            if any(_ROWDEP_VAR_RE.search(e) for e in m.dsl):
                return True
        for ex in op.extractors:
            if (ex.part or "") == "host":
                return True
    return False


def _content_key(r: Response) -> tuple:
    """The cross-batch verdict-memo key: everything the device and the
    content-side host walk read. host/port/duration are deliberately
    NOT in it (see MatchEngine._rowdep_t)."""
    return (
        r.banner, r.body, r.header, r.status,
        r.oob_protocols, r.oob_requests, r.oob_ips,
    )


def _alive_split(rows: Sequence[Response]):
    """(n_alive, alive_idx) — ``alive_idx`` is None when every row is
    alive (the common case pays one C pass and no index building)."""
    from swarm_tpu.ops.encoding import _native_encoder_available

    if _native_encoder_available() and isinstance(rows, list):
        from swarm_tpu.native.scanio import rows_alive

        n, mask = rows_alive(rows)
        if n == len(rows):
            return n, None
        return n, np.flatnonzero(mask).tolist()
    alive_idx = [i for i, r in enumerate(rows) if r.alive]
    if len(alive_idx) == len(rows):
        return len(rows), None
    return len(alive_idx), alive_idx


def _dedup_rows(rows: Sequence[Response]):
    """(uniq_indices, back, keys) — rows keyed by full response CONTENT.

    ``back[i]`` is the unique-slot index of row i; ``keys[s]`` is slot
    s's content key. The grouping runs as one C pass when the native
    lib is present (exact compare — same key semantics either way;
    steady-state fleet batches spend more time in this loop than in all
    remaining host work, so the Python loop is the fallback, not the
    path). Key tuples are built per unique slot only.
    """
    from swarm_tpu.ops.encoding import _native_encoder_available

    if _native_encoder_available() and isinstance(rows, list):
        from swarm_tpu.native.scanio import rows_dedup

        uniq_arr, back = rows_dedup(rows)
        uniq = uniq_arr.tolist()
        keys = [_content_key(rows[i]) for i in uniq]
        return uniq, back, keys
    key_of: dict = {}
    uniq = []
    keys = []
    back = np.empty(len(rows), dtype=np.int64)
    for i, r in enumerate(rows):
        k = _content_key(r)
        j = key_of.get(k)
        if j is None:
            j = key_of[k] = len(uniq)
            uniq.append(i)
            keys.append(k)
        back[i] = j
    return uniq, back, keys


def _place_rows_per_rank(nrows: list, padded: int, ranks: int):
    """Spread ``nrows`` real rows over ``ranks`` contiguous per-rank
    blocks of ``padded // ranks`` slots (docs/SHARDING.md placement
    rule): rank r gets ``floor(n/R)`` or ``ceil(n/R)`` real rows at the
    head of its block, padding fills the tails. Sharding a [B] batch
    over 'data' is contiguous blocks, so without this a partial bucket
    lands every real row on rank 0 and the rest of the mesh matches
    pure padding.

    Returns ``(placed_rows, row_index)``: a ``padded``-length row list
    (pad slots are empty Responses — zero-length, matched by nothing)
    and the position of each real row in it (``row_index[i]`` is where
    real row i landed; relative order is preserved within and across
    blocks, so verdict planes gather back with one fancy index)."""
    n = len(nrows)
    per = padded // ranks
    base, extra = divmod(n, ranks)
    placed: list = [None] * padded
    row_index = np.empty(n, dtype=np.int64)
    i = 0
    for r in range(ranks):
        take = base + (1 if r < extra else 0)
        for j in range(take):
            pos = r * per + j
            placed[pos] = nrows[i]
            row_index[i] = pos
            i += 1
    pad = Response()
    placed = [row if row is not None else pad for row in placed]
    return placed, row_index


class MatchEngine:
    def __init__(
        self,
        templates: Sequence[Template],
        max_body: int = 4096,
        max_header: int = 1024,
        batch_rows: int = 1024,
        candidate_k: int = 128,
        host_always: str = "full",  # "full" (exact) | "skip" (device-only)
        mesh="auto",  # "auto" | None | jax.sharding.Mesh
        db: Optional[CompiledDB] = None,  # precompiled (fingerprints/dbcache)
        pipeline: Optional[str] = None,  # "on" | "off" | None → SWARM_PIPELINE
        device_breaker_threshold: int = 2,
        device_breaker_cooldown_s: float = 60.0,
        walk_threads: Optional[int] = None,  # None → SWARM_WALK_THREADS
    ):
        self.templates = list(templates)
        self.db = db if db is not None else compile_corpus(self.templates)
        self.device = DeviceDB(self.db, candidate_k=candidate_k)
        self.max_body = max_body
        self.max_header = max_header
        self.batch_rows = batch_rows
        self.host_always_mode = host_always
        self.stats = EngineStats()
        # continuous-batching scheduler flag (swarm_tpu/sched): "on"
        # routes bulk :meth:`match` calls through the prefetch/bucket/
        # backpressure pipeline; None defers to SWARM_PIPELINE
        # (default off so existing callers keep the direct path)
        if pipeline is None:
            import os as _os

            pipeline = _os.environ.get("SWARM_PIPELINE", "off")
        self.pipeline = (
            "on" if str(pipeline).lower() in ("on", "1", "true") else "off"
        )
        self._sched = None  # lazy BatchScheduler (pipeline="on")
        # guards the stats fields BOTH the submit thread (begin_packed)
        # and the scheduler's walk worker (finish_packed → _walk_plane)
        # update — unsynchronized float += across threads loses updates
        self._stats_lock = threading.Lock()  # guards: stats.device_seconds, stats.device_faults
        # row-parallel batched confirm walk (docs/HOST_WALK.md):
        # explicit arg > SWARM_WALK_THREADS > SWARM_EXT_THREADS (compat)
        # > spare cores. 0 = serial reference walk; 1 = batched native
        # passes, inline; >=2 adds the worker pool.
        self._walk_threads_arg = walk_threads
        # Multi-chip: shard each batch dp×tp×sp across the local mesh
        # (the production analog of the reference's chunk-per-worker
        # scale-out, server/server.py:465-515 — here one worker drives a
        # whole slice). "auto" shards whenever >1 device is visible;
        # sharding never changes results (tests/test_sharding.py).
        # Resolution is lazy: construction must stay JAX-free (the
        # oracle-only and pre-fork users never touch a device).
        self._mesh_arg = mesh
        self._backend_ready = mesh is None
        self.sharded = None
        self.mesh = None
        self._candidate_k = candidate_k
        # every db-derived lookup table lives in _bind_db so a live
        # corpus refresh (refresh_corpus, docs/AOT.md) can re-derive
        # them against the new CompiledDB without rebuilding the engine
        self._bind_db()
        # content-keyed extraction memo (cross-batch): scan responses
        # repeat heavily (default pages are byte-identical fleet-wide)
        # and tech templates with version extractors fire on most rows,
        # so re-running the same regex/kval over the same bytes per row
        # dominated the host walk. Keyed per EXTRACTOR on exactly the
        # content it reads; bounded FIFO (keys hold the part bytes).
        self._ext_cache: dict = {}
        # cross-batch confirm memo for part-keyed matcher types
        # (word/regex/binary/size) — same bounding as _ext_cache
        self._confirm_cache: dict = {}
        # cross-batch VERDICT memo: content key -> (packed verdict row,
        # extraction entries, deferred row-dependent template ids).
        # Fleet batches repeat the same pages batch after batch; known
        # content skips the encode, the device, and the host walk
        # entirely. Entries are only stored for fully-resolved
        # (non-truncated, non-overflow) content. Bounded FIFO.
        self._verdict_memo: dict = {}
        # C resident verdict cache (native/scanio.VerdictMemo) — the
        # production form of _verdict_memo: its lookup pass serves
        # known rows straight into the batch's bits plane with no
        # per-row Python work. Lazily created on first encode so
        # oracle-only engines stay native-free; the dict memo remains
        # the no-toolchain fallback.
        self._vmemo = None
        self._native_memo_ok = None
        # fleet-wide shared result tier (docs/CACHING.md): when a
        # ResultCacheClient is attached, the memos above become the L1
        # in front of it — lookups go L1 → shared tier → device, fresh
        # walk results batch-write back after finish_packed, and the
        # batched walk's confirm cache promotes into the tier's second
        # value family. None (the default) keeps every path unchanged.
        self._result_cache = None
        # AOT executable cache (docs/AOT.md): when an AotClient is
        # attached, the device/sharded matchers fetch published
        # serialized executables before compiling and publish what
        # they compile. None (the default) keeps the compile path.
        self._aot_client = None
        # row ids the scheduler's prefetch stage already consulted the
        # shared tier for (hits landed in the L1, misses are
        # suppressed client-side): the encode-time consult skips them
        # so a fresh row's content is sha256'd once per batch, not
        # twice. id() keys are safe here because a stale entry can
        # only SKIP a consult (the row is computed locally) — it can
        # never serve wrong data. Bounded FIFO via _cache_put.
        self._shared_seen: dict = {}
        # recycled verdict planes for reuse_buffers encodes, keyed PER
        # SHAPE (see _encode_native): alternating batch shapes (bucket
        # scheduler, partial final chunks) each keep their own depth-8
        # rotation instead of re-allocating 8 planes on every change
        self._bits_pool = _RotatingPool(depth=8)
        # device-degraded mode (docs/RESILIENCE.md): a device-path
        # failure (XLA compile error, OOM, persistent-cache corruption
        # — or an injected device.dispatch fault) trips a per-shape-
        # class breaker and the batch falls back to the exact CPU
        # oracle; verdicts stay bit-identical, only throughput
        # degrades. The breaker cooldown periodically retries the
        # device path, so a transient fault self-heals.
        from swarm_tpu.resilience.breaker import BreakerBoard

        self._device_breakers = BreakerBoard(
            "engine.device",
            threshold=device_breaker_threshold,
            cooldown_s=device_breaker_cooldown_s,
        )
        # export this engine's stats to /metrics: weakref-tracked, read
        # only at scrape time — zero cost on the match hot path
        from swarm_tpu.telemetry.engine_export import register_engine

        register_engine(self)

    def _bind_db(self) -> None:
        """Derive every db-indexed lookup table the walk and the
        sparse-confirmation paths read (provenance maps, extractor
        plans, CSR op->matcher tables). Called from __init__ and again
        by :meth:`refresh_corpus` after a corpus-delta swap — the
        tables are pure functions of ``self.db``."""
        db = self.db
        # device matcher/op id → source objects for sparse confirmation.
        # m == -1 is a synthesized extraction prefilter (extractor-only
        # op, compile.lower_extraction_prefilter): no source matcher —
        # its op is always prefiltered, so confirmation goes through
        # _confirm_operation, never the per-matcher path
        self._m_obj = [
            db.templates[t].operations[o].matchers[m] if m >= 0 else None
            for t, o, m in db.m_src
        ] if db.templates else []
        # per-pattern extraction-prefilter provenance (m == -1 rows):
        # matcher id -> (extractor_local, pattern_idx), (-1, -1) for
        # real matchers and the fire-always degrade
        ext_src = getattr(db, "m_ext_src", None)
        self._m_ext_src_py = (
            [(int(a), int(b)) for a, b in ext_src]
            if ext_src is not None
            else [(-1, -1)] * len(self._m_obj)
        )
        # matcher id -> owning op id (per-pattern confirm needs the op
        # object; built once from the op->matchers table)
        self._m_op_id = [0] * len(self._m_obj)
        for op_id_, ids_ in enumerate(db.op_matchers):
            for m_ in ids_:
                self._m_op_id[int(m_)] = op_id_
        # ops lowered as per-pattern extraction prefilters: op id ->
        # tuple of (extractor_local, pattern_idx) aligned with the op's
        # matcher ids — the walk turns the device pm-uncertainty bits
        # into the extraction pass's live-pattern hints
        self._op_ext_pats = {}
        for op_id_, ids_ in enumerate(db.op_matchers):
            pats = [self._m_ext_src_py[int(m_)] for m_ in ids_]
            if pats and all(p[0] >= 0 for p in pats):
                self._op_ext_pats[op_id_] = tuple(pats)
        # templates whose EVERY op is a per-pattern extraction
        # prefilter: their verdict IS "any extraction non-empty", so
        # the walk defers their uncertain bits to the batched
        # extraction pass (one native dispatch per distinct pattern)
        # instead of paying a per-(row, pattern) confirm round trip
        self._pseudo_t = frozenset(
            t_idx for t_idx, t_op_ids in enumerate(db.t_ops)
            if len(t_op_ids)
            and all(int(op) in self._op_ext_pats for op in t_op_ids)
        )
        self._op_obj = [
            db.templates[t].operations[o] for t, o in db.op_src
        ] if db.templates else []
        # all-regex extractor ops: precomputed (id(ex), ex, part) plan
        # — _extract_pending's unified inline loop rides it for both
        # cache hits AND misses (per-hit analyze() lookups and the
        # segs/fills bookkeeping cost more than the actual literal
        # gates at walk rates). Pattern infos resolve lazily on an
        # op's FIRST fired hit (_op_plan_infos): analyzing every
        # pattern of every extractor op up front would tax engine
        # construction for ops a scan never fires.
        self._op_ext_plan = {}
        self._op_ext_infos: dict = {}  # op_id -> per-ex infos tuples
        for op_id_, op_ in enumerate(self._op_obj):
            if op_.extractors and all(
                ex.type == "regex" for ex in op_.extractors
            ):
                self._op_ext_plan[op_id_] = tuple(
                    (id(ex), ex, ex.part) for ex in op_.extractors
                )
        # templates with extractors need a host pass on *hits* even when
        # the verdict itself was device-certain, so extraction output
        # stays bit-identical to the oracle
        self._has_extractors = [
            any(op.extractors for op in t.operations) for t in db.templates
        ]
        self._ext_t_idx = [
            i for i, has in enumerate(self._has_extractors) if has
        ]
        self._ext_cols = np.asarray(self._ext_t_idx, dtype=np.int64)
        self._ext_masks = (
            0x80 >> (self._ext_cols & 7)
        ).astype(np.uint8) if len(self._ext_cols) else np.zeros(0, np.uint8)
        # packed byte mask of extractor-template bits: the ext pass
        # ANDs it against the verdict plane (contiguous, one pass)
        # instead of a [B, n_ext] fancy-gather per batch
        self._ext_byte_mask = np.zeros(
            (db.num_templates + 7) // 8, dtype=np.uint8
        )
        for t_idx in self._ext_t_idx:
            self._ext_byte_mask[t_idx >> 3] |= 0x80 >> (t_idx & 7)
        # vectorized per-op matcher-id tables: resolving a giant op
        # (fingerprinthub: 2,897 matchers) must not walk bits in Python
        self._op_m_arr = [
            np.asarray(ids, dtype=np.int64) for ids in db.op_matchers
        ]
        # pre-shifted byte/bit index twins (the uncertain-op gather
        # runs per (row, op) — recomputing ids>>3 / 7-(ids&7) there
        # allocated three temporaries per pair)
        self._op_m_bytes = [ids >> 3 for ids in self._op_m_arr]
        self._op_m_shift = [
            (7 - (ids & 7)).astype(np.uint8) for ids in self._op_m_arr
        ]
        # CSR twin of op_matchers for the batched walk's vectorized
        # candidate expansion (one fancy index over the whole batch's
        # candidate ops instead of a per-op gather)
        self._op_m_indptr = np.zeros(
            len(self._op_m_arr) + 1, dtype=np.int64
        )
        for i, ids in enumerate(self._op_m_arr):
            self._op_m_indptr[i + 1] = self._op_m_indptr[i] + len(ids)
        self._op_m_flat = (
            np.concatenate(self._op_m_arr)
            if self._op_m_arr
            else np.zeros(0, dtype=np.int64)
        ).astype(np.int64, copy=False)
        self._op_m_counts = np.diff(self._op_m_indptr)
        # python-native twins of the per-template op tables: the walk's
        # inner loops hash (row, op) keys and index bit planes with
        # these, and numpy int scalars make every such op ~3x slower
        self._t_ops_py = [
            tuple(int(o) for o in ops) for ops in db.t_ops
        ]
        self._op_prefilter_py = [bool(x) for x in db.op_prefilter]
        self._op_cond_and_py = [bool(x) for x in db.op_cond_and]
        # ROW-dependent templates: verdicts/extractions that read
        # beyond the response content (host/port/duration dsl vars,
        # part "host") — e.g. the takeover family's
        # !contains(host, "tumblr.com") gates. Content-identical rows
        # from different hosts can disagree on exactly these templates,
        # so the content-dedup fast path resolves them per member row
        # (everything else resolves once per distinct content).
        # Conservative detection: false positives only cost speed.
        self._rowdep_t = frozenset(
            i for i, t in enumerate(db.templates) if _is_row_dependent(t)
        )
        # extractor templates that are ALSO row-dependent (their
        # values may read host): the per-batch certain-set scan walks
        # only these columns, not all extractor templates
        self._rowdep_ext_t = [
            t_idx for t_idx in self._ext_t_idx if t_idx in self._rowdep_t
        ]
        # CSR twin of t_ops + rowdep byte mask for the C extraction
        # driver (native/fastpack.cpp sw_ext_resolve)
        self._t_ops_indptr = np.zeros(db.num_templates + 1, dtype=np.int64)
        for i, ops in enumerate(self._t_ops_py):
            self._t_ops_indptr[i + 1] = self._t_ops_indptr[i] + len(ops)
        self._t_ops_flat = np.asarray(
            [o for ops in self._t_ops_py for o in ops], dtype=np.int64
        )
        self._rowdep_mask = np.zeros(db.num_templates, dtype=np.uint8)
        for i in self._rowdep_t:
            self._rowdep_mask[i] = 1


    _EXT_CACHE_MAX = 16384

    @classmethod
    def _cache_put(cls, cache: dict, key, val) -> None:
        """Bounded FIFO insert shared by the cross-batch content memos:
        past the cap, drop the oldest half (dict preserves order).
        Thread-tolerant under the GIL for the walk pool's fallback
        tasks: each dict op is atomic, the key snapshot tolerates
        concurrent inserts, and eviction uses pop (two racing evictors
        must not KeyError on a key the other already dropped). Values
        for one key are always identical (pure content functions), so
        a double insert is benign."""
        if len(cache) >= cls._EXT_CACHE_MAX:
            for k in list(cache)[: cls._EXT_CACHE_MAX // 2]:
                cache.pop(k, None)
        cache[key] = val

    def _extract_op(self, op, row: Response) -> list:
        """cpu_ref._extract with per-extractor content memoization."""
        out: list = []
        cache = self._ext_cache
        for ex in op.extractors:
            if ex.type in ("regex", "json", "xpath"):
                key = (id(ex), row.part(ex.part))
            elif ex.type == "kval":
                key = (id(ex), row.part("header"), row.oob_ips)
            else:
                out.extend(cpu_ref.extract_one(ex, row))
                continue
            vals = cache.get(key)
            if vals is None:
                if ex.type == "regex":
                    vals = self._accel_extract_regex(ex, key[1])
                else:
                    vals = cpu_ref.extract_one(ex, row)
                self._cache_put(cache, key, vals)
            out.extend(vals)
        return out

    @property
    def walk_threads(self) -> int:
        """Effective walk worker count: constructor arg >
        ``SWARM_WALK_THREADS`` > ``SWARM_EXT_THREADS`` (compat) >
        spare cores capped at 4. 0 disables the batched walk entirely
        (the serial reference path); 1 runs the batched native passes
        inline; >=2 row-shards them across the worker pool."""
        n = self._walk_threads_arg
        if n is None:
            import os as _os

            env = _os.environ.get("SWARM_WALK_THREADS") or _os.environ.get(
                "SWARM_EXT_THREADS"
            )
            n = int(env) if env else min(
                4, max(1, (_os.cpu_count() or 1) - 1)
            )
        return max(0, int(n))

    def configure_walk(self, threads: Optional[int]) -> None:
        """Re-point the walk pool at runtime (bench A/B, tests): shuts
        any existing pool down, then re-decides lazily on next use.
        ``None`` restores env-derived sizing."""
        pool = getattr(self, "_walk_pool_obj", None)
        if pool:
            pool.shutdown(wait=True)
        self._walk_pool_obj = None
        self._walk_threads_arg = threads
        self.stats.walk_pool_threads = 0

    def _walk_pool(self):
        """Shared row-sharded worker pool for the walk's GIL-released
        native batches — confirm passes AND extraction finditer
        batches (what used to be the extraction-only ``_ext_pool``).
        Sized by :attr:`walk_threads`; None when threading is off
        (batched passes then run inline on the walk thread)."""
        pool = getattr(self, "_walk_pool_obj", None)
        if pool is not None:
            return pool or None
        workers = self.walk_threads
        if workers <= 1:
            self._walk_pool_obj = ()  # sentinel: decided, disabled
            return None
        from concurrent.futures import ThreadPoolExecutor

        self._walk_pool_obj = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="swarm-walk"
        )
        self.stats.walk_pool_threads = workers
        return self._walk_pool_obj

    def _resolve_regex_ex(
        self, ex, ex_local, key, hint, infos,
        cache, fills, tasks, ncrex, _fastre,
    ) -> tuple:
        """Resolve one regex extractor against one content part:
        ``("v", vals)`` when decided inline (and memoized), ``("k",
        key)`` when a batched finditer task was registered in
        ``fills``/``tasks``. Gate order is cheapest-proof-first and
        every gate is a necessary-condition proof (skipping a pattern
        is exact): device pm-bit hint → required-literal CNF
        (the device bit only proves ONE group's gram presence; the
        other mandatory groups, e.g. the '@' an email must contain,
        are a few bytes.find) → lazy-DFA existence (a proven-absent
        pattern runs no finditer at all; a missing match costs the
        backtracker/re its worst case — 2-19 ms for leading-repeat
        shapes vs ~6 µs here)."""
        part = key[1]
        if hint is not None:
            live = hint.get(ex_local, [])
            if live:
                lowered = part.lower()
                live = [
                    p
                    for p in live
                    if not _fastre.literals_absent(infos[p], lowered)
                ]
        else:
            lowered = part.lower()
            live = []
            for p_idx, info in enumerate(infos):
                if info.ok and (info.literals or info.cnf) and (
                    _fastre.literals_absent(info, lowered)
                ):
                    continue
                live.append(p_idx)
        if live:
            kept = []
            for p_idx in live:
                nfa = infos[p_idx].nfa
                if nfa is not None and ncrex.exists(nfa, part) is False:
                    continue
                kept.append(p_idx)
            live = kept
        if not live:
            self._cache_put(cache, key, [])
            return ("v", [])
        if not isinstance(ex.group, int) or not all(
            infos[p].ok and ncrex.usable(infos[p].cprog) for p in live
        ):
            vals = self._accel_extract_regex(ex, part)
            self._cache_put(cache, key, vals)
            return ("v", vals)
        fills[key] = {"ex": ex, "part": part, "live": live, "by_pat": {}}
        for p_idx in live:
            t = tasks.setdefault(
                (ex.regex[p_idx], ex.group),
                {"cp": infos[p_idx].cprog, "items": [], "parts": []},
            )
            t["items"].append((key, p_idx))
            t["parts"].append(part)
        return ("k", key)

    def _extract_pending(
        self, pending: list, nrows: list, hints: Optional[dict] = None
    ) -> dict:
        """(b, t_idx) -> ordered extraction values for the native
        walk's resolved hit list.

        ``hints``: optional {(b, op_id): {ex_local: [p_idx, ...]}}
        of LIVE patterns for per-pattern extraction-prefilter ops
        (from the device pm-uncertainty bits): non-live patterns are
        exact no-matches and are skipped with no host work at all —
        every structure here is sized by the live count, never the
        op's full pattern population (credentials-disclosure: 689).

        Semantics are exactly ``_extract_op`` applied in hit order —
        same content-keyed memo, same extractor/pattern ordering, same
        oracle fallbacks — but every crex-able regex extraction runs as
        ONE batched native dispatch per distinct (pattern, group) over
        all pending contents (native/crex.cpp sw_crex_finditer_batch):
        at fresh-content walk rates the per-call dispatch overhead was
        the dominant extraction cost."""
        out: dict = {}
        if not pending:
            return out
        import os as _os

        if _os.environ.get("SWARM_EXT_BATCH", "1") == "0":
            # measurement escape hatch: per-hit _extract_op calls
            for b, t_idx, op_id in pending:
                vals = out.setdefault((b, t_idx), [])
                vals.extend(self._extract_op(self._op_obj[op_id], nrows[b]))
            return out
        from swarm_tpu.native import crex as ncrex
        from swarm_tpu.ops import fastre as _fastre

        cache = self._ext_cache
        segs: dict = {}   # (b, t_idx) -> [("v", vals) | ("k", key)]
        fills: dict = {}  # key -> {"ex", "part", "by_pat"}
        tasks: dict = {}  # (pattern, group) -> {"cp", "items", "parts"}
        # Unified inline loop. All-regex ops (the population, incl.
        # every per-pattern-prefilter pseudo op) resolve each extractor
        # in place with precomputed PatternInfos — cache hit, literal/
        # CNF gate, DFA existence, [] short-circuit — and only rows
        # that create a batch task (or share a key with one) touch the
        # segs/fills bookkeeping. Mixed/non-regex ops keep the general
        # per-extractor walk. A (b, t) whose hits span both modes keeps
        # extraction order by replaying its inline values into the seg
        # list at transition time.
        plan_map = self._op_ext_plan
        cache_put = self._cache_put
        fast_acc: dict = {}  # (b, t_idx) -> [vals, ...] in hit order

        def to_seg(bt) -> list:
            seg = segs.get(bt)
            if seg is None:
                prior = fast_acc.pop(bt, None)
                seg = segs[bt] = (
                    [("v", v) for v in prior] if prior else []
                )
            return seg

        for b, t_idx, op_id in pending:
            bt = (b, t_idx)
            row = nrows[b]
            plan = plan_map.get(op_id)
            hint = hints.get((b, op_id)) if hints else None
            if plan is None:
                # mixed/non-regex extractors: general per-ex walk
                seg = to_seg(bt)
                for ex_local, ex in enumerate(
                    self._op_obj[op_id].extractors
                ):
                    if ex.type in ("regex", "json", "xpath"):
                        key = (id(ex), row.part(ex.part))
                    elif ex.type == "kval":
                        key = (id(ex), row.part("header"), row.oob_ips)
                    else:
                        seg.append(("v", cpu_ref.extract_one(ex, row)))
                        continue
                    vals = cache.get(key)
                    if vals is not None:
                        seg.append(("v", vals))
                        continue
                    if key in fills:
                        seg.append(("k", key))
                        continue
                    if ex.type != "regex":
                        vals = cpu_ref.extract_one(ex, row)
                        cache_put(cache, key, vals)
                        seg.append(("v", vals))
                        continue
                    # regex inside a mixed op: same inline resolution,
                    # infos from the shared analyze cache
                    infos = tuple(
                        _fastre.analyze(p) for p in ex.regex
                    )
                    entry = self._resolve_regex_ex(
                        ex, ex_local, key, hint, infos,
                        cache, fills, tasks, ncrex, _fastre,
                    )
                    seg.append(entry)
                continue
            # all-regex plan: inline resolution, no seg unless a task
            entries = None   # created only when a "k" entry appears
            chunks = None
            infos_op = self._op_ext_infos.get(op_id)
            if infos_op is None:
                infos_op = self._op_ext_infos[op_id] = tuple(
                    tuple(_fastre.analyze(p) for p in e.regex)
                    for _i, e, _pn in plan
                )
            for ex_local, (id_ex, ex, part_name) in enumerate(plan):
                infos = infos_op[ex_local]
                part = row.part(part_name)
                key = (id_ex, part)
                vals = cache.get(key)
                if vals is None and key not in fills:
                    entry = self._resolve_regex_ex(
                        ex, ex_local, key, hint, infos,
                        cache, fills, tasks, ncrex, _fastre,
                    )
                    if entry[0] == "v":
                        vals = entry[1]
                    # else: falls through to the "k" handling below
                elif vals is None:
                    entry = ("k", key)
                if vals is not None:
                    if entries is not None:
                        entries.append(("v", vals))
                    elif vals:
                        if chunks is None:
                            chunks = []
                        chunks.append(vals)
                    continue
                # task-backed key: this (b, t) needs seg ordering
                if entries is None:
                    entries = [("v", c) for c in chunks] if chunks else []
                    chunks = None
                entries.append(entry)
            if entries is not None:
                to_seg(bt).extend(entries)
            elif chunks:
                acc = fast_acc.get(bt)
                if acc is None and bt in segs:
                    segs[bt].extend(("v", c) for c in chunks)
                else:
                    if acc is None:
                        fast_acc[bt] = acc = []
                    acc.extend(chunks)

        import time as _time

        _dbg = _os.environ.get("SWARM_EXT_DEBUG")
        if _dbg:
            _tA = _time.perf_counter()
            print(f"    extA hits={len(pending)} keys={len(fills)} "
                  f"tasks={len(tasks)} segs={len(segs)}", flush=True)
        done: dict = {}
        if fills:
            failed: set = set()
            task_list = list(tasks.items())
            # the batch C calls release the GIL: on hosts with spare
            # cores the per-pattern scans run concurrently (disjoint
            # outputs, no shared mutable state inside the call)
            pool = self._walk_pool()
            if pool is not None and len(task_list) > 1:
                results = list(pool.map(
                    lambda kv: ncrex.finditer_spans_batch(
                        kv[1]["cp"], kv[1]["parts"], kv[0][1]
                    ),
                    task_list,
                ))
            else:
                results = [
                    ncrex.finditer_spans_batch(t["cp"], t["parts"], group)
                    for (_pat, group), t in task_list
                ]
            for ((pattern, group), t), res in zip(task_list, results):
                if _dbg:
                    nsp = sum(len(s) for s in res if s) if res else -1
                    print(f"    extB {pattern[:40]!r} items="
                          f"{len(t['parts'])} spans={nsp} "
                          f"none={res is None}", flush=True)
                if res is None:
                    failed.update(k for k, _p in t["items"])
                    continue
                for (key, p_idx), spans in zip(t["items"], res):
                    if spans is None:
                        failed.add(key)  # per-item native budget hit
                        continue
                    f = fills[key]
                    text = f.get("text")
                    if text is None:
                        text = f["text"] = f["part"].decode("latin-1")
                    f["by_pat"][p_idx] = [
                        None if s < 0 else text[s:e] for s, e in spans
                    ]
            for key, f in fills.items():
                if key in failed:
                    # any pattern short of native resources: the whole
                    # extractor re-runs on the exact per-call path
                    vals = self._accel_extract_regex(f["ex"], f["part"])
                else:
                    by_pat = f["by_pat"]
                    vals = [v for p in f["live"] for v in by_pat[p]]
                self._cache_put(cache, key, vals)
                done[key] = vals

        if _dbg:
            print(f"    extC batchcalls {_time.perf_counter()-_tA:.4f}s "
                  f"failed={len(failed) if fills else 0}", flush=True)
        for bt, seg in segs.items():
            vals = []
            for kind, v in seg:
                vals.extend(v if kind == "v" else done[v])
            out[bt] = vals
        for bt, chunks in fast_acc.items():
            # fresh list: cached value lists must never be aliased into
            # per-batch results (downstream consumers own `vals`)
            vals = []
            for c in chunks:
                vals.extend(c)
            out[bt] = vals
        return out

    @staticmethod
    def _accel_extract_regex(ex, part: bytes) -> list:
        """Candidate-anchored regex extraction — byte-identical to
        cpu_ref.extract_one for type=regex (fuzz-pinned by
        tests/test_fastre.py); patterns the accelerator can't take
        fall back to the oracle's finditer loop per pattern.

        Per-pattern literal gate: a pattern whose necessary literals
        are all absent CANNOT match — skipping it is exact and turns a
        fired multi-hundred-pattern extractor (credentials-disclosure:
        689 regexes) into a few bytes.find calls plus the one or two
        patterns whose literal actually occurred."""
        from swarm_tpu.native import crex as _ncrex

        out: list = []
        text = None
        lowered = None
        for pattern in ex.regex:
            info = fastre.analyze(pattern)
            if info.ok and (info.literals or info.cnf):
                if lowered is None:
                    lowered = part.lower()
                if fastre.literals_absent(info, lowered):
                    continue
            # linear-time existence pre-gate (same proof as the
            # batched path): no match => no values, skip the finditer
            if info.nfa is not None and _ncrex.exists(
                info.nfa, part
            ) is False:
                continue
            if text is None:
                text = part.decode("latin-1")
            vals = fastre.finditer_values(pattern, part, text, ex.group)
            if vals is not None:
                out.extend(vals)
                continue
            # fallback mirrors cpu_ref.extract_one exactly
            try:
                for m in cpu_ref._compile_cached(pattern).finditer(text):
                    try:
                        out.append(m.group(ex.group))
                    except IndexError:
                        out.append(m.group(0))
            except re.error:
                continue
        return out

    def _confirm_ext_pattern(self, m_id: int, row: Response) -> bool:
        """Exact verdict of ONE synthesized extraction-prefilter
        matcher: does this extraction pattern match the row's part
        (any match ⇒ the extractor extracts ⇒ the op matches — group
        participation doesn't matter for the bool). Content-keyed
        cache shared with the matcher confirms."""
        op = self._op_obj[self._m_op_id[m_id]]
        ex_local, p_idx = self._m_ext_src_py[m_id]
        if ex_local < 0:  # fire-always degrade: whole-op confirm
            return self._confirm_operation(op, row)
        ex = op.extractors[ex_local]
        pattern = ex.regex[p_idx]
        part = row.part(ex.part)
        key = ("pe", m_id, part)
        cache = self._confirm_cache
        v = cache.get(key)
        if v is None:
            info = fastre.analyze(pattern)
            if not info.ok:
                v = False  # invalid under re: extract_one yields nothing
            else:
                text = part.decode("latin-1")
                sv = fastre.search_bool(pattern, part, text)
                if sv is None:
                    sv = info.rex.search(text) is not None
                v = bool(sv)
            self._cache_put(cache, key, v)
        return v

    def _confirm_operation(self, op, row: Response) -> bool:
        """Exactly ``cpu_ref.match_operation(op, row)[0]`` with the
        part-keyed confirm cache and regex prefilter applied per
        matcher — the superset-lowered ops route here, where the slow
        literal-less regexes (waf-detect's cloudfront backtracker)
        otherwise re-scan every confirm."""
        if not op.matchers:
            # extractor-only operation: matches iff any extractor
            # extracts (nuclei semantics; cpu_ref.match_operation's
            # empty-verdicts branch is the oracle twin). _extract_op's
            # content-keyed memo makes the later extraction pass a
            # cache hit on the same values.
            return bool(op.extractors) and bool(self._extract_op(op, row))
        verdicts = []
        cache = self._confirm_cache
        for matcher in op.matchers:
            if matcher.type in ("word", "regex", "binary", "size"):
                # 'op'-namespaced: the walk's confirm_matcher keys this
                # same dict by small-int matcher index — unnamespaced,
                # correctness would rest on id() never colliding with it
                part = row.part(matcher.part)
                key = ("op", id(matcher), part)
                v = cache.get(key)
                if v is None:
                    raw = (
                        self._regex_matcher_raw(matcher, part)
                        if matcher.type == "regex"
                        else None
                    )
                    if raw is not None:
                        v = (not raw) if matcher.negative else raw
                    else:
                        mv = cpu_ref.match_matcher(matcher, row)
                        v = bool(mv) if mv is not None else False
                    self._cache_put(cache, key, v)
            else:
                mv = cpu_ref.match_matcher(matcher, row)
                v = bool(mv) if mv is not None else False
            verdicts.append(v)
        if not verdicts:
            return False
        if op.matchers_condition == "and":
            return all(verdicts)
        return any(verdicts)

    def _redo_template(self, template, row: Response):
        """``(matched, extractions)`` — exactly the fields the redo
        pass reads from ``cpu_ref.match_template``, evaluated through
        the prefiltered+cached op/extract paths (identical semantics:
        _confirm_operation ≡ match_operation[0], _extract_op ≡ the
        oracle's extractor loop with content memoization)."""
        if not row.alive:
            return False, []
        matched = False
        extractions: list = []
        for op in template.operations:
            if self._confirm_operation(op, row):
                matched = True
                extractions.extend(self._extract_op(op, row))
        return matched, extractions

    def _confirm_matcher_serial(self, m_id: int, row: Response) -> bool:
        """Exact verdict of ONE device matcher for one row — the
        serial reference confirm (content-keyed cache + per-pattern
        proofs). The batched walk's precomputed planes must agree with
        this bit for bit (tests/test_walk_parallel.py); pairs the
        native passes can't answer re-run here."""
        matcher = self._m_obj[m_id]
        if matcher is None:
            # synthesized extraction prefilter: per-pattern verdict
            return self._confirm_ext_pattern(m_id, row)
        if matcher.type not in ("word", "regex", "binary", "size"):
            # dsl/status/kval read beyond matcher.part — not cacheable
            mv = cpu_ref.match_matcher(matcher, row)
            return bool(mv) if mv is not None else False
        part = row.part(matcher.part)
        key = ("m", m_id, part)
        cache = self._confirm_cache
        v = cache.get(key)
        if v is None:
            # exact per-pattern evaluation with literal/candidate
            # proofs: most confirms are q-gram collisions whose slow
            # regex (waf-detect's ~2 ms backtrackers) certainly can't
            # match — those are decided at bytes.find speed; unproven
            # patterns get a real re.search. Negation mirrors
            # cpu_ref.match_matcher.
            raw = (
                self._regex_matcher_raw(matcher, part)
                if matcher.type == "regex"
                else None
            )
            if raw is not None:
                v = (not raw) if matcher.negative else raw
            else:
                mv = cpu_ref.match_matcher(matcher, row)
                v = bool(mv) if mv is not None else False
            self._cache_put(cache, key, v)
        return v

    def _regex_matcher_raw(self, matcher, part: bytes):
        """The EXACT raw (pre-negation) verdict of a regex matcher over
        ``part`` — each pattern decided by the cheapest sound means:
        required-literal absence (bytes.find), candidate-anchored
        search (ops/fastre.py), or a real ``re.search`` when neither
        proof applies — with short-circuit under the matcher condition.
        Returns None when any pattern fails to compile (the oracle's
        unsupported semantics; caller must fall back)."""
        if not matcher.regex:
            return None
        infos = [fastre.analyze(p) for p in matcher.regex]
        if not all(i.ok for i in infos):
            return None
        lowered = None
        text = None
        want_all = matcher.condition == "and"
        for p, info in zip(matcher.regex, infos):
            if info.literals or info.cnf:
                if lowered is None:
                    lowered = part.lower()
                if fastre.literals_absent(info, lowered):
                    if want_all:
                        return False
                    continue
            if text is None:
                text = part.decode("latin-1")
            v = fastre.search_bool(p, part, text)
            if v is None:
                v = info.rex.search(text) is not None
            if v and not want_all:
                return True
            if not v and want_all:
                return False
        return want_all

    # ------------------------------------------------------------------
    def scheduler(self):
        """This engine's continuous-batching scheduler (lazily built;
        exists regardless of the ``pipeline`` flag so callers can drive
        it explicitly for A/B runs)."""
        if self._sched is None:
            from swarm_tpu.sched import BatchScheduler

            self._sched = BatchScheduler(self)
        return self._sched

    def match(self, responses: Sequence[Response]) -> list[RowMatches]:
        """Per-row exact match sets (compat/active-scanner form).

        Built from the packed path; per-row object assembly makes this
        the slower surface — bulk pipelines use :meth:`match_packed`.
        With ``pipeline="on"`` multi-row calls route through the
        continuous-batching scheduler (swarm_tpu/sched): memo-known
        rows short-circuit out of device batches, fresh rows are
        re-binned into padding buckets, encode/dispatch/walk overlap —
        results are bit-identical either way (tests/test_sched.py).
        """
        if self.pipeline == "on" and len(responses) > 1:
            return self.scheduler().match_rows(responses)
        # dead rows match nothing by contract; filtering them BEFORE
        # chunking keeps the pipelined pre-encode effective (a chunk
        # with any dead row would force match_packed to discard the
        # pre and re-encode the live subset serially)
        alive = [r for r in responses if r.alive]
        if len(alive) < len(responses):
            live_out = iter(self.match(alive))
            return [
                next(live_out)
                if r.alive
                else RowMatches(template_ids=[], extractions={})
                for r in responses
            ]
        out: list[RowMatches] = []
        chunks = [
            responses[s : s + self.batch_rows]
            for s in range(0, len(responses), self.batch_rows)
        ]
        for rows, pre in self._iter_encoded(chunks):
            packed = self.match_packed(rows, pre=pre)
            out.extend(self.rowmatches_from_packed(packed, len(rows)))
        return out

    def rowmatches_from_packed(self, packed: PackedMatches, n: int) -> list:
        """Per-row RowMatches assembly from one PackedMatches — THE
        single assembly used by both :meth:`match` and the scheduler
        (swarm_tpu/sched), so the pipelined path can never drift from
        the direct one. Per row: template ids ascending by template
        index, then the host-always tail.

        The verdict-plane scan runs as ONE native pass over the whole
        batch when the C lib is present — a per-row np.unpackbits costs
        more than the typical row's entire hit set at steady-state feed
        rates. Sparse side-tables are grouped by row ONCE (per-row
        scans of the whole extraction dict would be quadratic in fleet
        batches where extractor templates fire on most rows)."""
        NT = self.db.num_templates
        conf = packed.confirms_per_row
        extr_by_row: dict = {}
        for (rb, tid), ext in packed.extractions.items():
            extr_by_row.setdefault(rb, {})[tid] = ext
        always_by_row: dict = {}
        for rb, tid in packed.host_always_matches:
            always_by_row.setdefault(rb, []).append(tid)
        tid_names = self.db.template_ids
        tids_by_row: dict = {}
        if n and self._use_native_memo():
            from swarm_tpu.native.scanio import plane_bits

            rs, ts = plane_bits(
                np.ascontiguousarray(packed.bits[:n]), NT
            )
            for r, t in zip(rs.tolist(), ts.tolist()):
                tids_by_row.setdefault(r, []).append(tid_names[t])
        else:
            for b in range(n):
                hit = [
                    tid_names[t]
                    for t in _iter_set_bits(packed.bits[b], NT)
                ]
                if hit:
                    tids_by_row[b] = hit
        wf = packed.wf
        out = []
        for b in range(n):
            tids = tids_by_row.get(b, [])
            tids.extend(always_by_row.get(b, ()))
            row_wf = None
            if wf is not None and bool(wf["valid"][b]):
                row_wf = (
                    wf["cond_v"][b],
                    wf["cond_u"][b],
                    wf["emit_v"][b],
                    wf["emit_u"][b],
                )
            out.append(
                RowMatches(
                    template_ids=tids,
                    extractions=extr_by_row.get(b, {}),
                    confirmed_on_host=conf.get(b, 0),
                    wf=row_wf,
                )
            )
        return out

    # ------------------------------------------------------------------
    def _iter_encoded(self, chunks):
        """Yield (rows, pre_encoded) with the NEXT chunk's host encode
        overlapping the current chunk's device dispatch + confirmation
        (the encode is the feed ceiling at device rates; the device
        wait releases the GIL, so one helper thread recovers it)."""
        if len(chunks) <= 1:
            for c in chunks:
                yield c, None
            return
        from concurrent.futures import ThreadPoolExecutor

        if not self._backend_ready:
            self._resolve_backend()  # before threads touch the backend
        with ThreadPoolExecutor(max_workers=1) as pool:
            fut = pool.submit(self.encode_packed, chunks[0], True)
            for i, c in enumerate(chunks):
                pre = fut.result()
                if i + 1 < len(chunks):
                    fut = pool.submit(self.encode_packed, chunks[i + 1], True)
                yield c, pre

    # ------------------------------------------------------------------
    def _resolve_backend(self) -> None:
        """First-match mesh resolution (kept out of __init__ so engine
        construction never initializes the JAX backend)."""
        mesh = self._mesh_arg
        if mesh == "auto":
            import jax

            mesh = None
            if len(jax.devices()) > 1:
                from swarm_tpu.parallel.mesh import make_mesh

                mesh = make_mesh()
        if mesh is not None:
            from swarm_tpu.parallel.sharded import ShardedMatcher

            self.sharded = ShardedMatcher(self.db, mesh, candidate_k=self._candidate_k)
            self.mesh = mesh
            if self._aot_client is not None:
                # the sharded matcher is built lazily — a client
                # attached before backend resolution lands here
                self.sharded.attach_aot(self._aot_client)
        self._backend_ready = True

    # ------------------------------------------------------------------
    def encode_packed(self, rows: Sequence[Response], reuse_buffers: bool = False):
        """Public pre-encode for pipelined feeding: callers may encode
        batch i+1 on another thread while the device matches batch i
        (the encode is host memcpy work; the device dispatch releases
        the GIL) and pass the result to :meth:`match_packed` via
        ``pre``. Thread-safe after the first call resolved the backend.

        ``reuse_buffers=True`` draws the stream matrices from the
        recycled pool (faster, no zero-fill) — but a pooled batch's
        arrays are OVERWRITTEN a few same-shape encodes later
        (encoding._RotatingPool), so only enable it when each encoded
        batch is matched before more than a couple further encodes
        (the 1-deep pipelined pattern). The default allocates fresh
        arrays and is safe to hold indefinitely."""
        return self._encode_for_backend(rows, reuse_buffers=reuse_buffers)

    def _encode_for_backend(
        self, rows: Sequence[Response], reuse_buffers: bool = True
    ):
        """Encode rows for whichever device backend is active, CONTENT-
        DEDUPLICATED two ways: within the batch (fleet scans see the
        same default pages on most hosts) and ACROSS batches via the
        bounded verdict memo — content the engine has fully resolved
        before never rides the device again.

        Returns a TAGGED tuple. With the native lib present the first
        element is ``"native"`` (see :meth:`_encode_native` — the C
        resident cache serves known rows directly into a bits plane);
        the fallback is ``("py", batch, matcher, uniq, back, n_source,
        new_ids, keys, known)``: ``uniq``/``back`` are the in-batch
        dedup (slot ← source rows), ``keys[s]`` slot s's content key,
        ``new_ids`` the slots NOT served by the verdict memo, and
        ``batch`` covers exactly those (padded up to a 256-row bucket
        for a bounded set of jit shapes) — or None when every slot is
        known. The trailing ``known`` dict ({slot: memo entry})
        snapshots the served entries AT ENCODE TIME so eviction between
        a pipelined encode and its match can't lose a verdict.
        """
        if not self._backend_ready:
            self._resolve_backend()
        rows = list(rows)
        if self._use_native_memo():
            return self._encode_native(rows, reuse_buffers)
        uniq, back, keys = _dedup_rows(rows)
        memo = self._verdict_memo
        # snapshot known entries NOW: FIFO eviction between a pipelined
        # encode and its match must not lose a slot's served verdict
        known: dict = {}
        new_ids: list = []
        from swarm_tpu.ops.match import lru_fetch

        for s, k in enumerate(keys):
            # lru_fetch (not plain get): fleet-hot pages must stay
            # resident — FIFO would evict exactly the entries serving
            # the most rows
            entry = lru_fetch(memo, k)
            if entry is None:
                new_ids.append(s)
            else:
                known[s] = entry
        # L1 accounting + shared tier (docs/CACHING.md) — the dict-memo
        # fallback honors the same L1 → shared → device hierarchy as
        # the native path (slot-granular here: this IS the dedup plane)
        if len(known):
            L1_HITS.inc(len(known))
        if new_ids:
            L1_MISSES.inc(len(new_ids))
        if new_ids and self._result_cache is not None:
            seen = self._shared_seen
            if self._serve_shared(
                [
                    rows[uniq[s]]
                    for s in new_ids
                    if not seen or id(rows[uniq[s]]) not in seen
                ],
                into_native=False,
            ):
                still = []
                for s in new_ids:
                    entry = lru_fetch(memo, keys[s])
                    if entry is None:
                        still.append(s)
                    else:
                        known[s] = entry
                new_ids = still
        if not new_ids:
            return (
                "py", None, None, uniq, back, len(rows), new_ids, keys, known
            )
        nrows = [rows[uniq[s]] for s in new_ids]
        batch, matcher = self._encode_unique(nrows, reuse_buffers)
        return (
            "py", batch, matcher, uniq, back, len(rows), new_ids, keys, known
        )

    def _use_native_memo(self) -> bool:
        """Whether the C resident verdict cache drives the packed path
        (native lib present; the Python dict memo is the fallback)."""
        use = self._native_memo_ok
        if use is None:
            from swarm_tpu.ops.encoding import _native_encoder_available

            use = self._native_memo_ok = _native_encoder_available()
        return use

    def _encode_native(self, rows: list, reuse_buffers: bool):
        """C-memo encode: ONE native pass serves every known row's
        packed verdict straight into the batch plane (and collects
        their extras), in-batch-dedups the misses, and only the miss
        uniques are encoded for the device.

        The returned ``bits`` plane snapshots the MEMO STATE (eviction
        between a pipelined encode and its match can't lose a served
        verdict) — but with ``reuse_buffers=True`` its STORAGE is
        drawn from the per-shape rotating pool and is overwritten 8
        same-shape encodes later. The ``PackedMatches.bits`` a match
        assembles from it aliases this plane, so results that outlive
        the 1-deep pipelined consume pattern must ``.copy()`` (the
        recycling contract documented on :class:`PackedMatches`); the
        default allocating path hands back a plane the caller owns."""
        nbits = max((self.db.num_templates + 7) >> 3, 1)
        self._ensure_vmemo(nbits)
        if reuse_buffers:
            # A fresh ~1 MB np.empty per batch lands on mmap'd pages
            # whose first-touch faults cost more than the lookup pass
            # itself — draw from the per-shape rotating pool instead.
            # Depth 8 honors the documented recycled-plane contract
            # (each batch consumed within a few further encodes;
            # PackedMatches.bits aliases the pool, so callers holding
            # many results copy). Keying per shape means the bucketed
            # scheduler's alternating batch shapes — and the partial
            # final chunk — each rotate their own ring instead of
            # re-allocating all 8 planes on every shape change.
            bits = self._bits_pool.get(len(rows), nbits, "vbits")
        else:
            bits = np.empty((len(rows), nbits), dtype=np.uint8)
        state, miss_uniq, extr_known, deferred_known = (
            self._vmemo.lookup(rows, bits)
        )
        # L1 accounting (docs/CACHING.md): row-granular, BEFORE the
        # shared tier serves anything — a shared hit is not an L1 hit
        if len(rows):
            n_hit = int((state == -1).sum())
            n_miss = int((state >= 0).sum())
            if n_hit:
                L1_HITS.inc(n_hit)
            if n_miss:
                L1_MISSES.inc(n_miss)
        # shared tier behind the L1: serve the miss slots' contents
        # from the fleet cache, then re-run the lookup so served rows
        # resolve exactly like locally-known content (one extra native
        # pass, paid only when the tier actually held something). Rows
        # the scheduler prefetch already consulted are skipped — their
        # hits are in the L1 and their misses suppressed, so re-asking
        # would only re-hash the content.
        if miss_uniq and self._result_cache is not None:
            seen = self._shared_seen
            cand = [
                rows[i]
                for i in miss_uniq
                if not seen or id(rows[i]) not in seen
            ]
            if cand and self._serve_shared(cand, into_native=True):
                state, miss_uniq, extr_known, deferred_known = (
                    self._vmemo.lookup(rows, bits)
                )
        served = (extr_known, deferred_known)
        if not miss_uniq:
            return (
                "native", None, None, bits, state, miss_uniq, served,
                len(rows),
            )
        nrows = [rows[i] for i in miss_uniq]
        batch, matcher = self._encode_unique(nrows, reuse_buffers)
        return (
            "native", batch, matcher, bits, state, miss_uniq, served,
            len(rows),
        )

    def _encode_unique(self, nrows: list, reuse_buffers: bool):
        """Encode the distinct-content rows for the active backend.

        The sharded backend additionally needs the row count divisible
        by the 'data' axis and every stream width divisible by 'seq'
        with each per-rank slice at least one halo wide
        (parallel/sharded.py raises otherwise); padding is zeros, which
        the length masks already ignore, and padded rows are sliced off
        the verdicts."""
        n_pad = round_up(max(len(nrows), 1), 256)
        if self.sharded is None:
            batch = encode_batch(
                nrows,
                max_body=self.max_body,
                max_header=self.max_header,
                pad_rows_to=n_pad,
                # the "all" stream synthesizes on device (half the
                # encode bytes and H2D traffic stay on the host);
                # coarse width buckets bound the compiled-shape set —
                # the args kernel (docs/DEVICE_MATCH.md) makes each
                # shape's executable corpus-free, but a compile is
                # still a compile
                reuse_buffers=reuse_buffers,
                build_all=False,
                width_multiple=512,
            )
            return batch, self.device
        data_ranks = self.sharded.ranks.get("data", 1)
        seq_ranks = self.sharded.ranks.get("seq", 1)
        P = round_up(n_pad, data_ranks)
        row_index = None
        encode_rows = nrows
        if data_ranks > 1 and nrows:
            # scheduler-aware placement (docs/SHARDING.md): real rows
            # interleave into per-data-rank blocks, so a partially
            # filled bucket spreads its LIVE rows across every rank
            # instead of handing rank 0 all the work and ranks 1..R-1
            # pure padding (sharding over 'data' is contiguous blocks)
            encode_rows, row_index = _place_rows_per_rank(
                nrows, P, data_ranks
            )
        batch = encode_batch(
            encode_rows,
            max_body=self.max_body,
            max_header=self.max_header,
            pad_rows_to=P,
            reuse_buffers=reuse_buffers,
            width_multiple=512,
        )
        if row_index is not None:
            batch.row_index = row_index
            from swarm_tpu.telemetry import shard_export

            per = P // data_ranks
            counts = np.bincount(row_index // per, minlength=data_ranks)
            shard_export.RANK_FILL.set(float(counts.min()) / per)
        if seq_ranks > 1:
            from swarm_tpu.parallel.sharded import pad_streams_for_seq

            pad_streams_for_seq(batch.streams, seq_ranks, self.sharded.halo)
        return batch, self.sharded

    def data_ranks(self) -> int:
        """'data' mesh-axis size of the active backend (1 = single
        device). The scheduler's bucket planner reads this so planned
        row counts fill per shard (docs/SHARDING.md)."""
        if not self._backend_ready:
            self._resolve_backend()
        if self.sharded is None:
            return 1
        return int(self.sharded.ranks.get("data", 1))


    # ------------------------------------------------------------------
    # Device-degraded mode helpers (docs/RESILIENCE.md)
    # ------------------------------------------------------------------
    @staticmethod
    def _shape_class(batch) -> str:
        """Breaker key: one breaker per compiled batch shape class
        (rows × per-stream widths), so a width bucket whose executable
        is poisoned degrades alone while other shapes stay on device."""
        streams = getattr(batch, "streams", None) or {}
        parts = [
            f"{name}{arr.shape[-1]}" for name, arr in sorted(streams.items())
        ]
        rows = next(iter(streams.values())).shape[0] if streams else 0
        return f"r{rows}." + ".".join(parts)

    def _note_phase_split(self, matcher, dt: float) -> None:
        """Attribute one device interval to phase A/B from the
        matcher's per-dispatch ``last_compact["phase_a_s"]`` marker
        (popped — exactly one consumer per dispatch, so a later
        non-compacted or failed dispatch can't replay a stale split)."""
        # requires-lock: _stats_lock
        lc = getattr(matcher, "last_compact", None)
        pa = lc.pop("phase_a_s", None) if isinstance(lc, dict) else None
        if isinstance(pa, (int, float)) and pa > 0:
            pa = min(float(pa), dt)
            self.stats.phase_a_seconds += pa
            self.stats.phase_b_seconds += max(0.0, dt - pa)

    def _note_device_fault(self, breaker, exc: BaseException) -> None:
        # under the scheduler's walk offload this runs on the submit
        # thread (begin_packed) AND the walk worker (_walk_plane) —
        # same cross-thread contract as device_seconds
        with self._stats_lock:
            self.stats.device_faults += 1
        breaker.record_failure()
        print(
            f"device path failed ({type(exc).__name__}: {exc}); "
            f"falling back to CPU oracle "
            f"[breaker {breaker.name}: {breaker.state}]"
        )

    def _oracle_planes(self, B: int):
        """Synthesized device output for a degraded batch: zero value/
        uncertainty planes plus an all-true overflow vector, which the
        walk treats as 'redo every row on the host oracle' — exactness
        is the redo path's existing contract."""
        db = self.db
        ntb = max((db.num_templates + 7) >> 3, 1)
        nob = max((len(db.op_matchers) + 7) >> 3, 1)
        nmb = max((len(db.m_src) + 7) >> 3, 1)
        return (
            np.zeros((B, ntb), dtype=np.uint8),
            np.zeros((B, ntb), dtype=np.uint8),
            np.zeros((B, nob), dtype=np.uint8),
            np.zeros((B, nob), dtype=np.uint8),
            np.zeros((B, nmb), dtype=np.uint8),
            np.ones((B,), dtype=bool),
            # no workflow gate planes: the runner resolves every
            # condition on the host (exact by construction)
            None,
        )

    def _gather_confirm_candidates(
        self, pt_value, pt_unc, pop_value, pop_unc, pm_unc, skip
    ):
        """Every (row, matcher) / prefiltered (row, op) pair the walk's
        serial loops COULD resolve, gathered from the device planes in
        one pass. Overapproximates the extraction pass's undecided ops
        via ``(pt_value | pt_unc) & ext_mask`` (the post-resolution
        extractor plane is a subset: value bits only appear there if
        they were set before the walk or uncertain) — extra pairs cost
        speculative native scans, never accounting, because
        ``host_confirm_pairs`` counts resolve_op calls, which this
        never changes. Returns ``(by_matcher {m_id: [b, ...]},
        op_pairs [(b, op_id), ...])``."""
        from swarm_tpu.native.scanio import ext_resolve, plane_bits

        NT = self.db.num_templates
        rowdep = self._rowdep_t
        pseudo_t = self._pseudo_t
        seen_ops: set = set()
        op_cands: list = []  # (b, op_id) in need of a verdict
        # uncertain-template pairs: genuinely sparse, Python loop is fine
        ub, ut = plane_bits(np.ascontiguousarray(pt_unc), NT)
        for b, t_idx in zip(ub.tolist(), ut.tolist()):
            if b in skip or t_idx in rowdep or t_idx in pseudo_t:
                continue
            for op_id in self._t_ops_py[t_idx]:
                if not _bit(pop_unc, b, op_id):
                    continue
                key = (b, op_id)
                if key not in seen_ops:
                    seen_ops.add(key)
                    op_cands.append(key)
        # extractor-plane hits can be DENSE (tech templates fire on
        # most fleet rows): reuse the extraction pass's C driver over
        # the overapproximated plane and keep only its undecided
        # (state 2) ops — Python never touches the certain hits
        if len(self._ext_cols):
            emask = self._ext_byte_mask
            masked = (
                pt_value[:, : len(emask)] | pt_unc[:, : len(emask)]
            ) & emask[None, :]
            skip_rows = np.zeros(len(pt_value), dtype=np.uint8)
            for rb in skip:
                skip_rows[rb] = 1
            bs, ts, opsv, sts = ext_resolve(
                masked, NT, self._rowdep_mask, skip_rows,
                self._t_ops_indptr, self._t_ops_flat,
                np.ascontiguousarray(pop_value),
                np.ascontiguousarray(pop_unc),
            )
            und = sts == 2
            for b, t_idx, op_id in zip(
                bs[und].tolist(), ts[und].tolist(), opsv[und].tolist()
            ):
                if t_idx in pseudo_t:
                    continue  # decided by the extraction pass, never
                    # resolve_op'd — no confirms behind them
                key = (b, op_id)
                if key not in seen_ops:
                    seen_ops.add(key)
                    op_cands.append(key)
        by_matcher: dict = {}
        op_pairs: list = []
        mm_bs: list = []
        mm_ops: list = []
        for b, op_id in op_cands:
            if self._op_prefilter_py[op_id]:
                op_pairs.append((b, op_id))
            else:
                mm_bs.append(b)
                mm_ops.append(op_id)
        if mm_ops:
            # vectorized op → uncertain-matcher expansion: ONE fancy
            # index over the unpacked pm plane for the whole batch's
            # candidate ops (a per-op numpy gather costs ~8 us each —
            # thousands of pairs on the reference corpus)
            NM = len(self._m_obj)
            ops_arr = np.asarray(mm_ops, dtype=np.int64)
            bs_arr = np.asarray(mm_bs, dtype=np.int64)
            # unpack ONLY the candidate rows' pm bits: the full [B, NM]
            # plane is multi-MB at production batch sizes while the
            # candidate set is typically a handful of rows
            rows_u, row_local = np.unique(bs_arr, return_inverse=True)
            pm_bits = np.unpackbits(
                np.ascontiguousarray(pm_unc[rows_u]), axis=1, count=NM
            )
            counts = self._op_m_counts[ops_arr]
            total = int(counts.sum())
            if total:
                b_all = np.repeat(bs_arr, counts)
                # flat matcher ids of each candidate op, concatenated:
                # global position − local slice start + CSR offset
                idx = (
                    np.arange(total, dtype=np.int64)
                    - np.repeat(np.cumsum(counts) - counts, counts)
                    + np.repeat(self._op_m_indptr[ops_arr], counts)
                )
                m_all = self._op_m_flat[idx]
                sel = pm_bits[
                    np.repeat(row_local, counts), m_all
                ].astype(bool)
                for b, m in zip(
                    b_all[sel].tolist(), m_all[sel].tolist()
                ):
                    by_matcher.setdefault(m, []).append(b)
        return by_matcher, op_pairs

    #: distinct parts per pooled native shard — small enough that a
    #: 4-worker pool sees work from one big matcher group, large
    #: enough that per-task dispatch stays negligible
    _WALK_SHARD = 256
    #: below this many pending pairs the batch machinery costs more
    #: than the serial loops it would feed — skip it (results are
    #: identical either way; only where verdicts come from changes)
    _WALK_MIN_PAIRS = 16

    def _precompute_confirms(
        self, nrows, pt_value, pt_unc, pop_value, pop_unc, pm_unc, skip
    ):
        """Row-parallel batched confirm (docs/HOST_WALK.md).

        Plan phase (this thread): gather the batch's pending pairs,
        group them BY MATCHER, short-circuit pairs the cross-batch
        ``_confirm_cache`` already holds, and content-dedup the rest
        per matcher (distinct part bytes, not rows, are the unit of
        work — repeated internet content confirms once). Dispatch
        phase: each (matcher, part-shard) group resolves in one
        GIL-released native pass — ``sw_confirm_needles_batch`` for
        word/binary, crex DFA/NFA ``exists_batch`` per regex pattern —
        sharded across the walk pool; pairs the native passes can't
        answer exactly (unsupported patterns, dsl/status/kval, stale
        .so) re-run on the serial reference path inside pooled
        fallback tasks, so every verdict is bit-identical to
        ``_confirm_matcher_serial``. Merge phase (this thread): fold
        each task's private verdicts and cache inserts back into the
        shared ``_confirm_cache`` (per-thread shards merged at batch
        end — worker tasks never mutate the shared dict mid-flight).

        Returns ``({(b, m_id): bool}, {(b, op_id): bool})``.
        """
        t0 = time.perf_counter()
        by_matcher, op_pairs = self._gather_confirm_candidates(
            pt_value, pt_unc, pop_value, pop_unc, pm_unc, skip
        )
        pre_m: dict = {}
        pre_op: dict = {}
        n_pending = sum(map(len, by_matcher.values())) + len(op_pairs)
        if n_pending < self._WALK_MIN_PAIRS:
            # near-empty batch: the serial loops resolve a handful of
            # pairs faster than the group/dispatch/merge machinery
            # costs — the stats time below still records the plan
            self.stats.walk_precompute_seconds += (
                time.perf_counter() - t0
            )
            return pre_m, pre_op
        from swarm_tpu.native import crex as ncrex
        from swarm_tpu.native.scanio import confirm_needles_batch

        cache = self._confirm_cache
        # confirm-family promotion (docs/CACHING.md): the shared tier's
        # second value family serves/absorbs the part-keyed confirm
        # verdicts around the batched native passes — local cache
        # first, one batched tier lookup per matcher group, and every
        # merged insert batch-writes back at the end
        shared = self._result_cache
        if shared is not None and not shared.confirm:
            shared = None
        shared_inserts: list = []
        parts_of: dict = {}  # (b, part_name) -> bytes

        def row_part(b: int, name) -> bytes:
            key = (b, name)
            p = parts_of.get(key)
            if p is None:
                p = parts_of[key] = nrows[b].part(name)
            return p

        tasks: list = []      # zero-arg callables -> (verdicts, inserts)
        fallback: list = []   # (b, m_id) pairs for the serial reference

        def needle_task(m_id, matcher, part_rows, needles, ci, cond_and):
            neg = bool(matcher.negative)

            def run():
                parts = [p for p, _bs in part_rows]
                raw = confirm_needles_batch(parts, needles, ci, cond_and)
                verdicts: dict = {}
                inserts: list = []
                if raw is None:  # stale .so: serial reference per pair
                    for p, bs_ in part_rows:
                        for b in bs_:
                            verdicts[(b, m_id)] = (
                                self._confirm_matcher_serial(m_id, nrows[b])
                            )
                    return verdicts, inserts, 0
                native = 0
                for (p, bs_), rv in zip(part_rows, raw.tolist()):
                    v = (not rv) if neg else bool(rv)
                    inserts.append((("m", m_id, p), v))
                    for b in bs_:
                        verdicts[(b, m_id)] = v
                        native += 1
                return verdicts, inserts, native

            return run

        def regex_task(m_id, matcher, part_rows, infos):
            neg = bool(matcher.negative)
            want_all = matcher.condition == "and"

            def run():
                verdicts: dict = {}
                inserts: list = []
                # pattern waterfall over still-undecided distinct
                # parts: exact per-pattern existence short-circuits
                # under the matcher condition exactly like
                # _regex_matcher_raw's loop (evaluation order is the
                # pattern order either way, so the combine is
                # identical); any non-exact item falls back whole.
                pending = list(part_rows)
                decided: list = []  # (part, bs, raw)
                bad: list = []
                for info in infos:
                    if not pending:
                        break
                    res = ncrex.exists_batch(
                        info.nfa, [p for p, _bs in pending]
                    )
                    if res is None:
                        bad.extend(pending)
                        pending = []
                        break
                    nxt: list = []
                    for (p, bs_), rv in zip(pending, res.tolist()):
                        if rv < 0:
                            bad.append((p, bs_))
                        elif want_all and not rv:
                            decided.append((p, bs_, False))
                        elif not want_all and rv:
                            decided.append((p, bs_, True))
                        else:
                            nxt.append((p, bs_))
                    pending = nxt
                # patterns exhausted without a short-circuit: the
                # combine's identity value (all -> True, any -> False)
                decided.extend((p, bs_, want_all) for p, bs_ in pending)
                native = 0
                for p, bs_, raw in decided:
                    v = (not raw) if neg else raw
                    inserts.append((("m", m_id, p), v))
                    for b in bs_:
                        verdicts[(b, m_id)] = v
                        native += 1
                for p, bs_ in bad:
                    for b in bs_:
                        verdicts[(b, m_id)] = (
                            self._confirm_matcher_serial(m_id, nrows[b])
                        )
                return verdicts, inserts, native

            return run

        def ext_pattern_task(m_id, pattern, part_rows, info):
            def run():
                verdicts: dict = {}
                inserts: list = []
                native = 0
                res = (
                    ncrex.exists_batch(info.nfa, [p for p, _b in part_rows])
                    if info.ok
                    else None
                )
                for idx, (p, bs_) in enumerate(part_rows):
                    is_native = False
                    if not info.ok:
                        v = False  # invalid under re: extracts nothing
                    elif res is not None and res[idx] >= 0:
                        v = bool(res[idx])
                        is_native = True
                    else:
                        text = p.decode("latin-1")
                        sv = fastre.search_bool(pattern, p, text)
                        if sv is None:
                            sv = info.rex.search(text) is not None
                        v = bool(sv)
                    inserts.append((("pe", m_id, p), v))
                    for b in bs_:
                        verdicts[(b, m_id)] = v
                        if is_native:
                            native += 1
                return verdicts, inserts, native

            return run

        def shard(part_rows: list) -> list:
            n = self._WALK_SHARD
            return [
                part_rows[i : i + n] for i in range(0, len(part_rows), n)
            ] or [[]]

        def dedup_misses(m_id, bs, part_name, cache_tag) -> list:
            """Cache-serve what the cross-batch memo holds, then the
            shared tier (one batched lookup per matcher group); group
            the remaining misses by DISTINCT part bytes →
            [(part, [b, ...]), ...]. A tier-served verdict also lands
            in the local cache so the next batch never re-asks."""
            by_part: dict = {}
            for b in bs:
                p = row_part(b, part_name)
                v = cache.get((cache_tag, m_id, p))
                if v is not None:
                    pre_m[(b, m_id)] = v
                else:
                    by_part.setdefault(p, []).append(b)
            if by_part and shared is not None:
                got = shared.lookup_confirms(
                    [(cache_tag, m_id, p) for p in by_part]
                )
                for key, v in got.items():
                    for b in by_part.pop(key[2]):
                        pre_m[(b, m_id)] = v
                    self._cache_put(cache, key, v)
            return list(by_part.items())

        for m_id, bs in by_matcher.items():
            matcher = self._m_obj[m_id]
            if matcher is None:
                op = self._op_obj[self._m_op_id[m_id]]
                ex_local, p_idx = self._m_ext_src_py[m_id]
                if ex_local < 0:  # fire-always degrade: whole-op path
                    fallback.extend((b, m_id) for b in bs)
                    continue
                ex = op.extractors[ex_local]
                pattern = ex.regex[p_idx]
                part_rows = dedup_misses(m_id, bs, ex.part, "pe")
                info = fastre.analyze(pattern)
                # one task per matcher, not per shard: the pattern's
                # lazy-DFA context serializes on its mutex, so sharding
                # ONE pattern across threads only buys lock ping-pong —
                # distinct matchers still run concurrently
                if part_rows:
                    tasks.append(
                        ext_pattern_task(m_id, pattern, part_rows, info)
                    )
                continue
            mtype = matcher.type
            if mtype in ("word", "binary"):
                if mtype == "word":
                    ci = bool(matcher.case_insensitive)
                    needles = [
                        w.encode("utf-8", "surrogateescape")
                        for w in matcher.words
                    ]
                    if ci:
                        needles = [nd.lower() for nd in needles]
                else:
                    ci = False
                    import binascii as _ba

                    try:
                        needles = [
                            _ba.unhexlify(re.sub(r"\s", "", hx))
                            for hx in matcher.binary
                        ]
                    except (_ba.Error, ValueError):
                        # oracle's unsupported path (verdict False):
                        # keep it on the serial reference
                        fallback.extend((b, m_id) for b in bs)
                        continue
                if not needles:
                    # empty needle list is False before the combine
                    # (cpu_ref), then negation applies
                    v = bool(matcher.negative)
                    for b in bs:
                        pre_m[(b, m_id)] = v
                    continue
                part_rows = dedup_misses(m_id, bs, matcher.part, "m")
                cond_and = matcher.condition == "and"
                for sh in shard(part_rows):
                    if sh:
                        tasks.append(
                            needle_task(m_id, matcher, sh, needles, ci,
                                        cond_and)
                        )
            elif mtype == "regex":
                infos = [fastre.analyze(p) for p in matcher.regex]
                if not matcher.regex or not all(i.ok for i in infos):
                    # raw would be None (no patterns / a pattern the
                    # oracle can't compile): serial reference keeps
                    # the oracle-fallback semantics exact
                    fallback.extend((b, m_id) for b in bs)
                    continue
                part_rows = dedup_misses(m_id, bs, matcher.part, "m")
                # per-matcher task (no shards): see the DFA-mutex note
                # on the ext-prefilter branch above
                if part_rows:
                    tasks.append(regex_task(m_id, matcher, part_rows, infos))
            elif mtype == "size":
                sizes = matcher.size
                neg = bool(matcher.negative)
                want_all = matcher.condition == "and"
                for b in bs:
                    p = row_part(b, matcher.part)
                    key = ("m", m_id, p)
                    v = cache.get(key)
                    if v is None:
                        if not sizes:
                            raw = False
                        elif want_all:
                            raw = all(len(p) == s for s in sizes)
                        else:
                            raw = any(len(p) == s for s in sizes)
                        v = (not raw) if neg else raw
                        self._cache_put(cache, key, v)
                        # NOT promoted to the tier: the size branch
                        # decides inline (a length compare) and never
                        # consults the confirm family, so sharing
                        # these would be write-only tier traffic
                    pre_m[(b, m_id)] = v
            else:
                # dsl/status/kval read beyond the part — serial pairs
                fallback.extend((b, m_id) for b in bs)

        if fallback:
            def fallback_task(pairs):
                def run():
                    return (
                        {
                            (b, m): self._confirm_matcher_serial(
                                m, nrows[b]
                            )
                            for b, m in pairs
                        },
                        (),
                        0,
                    )

                return run

            tasks.append(fallback_task(fallback))
        if op_pairs:
            def op_task(pairs):
                def run():
                    return (
                        {
                            ("op", b, o): self._confirm_operation(
                                self._op_obj[o], nrows[b]
                            )
                            for b, o in pairs
                        },
                        (),
                        0,
                    )

                return run

            tasks.append(op_task(op_pairs))

        pool = self._walk_pool() if tasks else None
        if pool is not None and len(tasks) > 1:
            results = list(pool.map(lambda f: f(), tasks))
        else:
            results = [f() for f in tasks]
        native_pairs = 0
        for verdicts, inserts, native in results:
            native_pairs += native
            for key, v in verdicts.items():
                if len(key) == 3:  # ("op", b, op_id) from op_task
                    pre_op[(key[1], key[2])] = v
                else:
                    pre_m[key] = v
            for ck, v in inserts:
                self._cache_put(cache, ck, v)
                shared_inserts.append((ck, v))
        # batch-promote this round's freshly decided confirms into the
        # tier's confirm family — every insert key here is one of the
        # shareable ("m"|"pe", m_id, part) namespaces by construction
        # (the per-object "op"-tagged keys never reach the insert lists)
        if shared_inserts and shared is not None:
            shared.writeback_confirms(shared_inserts)
        # ONLY pairs the grouped native passes actually decided — not
        # cache-served, plan-inline (size/empty-needle), or serial-
        # fallback pairs — so the gauge attributes real native load
        self.stats.walk_batched_pairs += native_pairs
        if tasks:
            self.stats.walk_batch_rounds += 1
        self.stats.walk_precompute_seconds += time.perf_counter() - t0
        return pre_m, pre_op

    def _walk_plane(self, nrows, batch, matcher, pending=None):
        """Device dispatch + sparse host resolution over DISTINCT new
        response contents (the unique content plane).

        ``pending`` is an already-launched device computation from
        :meth:`begin_packed` (DeviceDB.dispatch): the walk then only
        pays the blocking host read, and the kernel ran while the
        caller walked a previous batch.

        Returns ``(pt_value, uextractions, deferred, redo_pos,
        confirms)``: the final content-side verdict bits ``[B, nb]``
        (row-dependent undecided bits cleared and listed in
        ``deferred`` as ``(pos, t_idx)`` for per-member resolution),
        content-side extractions ``{(pos, tid): vals}``, the positions
        that needed a whole-row oracle redo (truncation/overflow —
        never memoized), and per-position host-confirm counts."""
        NT = self.db.num_templates
        db = self.db
        B = len(nrows)
        t0 = time.perf_counter()
        planes = None
        breaker = self._device_breakers.get(self._shape_class(batch))
        if pending is not None:
            try:
                planes = matcher.collect(pending)
                breaker.record_success()
            except Exception as e:
                self._note_device_fault(breaker, e)
        elif breaker.allow():
            try:
                planes = matcher.match(
                    batch.streams, batch.lengths, batch.status, full=True
                )
                breaker.record_success()
            except Exception as e:
                self._note_device_fault(breaker, e)
        if planes is None:
            # degraded mode: the all-overflow plane routes every row
            # through the whole-row oracle redo below — the same exact
            # path truncated/overflowed rows always take, so verdicts
            # and extractions are bit-identical to the device path
            planes = self._oracle_planes(B)
            self.stats.degraded_batches += 1
        pt_value, pt_unc, pop_value, pop_unc, pm_unc, overflow, wf_planes = (
            planes
        )
        # slice off bucket/mesh row padding before the host walk: the
        # leading B positions on the single-device layout, a fancy-
        # index gather when the sharded placement interleaved real
        # rows into per-data-rank blocks (batch.row_index). Degraded-
        # mode oracle planes are already B rows — identity either way.
        ridx = getattr(batch, "row_index", None)

        def _rows_view(a):
            a = np.asarray(a)
            if ridx is not None and a.shape[0] != B:
                return a[ridx]
            return a[:B]

        # np.array(order="C"): ALWAYS a writable copy (the row-redo
        # pass writes rowbits back) AND row-major — XLA may hand back
        # F-ordered planes, which would poison every derived array
        # handed to the native pass (order-'K' copies preserve F)
        pt_value = np.array(_rows_view(pt_value), order="C")
        pt_unc = _rows_view(pt_unc)
        pop_value = _rows_view(pop_value)
        pop_unc = _rows_view(pop_unc)
        pm_unc = _rows_view(pm_unc)
        overflow = _rows_view(overflow)
        if wf_planes is not None:
            # workflow gate planes (docs/WORKFLOWS.md): packed cond/emit
            # value+uncertainty bits, sliced to the same row view; the
            # caller invalidates redo rows (their planes were computed
            # from unsound word bits)
            wf_planes = tuple(
                np.ascontiguousarray(_rows_view(p)) for p in wf_planes
            )
        with self._stats_lock:
            dt_dev = time.perf_counter() - t0
            self.stats.device_seconds += dt_dev
            self._note_phase_split(matcher, dt_dev)
        # compile-time attribution rides the matcher's counters (the
        # sharded matcher carries the same spy fields per mesh shape)
        self.stats.device_compile_seconds = getattr(
            matcher, "compile_seconds", 0.0
        )
        self.stats.device_compiles = getattr(matcher, "compile_count", 0)
        self.stats.device_fetch_seconds = getattr(
            matcher, "fetch_seconds", 0.0
        )
        self.stats.device_fetches = getattr(matcher, "fetch_count", 0)
        # rows needing whole-row reconfirmation (candidate overflow or
        # stream truncation made word bits unsound for the row)
        row_redo = overflow | _rows_view(batch.truncated)
        self.stats.overflow_rows += int(row_redo.sum())

        t1 = time.perf_counter()
        confirms: dict = {}
        op_cache: dict = {}  # (b, op_id) -> exact bool
        # precomputed verdict planes from the row-parallel batched
        # confirm (docs/HOST_WALK.md): filled after the redo pass below,
        # consulted first by confirm_matcher/resolve_op. The resolution
        # structure (loops, short-circuits, counting) is untouched —
        # only where a pair's verdict COMES FROM changes, so verdicts
        # and host_confirm_pairs stay bit-identical to the serial walk.
        pre_m: dict = {}   # (b, m_id) -> exact bool
        pre_op: dict = {}  # (b, op_id) -> exact bool (prefiltered ops)

        def confirm_matcher(b: int, m_id: int, row: Response) -> bool:
            v = pre_m.get((b, m_id))
            if v is not None:
                return v
            return self._confirm_matcher_serial(m_id, row)

        op_prefilter = self._op_prefilter_py
        op_cond_and = self._op_cond_and_py

        def resolve_op(b: int, op_id: int, row: Response) -> bool:
            key = (b, op_id)
            v = op_cache.get(key)
            if v is not None:
                return v
            if not _bit(pop_unc, b, op_id):
                v = _bit(pop_value, b, op_id)
            elif op_prefilter[op_id]:
                # superset-lowered op: per-matcher bits are weakened, so
                # fired rows re-run the whole op (prefiltered + cached
                # per matcher — semantics identical to the oracle's
                # match_operation); the batched walk may have resolved
                # it already
                v = pre_op.get(key)
                if v is None:
                    v = self._confirm_operation(self._op_obj[op_id], row)
                confirms[b] = confirms.get(b, 0) + 1
                self.stats.host_confirm_pairs += 1
            else:
                # undecided ⇒ certain matchers are neutral; combine the
                # uncertain ones' exact values under the op condition
                ids = self._op_m_arr[op_id]
                bits = (
                    pm_unc[b, self._op_m_bytes[op_id]]
                    >> self._op_m_shift[op_id]
                ) & 1
                vals = [
                    confirm_matcher(b, int(m_id), row)
                    for m_id in ids[bits.astype(bool)]
                ]
                confirms[b] = confirms.get(b, 0) + len(vals)
                self.stats.host_confirm_pairs += len(vals)
                v = all(vals) if op_cond_and[op_id] else any(vals)
            op_cache[key] = v
            return v

        rowdep = self._rowdep_t
        # (unique slot, t_idx) pairs whose verdict must be decided per
        # MEMBER row (row-dependent template went device-undecided)
        deferred: list = []

        # --- full-row redo (rare): the oracle end to end, extractions
        # included (the extraction pass below skips these rows).
        # Content-independent templates run once on the representative;
        # row-dependent ones run per member in the fixup pass below ---
        redo_rows = np.flatnonzero(row_redo)
        uredo_extractions: dict = {}  # (new-subset pos, tid) -> values
        for b in redo_rows.tolist():
            row = nrows[b]
            rowbits = np.zeros((pt_value.shape[1],), dtype=np.uint8)
            for t_idx, template in enumerate(db.templates):
                if t_idx in rowdep:
                    deferred.append((b, t_idx))
                    continue
                res_matched, res_ext = self._redo_template(template, row)
                confirms[b] = confirms.get(b, 0) + 1
                self.stats.host_confirm_pairs += 1
                if res_matched:
                    rowbits[t_idx >> 3] |= 0x80 >> (t_idx & 7)
                    if res_ext:
                        uredo_extractions[(b, template.id)] = res_ext
            pt_value[b] = rowbits

        # --- sparse uncertainty resolution (unique plane) ---
        t_unc = time.perf_counter()
        use_native = self._use_native_memo()
        # row-parallel batched confirm (docs/HOST_WALK.md): resolve the
        # whole batch's pending (row, matcher) pairs with grouped
        # GIL-released native passes BEFORE the serial-structured loops
        # below consume them. walk_threads=0 keeps the reference walk.
        if use_native and not row_redo.all() and self.walk_threads > 0:
            pre_m, pre_op = self._precompute_confirms(
                nrows, pt_value, pt_unc, pop_value, pop_unc, pm_unc,
                set(redo_rows.tolist()),
            )
        # (b, t_idx) pairs whose verdict is decided by the extraction
        # pass below (pseudo-ext templates on the native path)
        pseudo_pending: list = []
        if not row_redo.all():
            skip = set(redo_rows.tolist())
            if use_native:
                from swarm_tpu.native.scanio import plane_bits

                ub, ut = plane_bits(np.ascontiguousarray(pt_unc), NT)
                pairs = zip(ub.tolist(), ut.tolist())
            else:
                pairs = (
                    (b, byte_i * 8 + k)
                    for b, byte_i in np.argwhere(pt_unc).tolist()
                    for k in range(8)
                    if (int(pt_unc[b, byte_i]) & (0x80 >> k))
                    and byte_i * 8 + k < NT
                )
            pseudo_t = self._pseudo_t
            for b, t_idx in pairs:
                if b in skip:
                    continue
                byte_i = t_idx >> 3
                mask = 0x80 >> (t_idx & 7)
                if (
                    use_native
                    and t_idx in pseudo_t
                    and t_idx not in rowdep
                ):
                    # verdict == extraction non-emptiness: decided by
                    # the batched extraction pass (bit set there on
                    # extraction); per-pair confirm calls cost ~10x
                    # the batched native scan at walk rates
                    pseudo_pending.append((b, t_idx))
                    pt_value[b, byte_i] &= 0xFF ^ mask
                    continue
                row = nrows[b]
                if t_idx in rowdep:
                    # undecided row-dependent template: content-
                    # identical rows can disagree here — decide per
                    # member below; clear the broadcast bit
                    deferred.append((b, t_idx))
                    pt_value[b, byte_i] &= 0xFF ^ mask
                    continue
                # undecided ⇒ no certain-true op; OR over the
                # uncertain ops' exact values decides the template
                hit = False
                for op_id in self._t_ops_py[t_idx]:
                    if _bit(pop_unc, b, op_id) and resolve_op(
                        b, op_id, row
                    ):
                        hit = True
                        break
                if hit:
                    pt_value[b, byte_i] |= mask
                else:
                    pt_value[b, byte_i] &= 0xFF ^ mask

        # --- extraction pass (unique plane): only extractor templates,
        # only hit rows (one vectorized gather over all extractor
        # columns at once — a Python loop over ~600 extractor templates
        # costs more than the actual extractions). Row-dependent
        # templates are handled in the member fixup pass ---
        t_ext = time.perf_counter()
        self.stats.unc_seconds += t_ext - t_unc
        uextractions: dict = dict(uredo_extractions)
        redo_set = set(redo_rows.tolist())
        if len(self._ext_cols):
            emask = self._ext_byte_mask
            masked = pt_value[:, : len(emask)] & emask[None, :]
            tids = db.template_ids
            if self._use_native_memo():
                # one C pass enumerates the extractor-plane hits AND
                # resolves op certainty against the packed planes —
                # Python touches only ops that are certainly-true
                # (extract) or undecided (resolve_op), in the same
                # (b-major, t, op) order the Python loop used
                from swarm_tpu.native.scanio import ext_resolve

                skip_rows = np.zeros(len(nrows), dtype=np.uint8)
                for rb in redo_set:
                    skip_rows[rb] = 1
                bs, ts, opsv, sts = ext_resolve(
                    masked, NT, self._rowdep_mask, skip_rows,
                    self._t_ops_indptr, self._t_ops_flat,
                    np.ascontiguousarray(pop_value),
                    np.ascontiguousarray(pop_unc),
                )
                t_sub = time.perf_counter()
                self.stats.ext_enum_seconds += t_sub - t_ext
                # certainty resolution stays in (b-major, t, op) order;
                # the regex extractions themselves then run BATCHED —
                # one native dispatch per distinct pattern over every
                # pending content (per-call overhead dominated the
                # fresh-content walk at per-hit rates)
                pending: list = []
                for b, t_idx, op_id, st in zip(
                    bs.tolist(), ts.tolist(), opsv.tolist(), sts.tolist()
                ):
                    if st == 2 and not resolve_op(b, op_id, nrows[b]):
                        continue
                    pending.append((b, t_idx, op_id))
                # deferred pseudo-ext verdicts ride the same batch:
                # each uncertain op with >= 1 live pattern joins the
                # pending list; its (b, t) verdict bit is set below
                # iff the batched extraction produced values
                pseudo_set = set()
                for b, t_idx in pseudo_pending:
                    pseudo_set.add((b, t_idx))
                    for op_id in self._t_ops_py[t_idx]:
                        if _bit(pop_unc, b, op_id):
                            pending.append((b, t_idx, op_id))
                if pseudo_pending:
                    self.stats.host_confirm_pairs += len(pseudo_pending)
                    for b, _t in pseudo_pending:
                        confirms[b] = confirms.get(b, 0) + 1
                # live-pattern hints for per-pattern extraction
                # prefilters: the device pm-uncertainty bits already
                # say WHICH patterns' literals occurred — the
                # extraction pass then skips every other pattern with
                # no host scanning at all (certain-false bits are an
                # exact no-match proof)
                # hints are {ex_local: [p_idx, ...]} with only LIVE
                # patterns (flatnonzero is ascending and matcher order
                # is (ex_local, p_idx)-ascending, so lists stay in
                # pattern order) — consumers never touch the op's full
                # pattern population. The pm-plane gather batches per
                # op across all its pending rows: one 2D fancy-index
                # instead of a per-(row, op) 689-element gather.
                hints: dict = {}
                by_op: dict = {}
                for b, _t_idx, op_id in pending:
                    if op_id in self._op_ext_pats:
                        by_op.setdefault(op_id, set()).add(b)
                for op_id, bset in by_op.items():
                    rows_ = sorted(bset)
                    bits2 = (
                        pm_unc[np.ix_(rows_, self._op_m_bytes[op_id])]
                        >> self._op_m_shift[op_id][None, :]
                    ) & 1
                    pats = self._op_ext_pats[op_id]
                    for b in rows_:
                        hints[(b, op_id)] = {}
                    # one nonzero over the whole (rows × matchers)
                    # plane instead of a per-row flatnonzero
                    ris, ks = np.nonzero(bits2)
                    for ri, k in zip(ris.tolist(), ks.tolist()):
                        el, pi = pats[k]
                        hints[(rows_[ri], op_id)].setdefault(
                            el, []
                        ).append(pi)
                t_sub2 = time.perf_counter()
                self.stats.ext_resolve_seconds += t_sub2 - t_sub
                for (b, t_idx), vals in self._extract_pending(
                    pending, nrows, hints
                ).items():
                    if vals:
                        uextractions[(b, tids[t_idx])] = vals
                        if (b, t_idx) in pseudo_set:
                            # fused verdict: extraction fired
                            pt_value[b, t_idx >> 3] |= 0x80 >> (t_idx & 7)
                self.stats.ext_extract_seconds += (
                    time.perf_counter() - t_sub2
                )
            else:
                hit_b, hit_t = np.nonzero(
                    np.unpackbits(masked, axis=1, count=NT)
                )
                t_ops = self._t_ops_py
                for b, t_idx in zip(hit_b.tolist(), hit_t.tolist()):
                    if b in redo_set:
                        continue  # oracle already extracted above
                    if t_idx in rowdep:
                        continue
                    row = nrows[b]
                    parts = []
                    for op_id in t_ops[t_idx]:
                        if resolve_op(b, op_id, row):
                            parts.extend(
                                self._extract_op(self._op_obj[op_id], row)
                            )
                    if parts:
                        uextractions[(b, tids[t_idx])] = parts

        self.stats.ext_seconds += time.perf_counter() - t_ext
        self.stats.host_confirm_seconds += time.perf_counter() - t1
        return (
            pt_value,
            uextractions,
            deferred,
            set(redo_rows.tolist()),
            confirms,
            wf_planes,
        )

    # ------------------------------------------------------------------
    def begin_packed(self, all_rows: Sequence[Response], pre=None):
        """Start a batch WITHOUT blocking on the device: encode (or
        adopt ``pre``, an :meth:`encode_packed` result for the same
        rows) and launch the device kernel asynchronously. Returns an
        opaque in-flight handle for :meth:`finish_packed`.

        This is the continuous-batching scheduler's submission surface:
        with bounded in-flight handles the device crunches batch i+1
        while the host walks batch i. The split is only effective on
        the native-memo single-device path (DeviceDB.dispatch); other
        configurations defer all work to finish time — same results,
        no overlap."""
        if pre is None and self._use_native_memo():
            pre = self._encode_for_backend(all_rows)
        if pre is None or pre[0] != "native":
            return ("deferred", all_rows, pre, None)
        batch, matcher = pre[1], pre[2]
        pending = None
        if batch is not None and hasattr(matcher, "dispatch"):
            breaker = self._device_breakers.get(self._shape_class(batch))
            if breaker.allow():
                t0 = time.perf_counter()
                try:
                    pending = matcher.dispatch(
                        batch.streams, batch.lengths, batch.status
                    )
                except Exception as e:
                    # async launch failed: degrade this batch (the walk
                    # re-tries the sync path only if the breaker allows)
                    self._note_device_fault(breaker, e)
                with self._stats_lock:
                    dt_dev = time.perf_counter() - t0
                    self.stats.device_seconds += dt_dev
                    self._note_phase_split(matcher, dt_dev)
        return ("native", all_rows, pre, pending)

    def finish_packed(self, handle) -> PackedMatches:
        """Complete a :meth:`begin_packed` batch: block on the device
        read, run the sparse host walk, assemble exact verdicts —
        bit-identical to :meth:`match_packed` on the same rows."""
        tag, rows, pre, pending = handle
        if tag == "deferred":
            return self.match_packed(rows, pre=pre)
        return self._match_packed_native(rows, pre, pending=pending)

    # ------------------------------------------------------------------
    def match_packed(
        self, all_rows: Sequence[Response], pre=None
    ) -> PackedMatches:
        """Exact verdict bitsets for up to ``batch_rows`` responses.

        The production wire format: one device dispatch, vectorized
        verdict assembly, host work proportional to the number of
        *uncertain fired matchers* — not to rows × templates.

        ``pre`` is an optional :meth:`encode_packed` result for the SAME
        rows (pipelined feeding). The native path handles dead rows
        inline (the C lookup serves them as zero-verdict rows); on the
        fallback path a batch with dead rows ignores ``pre`` (the
        live-subset recursion re-encodes).
        """
        # native resident-cache path: the C lookup pass already folds
        # in the dead-row contract, so no alive pre-pass is needed
        if pre is not None:
            if pre[0] == "native":
                return self._match_packed_native(all_rows, pre)
        elif self._use_native_memo():
            return self._match_packed_native(
                all_rows, self._encode_for_backend(all_rows)
            )
        NT = self.db.num_templates
        nbytes = (NT + 7) >> 3
        # dead rows (no response observed) match nothing by contract —
        # drop them before encoding so the device never pays for them
        n_alive, alive_idx = _alive_split(all_rows)
        if n_alive < len(all_rows):
            bits = np.zeros((len(all_rows), max(nbytes, 1)), dtype=np.uint8)
            extractions: dict = {}
            host_always: list = []
            conf: dict = {}
            wf_full: Optional[dict] = None
            if alive_idx:
                live = self.match_packed([all_rows[i] for i in alive_idx])
                back = {j: i for j, i in enumerate(alive_idx)}
                for j, i in enumerate(alive_idx):
                    bits[i] = live.bits[j]
                extractions = {
                    (back[rb], tid): ext
                    for (rb, tid), ext in live.extractions.items()
                }
                host_always = [
                    (back[rb], tid) for rb, tid in live.host_always_matches
                ]
                conf = {
                    back[rb]: n for rb, n in live.confirms_per_row.items()
                }
                if live.wf is not None:
                    # dead rows keep valid=False planes: workflows
                    # match nothing on them by the same contract
                    wf_full = {
                        k: np.zeros(
                            (len(all_rows),) + v.shape[1:], dtype=v.dtype
                        )
                        for k, v in live.wf.items()
                    }
                    for j, i in enumerate(alive_idx):
                        for k, v in live.wf.items():
                            wf_full[k][i] = v[j]
            self.stats.rows += len(all_rows) - len(alive_idx)
            return PackedMatches(
                bits=bits,
                template_ids=self.db.template_ids,
                extractions=extractions,
                host_always_matches=host_always,
                confirms_per_row=conf,
                wf=wf_full,
            )

        rows = all_rows
        enc = pre if pre is not None else self._encode_for_backend(rows)
        _tag, batch, matcher, uniq, back, n_src, new_ids, keys, known = enc
        if n_src != len(rows):
            raise ValueError(
                f"pre-encoded batch is for {n_src} rows, "
                f"match_packed got {len(rows)}"
            )
        # the device and the content-side host walk run over DISTINCT
        # NEW response contents only (in-batch dedup + cross-batch
        # verdict memo); verdicts broadcast back per member at the end
        nrows = [rows[uniq[s]] for s in new_ids]
        B = len(nrows)
        if batch is not None:
            pt_value, uextractions, deferred, redo_pos, confirms, wf_slots = (
                self._walk_plane(nrows, batch, matcher)
            )
        else:  # every slot served by the verdict memo
            pt_value = np.zeros((0, max(nbytes, 1)), dtype=np.uint8)
            uextractions = {}
            deferred = []
            redo_pos = set()
            confirms = {}
            wf_slots = None
        self.stats.rows += len(rows)
        self.stats.batches += 1
        # memo-served rows = everything not mapped to a walked slot
        # (same row-count semantics as the native path)
        if len(new_ids) < len(uniq):
            self.stats.memo_slots += len(rows) - int(
                np.isin(back, np.asarray(new_ids, dtype=np.int64)).sum()
            )

        t1 = time.perf_counter()
        db = self.db
        # lazy member grouping per unique slot (for per-member fixups
        # and extraction fan-out): one vectorized argsort instead of a
        # per-row Python append loop, slices materialized only for the
        # slots actually touched (extraction hits, row-dependent
        # deferrals) — at fleet steady state that is a small fraction
        member_order = np.argsort(back, kind="stable")
        member_bounds = np.searchsorted(
            back[member_order], np.arange(len(uniq) + 1)
        )
        _member_cache: dict = {}

        def members_of(ub: int) -> list:
            m = _member_cache.get(ub)
            if m is None:
                m = member_order[
                    member_bounds[ub] : member_bounds[ub + 1]
                ].tolist()
                _member_cache[ub] = m
            return m
        rowdep = self._rowdep_t
        # --- assemble the full unique plane: walked NEW slots + memo-
        # served known slots; store fully-resolved new content ---
        U = len(uniq)
        nbits_row = max(nbytes, 1)
        ubits = np.zeros((U, nbits_row), dtype=np.uint8)
        uext_all: dict = {}  # (slot, tid) -> values
        deferred_slots: list = []  # (slot, t_idx)
        ext_by_pos: dict = {}
        for (b, tid), vals in uextractions.items():
            ext_by_pos.setdefault(int(b), []).append((tid, vals))
        def_by_pos: dict = {}
        for b, t_idx in deferred:
            def_by_pos.setdefault(int(b), []).append(t_idx)
        shared_wb: list = []
        for b in range(B):
            s = new_ids[b]
            ubits[s] = pt_value[b]
            for tid, vals in ext_by_pos.get(b, ()):
                uext_all[(s, tid)] = vals
            for t_idx in def_by_pos.get(b, ()):
                deferred_slots.append((s, t_idx))
            if b not in redo_pos:
                # deep-freeze what goes in: bits copied out of the
                # (reused) plane, extraction VALUES tuple-copied —
                # callers receive mutable lists, and a caller's in-place
                # edit must never rewrite the cache
                entry = (
                    pt_value[b].tobytes(),
                    tuple(
                        (tid, tuple(vals))
                        for tid, vals in ext_by_pos.get(b, ())
                    ),
                    tuple(def_by_pos.get(b, ())),
                )
                self._cache_put(self._verdict_memo, keys[s], entry)
                shared_wb.append(
                    (nrows[b], entry[0], (entry[1], entry[2]))
                )
        # shared-tier writeback, dict-memo twin of the native path's
        # (docs/CACHING.md)
        if shared_wb and self._result_cache is not None:
            self._result_cache.writeback_rows(shared_wb)
        for s, entry in known.items():
            mb, ment, mdef = entry
            ubits[s] = np.frombuffer(mb, dtype=np.uint8)
            for tid, vals in ment:
                uext_all[(s, tid)] = list(vals)  # thaw per replay
            for t_idx in mdef:
                deferred_slots.append((s, t_idx))

        # --- broadcast the unique plane to the source rows ---
        bits = ubits[back] if len(rows) else ubits[:0]
        bits = np.ascontiguousarray(bits)
        wf_rows: Optional[dict] = None
        if wf_slots is not None:
            # workflow gate planes broadcast like the verdict plane;
            # memo-served slots and redo slots stay valid=False (the
            # runner resolves their conditions on the host)
            cv, cu, ev, eu = wf_slots
            uwf = {
                "cond_v": np.zeros((U, cv.shape[1]), dtype=np.uint8),
                "cond_u": np.zeros((U, cu.shape[1]), dtype=np.uint8),
                "emit_v": np.zeros((U, ev.shape[1]), dtype=np.uint8),
                "emit_u": np.zeros((U, eu.shape[1]), dtype=np.uint8),
            }
            uvalid = np.zeros((U,), dtype=bool)
            for b in range(B):
                s = new_ids[b]
                uwf["cond_v"][s] = cv[b]
                uwf["cond_u"][s] = cu[b]
                uwf["emit_v"][s] = ev[b]
                uwf["emit_u"][s] = eu[b]
                uvalid[s] = b not in redo_pos
            wf_rows = {k: v[back] for k, v in uwf.items()}
            wf_rows["valid"] = uvalid[back]
        extractions = {}
        for (ub, tid), vals in uext_all.items():
            for i in members_of(ub):
                extractions[(i, tid)] = vals
        conf_full: dict = {
            uniq[new_ids[b]]: n for b, n in confirms.items()
        }

        # --- member fixups: row-dependent templates (takeover family's
        # host gates, duration checks) decided per actual row via the
        # oracle; also their certain hits' extractions, which may read
        # host. Rare by construction — these bits only defer when the
        # content side actually fired ---
        seen_pairs = set()
        for ub, t_idx in deferred_slots:
            if (ub, t_idx) in seen_pairs:
                continue
            seen_pairs.add((ub, t_idx))
            template = db.templates[t_idx]
            mask = 0x80 >> (t_idx & 7)
            byte_i = t_idx >> 3
            for i in members_of(ub):
                res = cpu_ref.match_template(template, rows[i])
                conf_full[i] = conf_full.get(i, 0) + 1
                self.stats.host_confirm_pairs += 1
                if res.matched:
                    bits[i, byte_i] |= mask
                    if res.extractions:
                        extractions[(i, template.id)] = res.extractions
                else:
                    bits[i, byte_i] &= 0xFF ^ mask
        # certain-set row-dependent templates with extractors: verdict
        # is content-determined (broadcast is exact) but extraction
        # values may read the member's host — covers memo-served slots
        # too (their member set is new every batch), hence ubits
        for t_idx in self._rowdep_ext_t:
            byte_i, mask = t_idx >> 3, 0x80 >> (t_idx & 7)
            template = db.templates[t_idx]
            for ub in np.flatnonzero(ubits[:, byte_i] & mask):
                for i in members_of(int(ub)):
                    res = cpu_ref.match_template(template, rows[i])
                    if res.matched and res.extractions:
                        extractions[(i, template.id)] = res.extractions

        host_always_matches = self._host_always_tail(rows, extractions)

        self.stats.host_confirm_seconds += time.perf_counter() - t1
        return PackedMatches(
            bits=bits,
            template_ids=db.template_ids,
            extractions=extractions,
            host_always_matches=host_always_matches,
            confirms_per_row=conf_full,
            wf=wf_rows,
        )

    # ------------------------------------------------------------------
    def _match_packed_native(self, rows, enc, pending=None) -> PackedMatches:
        """Assembly for the C-memo encode path (:meth:`_encode_native`).

        Known rows arrived with their packed verdicts already fanned
        into ``bits`` by the native lookup; only miss uniques walk. The
        result is bit-identical to the Python-memo path — pinned by
        tests/test_match_parity.py's memo/dedup suites, which run on
        whichever path the build provides, and the native-vs-fallback
        equivalence test."""
        _tag, batch, matcher, bits, state, miss_uniq, served, n_src = enc
        if n_src != len(rows):
            raise ValueError(
                f"pre-encoded batch is for {n_src} rows, "
                f"match_packed got {len(rows)}"
            )
        db = self.db
        self.stats.rows += len(rows)
        self.stats.batches += 1
        extractions: dict = {}
        conf_full: dict = {}
        deferred_rows: list = []  # (row_i, t_idx) — decide per row
        wf_rows: Optional[dict] = None
        if batch is not None:
            nrows = [rows[i] for i in miss_uniq]
            B = len(nrows)
            pt_value, uext, deferred, redo_pos, confirms, wf_slots = (
                self._walk_plane(nrows, batch, matcher, pending=pending)
            )
            t1 = time.perf_counter()
            self.stats.memo_slots += int((state == -1).sum())
            # broadcast walked bits to their member rows
            miss_rows = np.flatnonzero(state >= 0)
            bits[miss_rows] = pt_value[state[miss_rows]]
            if wf_slots is not None:
                # workflow gate planes for walked rows; memo-served
                # rows stay valid=False (host-resolved by the runner)
                cv, cu, ev, eu = wf_slots
                R = len(rows)
                sl = state[miss_rows]
                wf_rows = {
                    "cond_v": np.zeros((R, cv.shape[1]), dtype=np.uint8),
                    "cond_u": np.zeros((R, cu.shape[1]), dtype=np.uint8),
                    "emit_v": np.zeros((R, ev.shape[1]), dtype=np.uint8),
                    "emit_u": np.zeros((R, eu.shape[1]), dtype=np.uint8),
                }
                wf_rows["cond_v"][miss_rows] = cv[sl]
                wf_rows["cond_u"][miss_rows] = cu[sl]
                wf_rows["emit_v"][miss_rows] = ev[sl]
                wf_rows["emit_u"][miss_rows] = eu[sl]
                slot_ok = np.ones((B,), dtype=bool)
                for pos in redo_pos:
                    slot_ok[pos] = False
                valid = np.zeros((R,), dtype=bool)
                valid[miss_rows] = slot_ok[sl]
                wf_rows["valid"] = valid
            ext_by_pos: dict = {}
            for (b, tid), vals in uext.items():
                ext_by_pos.setdefault(int(b), []).append((tid, vals))
            def_by_pos: dict = {}
            for b, t_idx in deferred:
                def_by_pos.setdefault(int(b), []).append(t_idx)
            # memo inserts for fully-resolved content (deep-frozen
            # extras — callers receive thawed list copies, so a
            # caller's in-place edit can never rewrite the cache;
            # truncated/overflow positions are never stored). One
            # native call inserts the whole walked plane.
            t_ins = time.perf_counter()
            skip = np.zeros(B, dtype=np.uint8)
            for pos in redo_pos:
                skip[pos] = 1
            extras_list: list = [None] * B
            for pos in ext_by_pos.keys() | def_by_pos.keys():
                ment = tuple(
                    (tid, tuple(vals))
                    for tid, vals in ext_by_pos.get(pos, ())
                )
                mdef = tuple(def_by_pos.get(pos, ()))
                if ment or mdef:
                    extras_list[pos] = (ment, mdef)
            self._vmemo.insert_batch(nrows, pt_value[:B], skip, extras_list)
            # shared-tier writeback (docs/CACHING.md): the same fully-
            # resolved planes the L1 just absorbed batch-write to the
            # fleet tier — truncated/overflow positions stay local-only
            # exactly like the L1 (never memoized anywhere). Runs after
            # finish_packed's walk, off the dispatch path; a fenced or
            # degraded put drops silently (the tier is an accelerator,
            # never a dependency).
            if (
                self._result_cache is not None
                and self._result_cache.writeback
            ):
                self._result_cache.writeback_rows(
                    [
                        (nrows[b], pt_value[b].tobytes(), extras_list[b])
                        for b in range(B)
                        if not skip[b]
                    ]
                )
            ins_dt = time.perf_counter() - t_ins
            self.stats.insert_seconds += ins_dt
            # member fan-out over miss rows. Fresh-content batches
            # (every row a unique miss) skip the argsort grouping —
            # slot s's only member is miss_uniq[s].
            if len(miss_uniq) == len(rows):
                def members_of(pos: int) -> tuple:
                    return (miss_uniq[pos],)
            else:
                order = np.argsort(state, kind="stable")
                sorted_state = state[order]

                def members_of(pos: int) -> list:
                    lo = np.searchsorted(sorted_state, pos)
                    hi = np.searchsorted(sorted_state, pos + 1)
                    return order[lo:hi].tolist()

            for (pos, tid), vals in uext.items():
                for i in members_of(int(pos)):
                    extractions[(i, tid)] = vals
            for pos, tids in def_by_pos.items():
                for i in members_of(pos):
                    for t_idx in tids:
                        deferred_rows.append((i, t_idx))
            conf_full = {
                miss_uniq[pos]: n for pos, n in confirms.items()
            }
        else:
            ins_dt = 0.0
            t1 = time.perf_counter()
            self.stats.memo_slots += int((state == -1).sum())
        # extras served by the memo arrive ALREADY applied by the C
        # lookup: a (row, tid) -> thawed-list dict plus the
        # row-dependent deferral pairs. At steady state (no walked
        # extractions yet) the C-built dict is adopted wholesale.
        extr_known, deferred_known = served
        if extractions:
            extractions.update(extr_known)
        else:
            extractions = extr_known
        deferred_rows.extend(deferred_known)
        # certain-set row-dependent templates with extractors: at this
        # point the bits plane is content-certain (deferred bits are
        # cleared), so a set bit broadcasts exactly — but extraction
        # values may read the row's host → oracle per hit row. Runs
        # BEFORE the deferred fixups so fixup-set bits don't re-run.
        for t_idx in self._rowdep_ext_t:
            byte_i, mask = t_idx >> 3, 0x80 >> (t_idx & 7)
            template = db.templates[t_idx]
            for i in np.flatnonzero(bits[:, byte_i] & mask):
                res = cpu_ref.match_template(template, rows[int(i)])
                if res.matched and res.extractions:
                    extractions[(int(i), template.id)] = res.extractions
        # row-dependent deferrals (takeover family's host gates,
        # duration checks): decided per actual row via the oracle
        for i, t_idx in deferred_rows:
            template = db.templates[t_idx]
            mask = 0x80 >> (t_idx & 7)
            byte_i = t_idx >> 3
            res = cpu_ref.match_template(template, rows[i])
            conf_full[i] = conf_full.get(i, 0) + 1
            self.stats.host_confirm_pairs += 1
            if res.matched:
                bits[i, byte_i] |= mask
                if res.extractions:
                    extractions[(i, template.id)] = res.extractions
            else:
                bits[i, byte_i] &= 0xFF ^ mask
        host_always_matches = self._host_always_tail(
            rows, extractions, dead_state=state
        )
        now = time.perf_counter()
        # the insert window sits inside t1..now but is attributed to
        # insert_seconds — exclude it so the sub-phases sum to the
        # host_confirm total instead of double-counting
        self.stats.fixup_seconds += now - t1 - ins_dt
        self.stats.host_confirm_seconds += now - t1
        return PackedMatches(
            bits=bits,
            template_ids=db.template_ids,
            extractions=extractions,
            host_always_matches=host_always_matches,
            confirms_per_row=conf_full,
            wf=wf_rows,
        )


    def _host_always_tail(
        self, rows, extractions: dict, dead_state=None
    ) -> list:
        """Host-always tail shared by both assembly paths: templates
        the compiler couldn't lower run exactly, per actual row (they
        may read host). Mutates ``extractions`` in place; returns the
        (row, template_id) hit list. ``dead_state`` is the native
        path's state vector — rows marked dead (-2) match nothing by
        contract and are skipped (the fallback path filters dead rows
        before assembly, so it passes None).
        """
        host_always_matches: list = []
        db = self.db
        if self.host_always_mode == "full" and db.host_always:
            for i, row in enumerate(rows):
                if dead_state is not None and dead_state[i] == -2:
                    continue
                for template in db.host_always:
                    res = cpu_ref.match_template(template, row)
                    self.stats.host_always_pairs += 1
                    if res.matched:
                        host_always_matches.append((i, template.id))
                        if res.extractions:
                            extractions[(i, template.id)] = res.extractions
        return host_always_matches

    # ------------------------------------------------------------------
    # Shared result tier (docs/CACHING.md): L1 → shared → device
    # ------------------------------------------------------------------
    # once: client.bind_corpus (attach binds the tier epoch exactly once)
    def attach_result_cache(self, client) -> None:
        """Attach a fleet-wide content-addressed result tier
        (:class:`swarm_tpu.cache.ResultCacheClient`). The client is
        bound to this engine's corpus digest, so entries can only be
        exchanged between engines compiled from identical templates
        (and identical lowering code — the epoch covers both). ``None``
        detaches."""
        if client is not None:
            from swarm_tpu.cache.tier import corpus_digest

            client.bind_corpus(corpus_digest(self.templates))
        self._result_cache = client

    def attach_aot(self, client) -> None:
        """Attach an AOT executable-cache client
        (:class:`swarm_tpu.aot.AotClient`) to whichever device backend
        serves this engine — the single-device :class:`DeviceDB` now,
        and the sharded matcher when backend resolution builds it
        (docs/AOT.md). ``None`` detaches."""
        self._aot_client = client
        self.device.attach_aot(client)
        if self.sharded is not None:
            self.sharded.attach_aot(client)

    def aot_prewarm(self) -> int:
        """Bring-up fetch: resolve the backend, then pool every
        published executable for this process's program group so the
        first dispatch of each published shape class loads instead of
        compiling (worker/runtime.py calls this right after engine
        construction). Returns the pooled executable count."""
        if self._aot_client is None:
            return 0
        if not self._backend_ready:
            self._resolve_backend()
        backend = self.sharded if self.sharded is not None else self.device
        return backend.aot_prewarm()

    # once: _result_cache.bind_corpus (ONE shared-cache epoch move per refresh, docs/CACHING.md)
    def refresh_corpus(self, templates_new, db_new=None) -> dict:
        """Zero-downtime corpus refresh against a LIVE engine
        (docs/AOT.md): delta-compile the new template list against the
        current CompiledDB (unchanged word tables adopted by identity,
        only the touched stacked-table rows rebuilt), upload only the
        changed layout leaves, re-derive the db-indexed lookup tables,
        drop every content-keyed memo (matcher/op indices renumber and
        plane widths can change — stale entries would be wrong, not
        slow), and move the shared result tier to the new corpus
        epoch with ONE ``bind_corpus`` call. When the trace signature
        survives the refresh, the live executables keep serving and
        the next batch pays only the delta uploads — no layout
        rebuild, no recompile.

        Caller contract: quiesce first — no batch may be in flight
        (dispatched-not-collected, or inside the scheduler's
        in-flight window) across this call.

        ``db_new``: optional precompiled CompiledDB for
        ``templates_new`` (e.g. from ``fingerprints/dbcache``); it is
        delta-layouted against the current db either way. Returns the
        combined delta stats."""
        from swarm_tpu.fingerprints.compile import (
            build_device_layout_delta,
            compile_corpus_delta,
        )

        stats: dict = {}
        if db_new is None:
            db_new, stats = compile_corpus_delta(
                list(templates_new), self.db
            )
        else:
            build_device_layout_delta(db_new, self.db, stats)
        self.templates = list(templates_new)
        self.db = db_new
        self._bind_db()
        stats.update(self.device.update_layout(db_new))
        if self.sharded is not None:
            stats["sharded"] = self.sharded.refresh(db_new)
        # stale-corpus state: every content-keyed memo maps content →
        # verdicts/indices of the OLD corpus — invalid, not just cold
        self._verdict_memo.clear()
        self._vmemo = None  # recreated lazily at the new plane width
        self._ext_cache.clear()
        self._confirm_cache.clear()
        self._shared_seen.clear()
        # shared result tier: ONE namespace move — the epoch's digest
        # half covers the corpus content + lowering code
        from swarm_tpu.cache.tier import corpus_digest

        digest = corpus_digest(self.templates)
        if self._result_cache is not None:
            self._result_cache.bind_corpus(digest)
        # corpus-delta fan-out: any in-process monitor service turns
        # this into a journaled due-now touch so standing specs fire
        # one immediate out-of-cadence diff epoch against the new
        # corpus (docs/MONITORING.md §Out-of-cadence re-evaluation)
        from swarm_tpu.monitor import notify as monitor_notify

        monitor_notify.notify_corpus_delta(digest)
        return stats

    def _ensure_vmemo(self, nbits: int):
        """The C resident verdict cache, created on first need (both
        the encode path and the scheduler-prefetch shared serve can be
        the first toucher)."""
        if self._vmemo is None:
            from swarm_tpu.native.scanio import VerdictMemo

            self._vmemo = VerdictMemo(self._EXT_CACHE_MAX, nbits)
        return self._vmemo

    def _serve_shared(self, cand: list, into_native: bool) -> int:
        """Serve shared-tier verdict entries for L1-missed rows: each
        hit is inserted into the L1 (native memo or dict memo), so the
        caller's re-lookup serves it exactly like locally-computed
        known content — verdicts can't differ between a shared hit and
        a local walk because the entry IS a walked result for the same
        content under the same corpus epoch. Entries whose plane width
        doesn't match this corpus are dropped (foreign layout — treat
        as a miss, never as data)."""
        client = self._result_cache
        if client is None or not cand:
            return 0
        entries = client.lookup_rows(cand)
        if not entries:
            return 0
        nbits = max((self.db.num_templates + 7) >> 3, 1)
        n = 0
        for pos, (mb, ment, mdef) in entries.items():
            if len(mb) != nbits:
                continue
            extras = (ment, mdef) if (ment or mdef) else None
            if into_native:
                self._ensure_vmemo(nbits).insert(
                    cand[pos],
                    np.frombuffer(mb, dtype=np.uint8).copy(),
                    extras,
                )
            else:
                self._cache_put(
                    self._verdict_memo, _content_key(cand[pos]),
                    (mb, ment, mdef),
                )
            n += 1
        return n

    def prefetch_shared_memo(self, rows: Sequence) -> int:
        """Pipeline the shared-tier lookup into the scheduler's
        prefetch stage (docs/CACHING.md): rows the L1 doesn't know are
        batch-looked-up in the shared tier and the hits inserted into
        the L1 BEFORE plan-time classification, so a fleet-known row
        rides the memo lane (no bucket, no device batch slot) and a
        shared miss costs nothing on the dispatch path — the remote
        round trip overlapped the in-flight device batches. Returns
        the number of contents served. No-op without an attached
        tier."""
        if self._result_cache is None or not rows:
            return 0
        rows = list(rows)
        known = self.memo_known_mask(rows)
        cand = [
            r
            for i, r in enumerate(rows)
            if not known[i] and getattr(r, "alive", True)
        ]
        if not cand:
            return 0
        # remember what this stage consulted (hits AND misses): the
        # encode-time consult skips these rows instead of re-hashing
        for r in cand:
            self._cache_put(self._shared_seen, id(r), None)
        return self._serve_shared(cand, into_native=self._use_native_memo())

    # ------------------------------------------------------------------
    def memo_contains(self, row: Response) -> bool:
        """Whether the cross-batch verdict memo holds this row's
        content (works for both the native and the dict memo form)."""
        if self._vmemo is not None:
            return self._vmemo.contains(row)
        return _content_key(row) in self._verdict_memo

    def memo_known_mask(self, rows: list) -> np.ndarray:
        """uint8 residency mask over ``rows`` (no LRU side effects) —
        ONE native pass when the C memo drives the packed path, else
        the dict probe. The scheduler's plan-time memo split runs at
        feed rates, where a per-row ctypes round trip dominated the
        probe itself."""
        if self._vmemo is not None:
            return self._vmemo.contains_batch(rows)
        memo = self._verdict_memo
        # alive gate mirrors the native pass: a dead row's (empty)
        # content may genuinely be resident from an alive row, but a
        # dead row must resolve to zero verdicts, never a memo entry
        return np.fromiter(
            (r.alive and _content_key(r) in memo for r in rows),
            dtype=np.uint8,
            count=len(rows),
        )

    def clear_content_memos(self) -> None:
        """Drop every cross-batch content memo (bench fresh-content
        adversarial runs; production never needs this)."""
        self._ext_cache.clear()
        self._confirm_cache.clear()
        self._verdict_memo.clear()
        if self._vmemo is not None:
            self._vmemo.clear()
