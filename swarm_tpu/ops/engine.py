"""MatchEngine: the user-facing exact fingerprint engine.

Composes the pieces: template corpus → CompiledDB (once), responses →
padded batches → device kernel → sparse host confirmation with the
exact CPU oracle. The result is bit-identical to running the oracle on
every (row, template) pair — the device does ~all the work, the host
touches only the specific uncertain *matchers* that actually fired
(plus the small, reported host-always template tail, empty for the
reference corpus).

Throughput contract: the packed path (:meth:`MatchEngine.match_packed`)
never does per-row Python work for certain rows — verdicts stay bitset
matrices end to end, uncertainty is resolved pair-sparsely, and the
three-valued (Kleene) refinement in the kernel (ops/match.py
``eval_verdicts``) keeps the uncertain set small. A key consequence of
that refinement drives the sparse resolver here: an op that is still
*undecided* after its certain matchers are known has a neutral certain
part (all-false under OR, all-true under AND), so its exact value is
the combination of its *uncertain* matchers alone — the host never
needs the certain siblings' values.

This replaces the reference worker's subprocess shell-outs to
nmap/-sV//nuclei (``worker/worker.py:79-84``) as the compute engine.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence

import numpy as np

from swarm_tpu.fingerprints.compile import CompiledDB, compile_corpus
from swarm_tpu.fingerprints.model import Response, Template
from swarm_tpu.ops import cpu_ref
from swarm_tpu.ops.encoding import encode_batch, round_up
from swarm_tpu.ops.match import DeviceDB


@dataclasses.dataclass
class RowMatches:
    """Exact match set for one response row."""

    template_ids: list
    extractions: dict  # template_id -> list[str]
    confirmed_on_host: int = 0  # uncertain pairs the host re-checked


@dataclasses.dataclass
class PackedMatches:
    """Exact verdicts for one batch in wire form.

    ``bits[b, t >> 3] & (0x80 >> (t & 7))`` is template ``t``'s verdict
    for row ``b`` (np.packbits MSB-first convention); ``template_ids``
    maps the column index to ids. ``extractions`` is sparse:
    ``(row, template_id) -> list[str]``. ``host_always_matches`` lists
    (row, template_id) hits from the host-only tail, if any.
    """

    bits: np.ndarray  # uint8 [B, ceil(NT/8)]
    template_ids: list
    extractions: dict
    host_always_matches: list
    confirms_per_row: dict  # row -> host confirmations spent on it


@dataclasses.dataclass
class EngineStats:
    rows: int = 0
    batches: int = 0
    device_seconds: float = 0.0
    host_confirm_seconds: float = 0.0
    host_confirm_pairs: int = 0
    host_always_pairs: int = 0
    overflow_rows: int = 0


def _bit(packed: np.ndarray, b: int, i: int) -> bool:
    return bool((packed[b, i >> 3] >> (7 - (i & 7))) & 1)


def _iter_set_bits(row_bytes: np.ndarray, limit: int) -> np.ndarray:
    """Indices of set bits in one packed row (MSB-first), < limit."""
    if limit <= 0:
        return np.empty((0,), dtype=np.int64)
    return np.flatnonzero(np.unpackbits(row_bytes, count=limit))


class MatchEngine:
    def __init__(
        self,
        templates: Sequence[Template],
        max_body: int = 4096,
        max_header: int = 1024,
        batch_rows: int = 1024,
        candidate_k: int = 128,
        host_always: str = "full",  # "full" (exact) | "skip" (device-only)
        mesh="auto",  # "auto" | None | jax.sharding.Mesh
        db: Optional[CompiledDB] = None,  # precompiled (fingerprints/dbcache)
    ):
        self.templates = list(templates)
        self.db = db if db is not None else compile_corpus(self.templates)
        self.device = DeviceDB(self.db, candidate_k=candidate_k)
        self.max_body = max_body
        self.max_header = max_header
        self.batch_rows = batch_rows
        self.host_always_mode = host_always
        self.stats = EngineStats()
        # Multi-chip: shard each batch dp×tp×sp across the local mesh
        # (the production analog of the reference's chunk-per-worker
        # scale-out, server/server.py:465-515 — here one worker drives a
        # whole slice). "auto" shards whenever >1 device is visible;
        # sharding never changes results (tests/test_sharding.py).
        # Resolution is lazy: construction must stay JAX-free (the
        # oracle-only and pre-fork users never touch a device).
        self._mesh_arg = mesh
        self._backend_ready = mesh is None
        self.sharded = None
        self.mesh = None
        self._candidate_k = candidate_k
        db = self.db
        # device matcher/op id → source objects for sparse confirmation
        self._m_obj = [
            db.templates[t].operations[o].matchers[m]
            for t, o, m in db.m_src
        ] if db.templates else []
        self._op_obj = [
            db.templates[t].operations[o] for t, o in db.op_src
        ] if db.templates else []
        # templates with extractors need a host pass on *hits* even when
        # the verdict itself was device-certain, so extraction output
        # stays bit-identical to the oracle
        self._has_extractors = [
            any(op.extractors for op in t.operations) for t in db.templates
        ]
        self._ext_t_idx = [
            i for i, has in enumerate(self._has_extractors) if has
        ]
        # vectorized per-op matcher-id tables: resolving a giant op
        # (fingerprinthub: 2,897 matchers) must not walk bits in Python
        self._op_m_arr = [
            np.asarray(ids, dtype=np.int64) for ids in db.op_matchers
        ]

    # ------------------------------------------------------------------
    def match(self, responses: Sequence[Response]) -> list[RowMatches]:
        """Per-row exact match sets (compat/active-scanner form).

        Built from the packed path; per-row object assembly makes this
        the slower surface — bulk pipelines use :meth:`match_packed`.
        """
        # dead rows match nothing by contract; filtering them BEFORE
        # chunking keeps the pipelined pre-encode effective (a chunk
        # with any dead row would force match_packed to discard the
        # pre and re-encode the live subset serially)
        alive = [r for r in responses if r.alive]
        if len(alive) < len(responses):
            live_out = iter(self.match(alive))
            return [
                next(live_out)
                if r.alive
                else RowMatches(template_ids=[], extractions={})
                for r in responses
            ]
        out: list[RowMatches] = []
        NT = self.db.num_templates
        chunks = [
            responses[s : s + self.batch_rows]
            for s in range(0, len(responses), self.batch_rows)
        ]
        for rows, pre in self._iter_encoded(chunks):
            packed = self.match_packed(rows, pre=pre)
            per_row_conf = packed.confirms_per_row
            for b in range(len(rows)):
                tids = [
                    self.db.template_ids[t]
                    for t in _iter_set_bits(packed.bits[b], NT)
                ]
                extr = {
                    tid: ext
                    for (rb, tid), ext in packed.extractions.items()
                    if rb == b
                }
                for rb, tid in packed.host_always_matches:
                    if rb == b:
                        tids.append(tid)
                out.append(
                    RowMatches(
                        template_ids=tids,
                        extractions=extr,
                        confirmed_on_host=per_row_conf.get(b, 0),
                    )
                )
        return out

    # ------------------------------------------------------------------
    def _iter_encoded(self, chunks):
        """Yield (rows, pre_encoded) with the NEXT chunk's host encode
        overlapping the current chunk's device dispatch + confirmation
        (the encode is the feed ceiling at device rates; the device
        wait releases the GIL, so one helper thread recovers it)."""
        if len(chunks) <= 1:
            for c in chunks:
                yield c, None
            return
        from concurrent.futures import ThreadPoolExecutor

        if not self._backend_ready:
            self._resolve_backend()  # before threads touch the backend
        with ThreadPoolExecutor(max_workers=1) as pool:
            fut = pool.submit(self.encode_packed, chunks[0], True)
            for i, c in enumerate(chunks):
                pre = fut.result()
                if i + 1 < len(chunks):
                    fut = pool.submit(self.encode_packed, chunks[i + 1], True)
                yield c, pre

    # ------------------------------------------------------------------
    def _resolve_backend(self) -> None:
        """First-match mesh resolution (kept out of __init__ so engine
        construction never initializes the JAX backend)."""
        mesh = self._mesh_arg
        if mesh == "auto":
            import jax

            mesh = None
            if len(jax.devices()) > 1:
                from swarm_tpu.parallel.mesh import make_mesh

                mesh = make_mesh()
        if mesh is not None:
            from swarm_tpu.parallel.sharded import ShardedMatcher

            self.sharded = ShardedMatcher(self.db, mesh, candidate_k=self._candidate_k)
            self.mesh = mesh
        self._backend_ready = True

    # ------------------------------------------------------------------
    def encode_packed(self, rows: Sequence[Response], reuse_buffers: bool = False):
        """Public pre-encode for pipelined feeding: callers may encode
        batch i+1 on another thread while the device matches batch i
        (the encode is host memcpy work; the device dispatch releases
        the GIL) and pass the result to :meth:`match_packed` via
        ``pre``. Thread-safe after the first call resolved the backend.

        ``reuse_buffers=True`` draws the stream matrices from the
        recycled pool (faster, no zero-fill) — but a pooled batch's
        arrays are OVERWRITTEN a few same-shape encodes later
        (encoding._RotatingPool), so only enable it when each encoded
        batch is matched before more than a couple further encodes
        (the 1-deep pipelined pattern). The default allocates fresh
        arrays and is safe to hold indefinitely."""
        return self._encode_for_backend(rows, reuse_buffers=reuse_buffers)

    def _encode_for_backend(
        self, rows: Sequence[Response], reuse_buffers: bool = True
    ):
        """Encode rows for whichever device backend is active.

        The sharded backend needs the batch row count divisible by the
        'data' axis and every stream width divisible by 'seq' with each
        per-rank slice at least one halo wide (parallel/sharded.py
        raises otherwise); padding is zeros, which the length masks
        already ignore, and padded rows are sliced off the verdicts.
        """
        if not self._backend_ready:
            self._resolve_backend()
        if self.sharded is None:
            return (
                encode_batch(
                    rows,
                    max_body=self.max_body,
                    max_header=self.max_header,
                    # the "all" stream synthesizes on device (half
                    # the encode bytes and H2D traffic stay on the
                    # host)
                    reuse_buffers=reuse_buffers,
                    build_all=False,
                ),
                self.device,
            )
        data_ranks = self.sharded.ranks.get("data", 1)
        seq_ranks = self.sharded.ranks.get("seq", 1)
        batch = encode_batch(
            rows,
            max_body=self.max_body,
            max_header=self.max_header,
            pad_rows_to=round_up(len(rows), data_ranks),
            reuse_buffers=reuse_buffers,
        )
        if seq_ranks > 1:
            from swarm_tpu.parallel.sharded import pad_streams_for_seq

            pad_streams_for_seq(batch.streams, seq_ranks, self.sharded.halo)
        return batch, self.sharded

    # ------------------------------------------------------------------
    def match_packed(
        self, all_rows: Sequence[Response], pre=None
    ) -> PackedMatches:
        """Exact verdict bitsets for up to ``batch_rows`` responses.

        The production wire format: one device dispatch, vectorized
        verdict assembly, host work proportional to the number of
        *uncertain fired matchers* — not to rows × templates.

        ``pre`` is an optional :meth:`encode_packed` result for the SAME
        rows (pipelined feeding); ignored when the batch contains dead
        rows (the live-subset recursion re-encodes).
        """
        NT = self.db.num_templates
        nbytes = (NT + 7) >> 3
        # dead rows (no response observed) match nothing by contract —
        # drop them before encoding so the device never pays for them
        alive_idx = [i for i, r in enumerate(all_rows) if r.alive]
        if len(alive_idx) < len(all_rows):
            bits = np.zeros((len(all_rows), max(nbytes, 1)), dtype=np.uint8)
            extractions: dict = {}
            host_always: list = []
            conf: dict = {}
            if alive_idx:
                live = self.match_packed([all_rows[i] for i in alive_idx])
                back = {j: i for j, i in enumerate(alive_idx)}
                for j, i in enumerate(alive_idx):
                    bits[i] = live.bits[j]
                extractions = {
                    (back[rb], tid): ext
                    for (rb, tid), ext in live.extractions.items()
                }
                host_always = [
                    (back[rb], tid) for rb, tid in live.host_always_matches
                ]
                conf = {
                    back[rb]: n for rb, n in live.confirms_per_row.items()
                }
            self.stats.rows += len(all_rows) - len(alive_idx)
            return PackedMatches(
                bits=bits,
                template_ids=self.db.template_ids,
                extractions=extractions,
                host_always_matches=host_always,
                confirms_per_row=conf,
            )

        rows = all_rows
        if pre is not None and len(pre[0].rows) != len(rows):
            raise ValueError(
                f"pre-encoded batch is for {len(pre[0].rows)} rows, "
                f"match_packed got {len(rows)}"
            )
        batch, matcher = pre if pre is not None else self._encode_for_backend(rows)
        t0 = time.perf_counter()
        pt_value, pt_unc, pop_value, pop_unc, pm_unc, overflow = (
            matcher.match(batch.streams, batch.lengths, batch.status, full=True)
        )
        # slice off mesh row padding before the host walk
        B = len(rows)
        pt_value = np.array(np.asarray(pt_value)[:B])  # writable copy
        pt_unc = np.asarray(pt_unc)[:B]
        pop_value = np.asarray(pop_value)[:B]
        pop_unc = np.asarray(pop_unc)[:B]
        pm_unc = np.asarray(pm_unc)[:B]
        overflow = np.asarray(overflow)[:B]
        self.stats.device_seconds += time.perf_counter() - t0
        self.stats.rows += B
        self.stats.batches += 1

        # rows needing whole-row reconfirmation (candidate overflow or
        # stream truncation made word bits unsound for the row)
        row_redo = overflow | batch.truncated[:B]
        self.stats.overflow_rows += int(row_redo.sum())

        t1 = time.perf_counter()
        confirms: dict = {}
        db = self.db

        op_cache: dict = {}  # (b, op_id) -> exact bool
        # content-keyed matcher memo: scan batches repeat headers and
        # default pages heavily, and a matcher's verdict depends only on
        # its part bytes (bytes hashing is cached by CPython, so the
        # dict lookup is cheap after the first touch per row)
        part_cache: dict = {}

        def confirm_matcher(m_id: int, row: Response) -> bool:
            matcher = self._m_obj[m_id]
            if matcher.type not in ("word", "regex", "binary", "size"):
                # dsl/status/kval read beyond matcher.part — not cacheable
                mv = cpu_ref.match_matcher(matcher, row)
                return bool(mv) if mv is not None else False
            key = (m_id, row.part(matcher.part))
            v = part_cache.get(key)
            if v is None:
                mv = cpu_ref.match_matcher(matcher, row)
                v = bool(mv) if mv is not None else False
                part_cache[key] = v
            return v

        def resolve_op(b: int, op_id: int, row: Response) -> bool:
            key = (b, op_id)
            v = op_cache.get(key)
            if v is not None:
                return v
            if not _bit(pop_unc, b, op_id):
                v = _bit(pop_value, b, op_id)
            elif db.op_prefilter[op_id]:
                # superset-lowered op: per-matcher bits are weakened, so
                # fired rows re-run the whole op on the oracle
                v = cpu_ref.match_operation(self._op_obj[op_id], row)[0]
                confirms[b] = confirms.get(b, 0) + 1
                self.stats.host_confirm_pairs += 1
            else:
                # undecided ⇒ certain matchers are neutral; combine the
                # uncertain ones' exact values under the op condition
                ids = self._op_m_arr[op_id]
                bits = (pm_unc[b, ids >> 3] >> (7 - (ids & 7))) & 1
                vals = [
                    confirm_matcher(int(m_id), row)
                    for m_id in ids[bits.astype(bool)]
                ]
                confirms[b] = confirms.get(b, 0) + len(vals)
                self.stats.host_confirm_pairs += len(vals)
                v = all(vals) if db.op_cond_and[op_id] else any(vals)
            op_cache[key] = v
            return v

        # --- full-row redo (rare): the oracle end to end, extractions
        # included (the extraction pass below skips these rows) ---
        redo_rows = np.flatnonzero(row_redo)
        redo_extractions: dict = {}
        for b in redo_rows:
            row = rows[b]
            rowbits = np.zeros((pt_value.shape[1],), dtype=np.uint8)
            for t_idx, template in enumerate(db.templates):
                res = cpu_ref.match_template(template, row)
                confirms[b] = confirms.get(b, 0) + 1
                self.stats.host_confirm_pairs += 1
                if res.matched:
                    rowbits[t_idx >> 3] |= 0x80 >> (t_idx & 7)
                    if res.extractions:
                        redo_extractions[(int(b), template.id)] = (
                            res.extractions
                        )
            pt_value[b] = rowbits

        # --- sparse uncertainty resolution ---
        if not row_redo.all() and pt_unc.any():
            skip = set(redo_rows.tolist())
            for b, byte_i in np.argwhere(pt_unc):
                if b in skip:
                    continue
                v = int(pt_unc[b, byte_i])
                row = rows[b]
                base = int(byte_i) * 8
                for k in range(8):
                    if not (v & (0x80 >> k)):
                        continue
                    t_idx = base + k
                    if t_idx >= NT:
                        continue
                    # undecided ⇒ no certain-true op; OR over the
                    # uncertain ops' exact values decides the template
                    hit = False
                    for op_id in db.t_ops[t_idx]:
                        if _bit(pop_unc, b, op_id) and resolve_op(
                            b, op_id, row
                        ):
                            hit = True
                            break
                    mask = 0x80 >> (t_idx & 7)
                    if hit:
                        pt_value[b, byte_i] |= mask
                    else:
                        pt_value[b, byte_i] &= 0xFF ^ mask

        # --- extraction pass: only extractor templates, only hit rows ---
        extractions: dict = dict(redo_extractions)
        redo_set = set(redo_rows.tolist())
        for t_idx in self._ext_t_idx:
            col = pt_value[:, t_idx >> 3] & (0x80 >> (t_idx & 7))
            for b in np.flatnonzero(col):
                if int(b) in redo_set:
                    continue  # oracle already extracted above
                row = rows[b]
                parts: list = []
                for op_id in db.t_ops[t_idx]:
                    if resolve_op(b, op_id, row):
                        parts.extend(
                            cpu_ref._extract(self._op_obj[op_id], row)
                        )
                if parts:
                    extractions[(int(b), db.template_ids[t_idx])] = parts

        # --- host-always tail: templates the compiler couldn't lower ---
        host_always_matches: list = []
        if self.host_always_mode == "full" and db.host_always:
            for b, row in enumerate(rows):
                for template in db.host_always:
                    res = cpu_ref.match_template(template, row)
                    self.stats.host_always_pairs += 1
                    if res.matched:
                        host_always_matches.append((b, template.id))
                        if res.extractions:
                            extractions[(b, template.id)] = res.extractions

        self.stats.host_confirm_seconds += time.perf_counter() - t1
        return PackedMatches(
            bits=pt_value,
            template_ids=db.template_ids,
            extractions=extractions,
            host_always_matches=host_always_matches,
            confirms_per_row=confirms,
        )
