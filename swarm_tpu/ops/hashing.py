"""Rolling q-gram hashes shared by the compiler (numpy) and kernels (jnp).

The device never runs a general string-search: fixed-length q-grams of
each pattern are hashed at compile time into sorted tables + a Bloom
bitmap, and at match time the same hash is computed for every window
position of the response streams with q shifted multiply-adds (pure
vector ops, no gathers). Window hits are then verified exactly.

Both sides MUST compute identical values, so the polynomial and bases
live here: H(b, i) = Σ_{j<q} b[i+j]·r^j (mod 2^32), two independent
bases per gram size (h1 indexes the table, h2 kills collisions before
the exact byte verify).
"""

from __future__ import annotations

import numpy as np

# Odd multipliers (invertible mod 2^32), chosen independently per role.
BASE1 = np.uint32(0x01000193)  # FNV-ish
BASE2 = np.uint32(0x85EBCA77)
GRAM_LONG = 8  # words >= 8 bytes hash an 8-gram
GRAM_SHORT = 4  # words 4..7 bytes hash a 4-gram
TINY_MAX = GRAM_SHORT - 1  # words 1..3 bytes take the dense-compare path

BLOOM_BITS = 1 << 18  # 32 KiB bitmap per table: ~0.3% window FP at 7.5k words
BLOOM_WORDS = BLOOM_BITS // 32


def _powers(base: np.uint32, q: int) -> np.ndarray:
    out = np.ones(q, dtype=np.uint64)
    for j in range(1, q):
        out[j] = (out[j - 1] * np.uint64(base)) & np.uint64(0xFFFFFFFF)
    return out


def gram_hash_np(data: bytes | np.ndarray, q: int) -> tuple[int, int]:
    """Hash the first q bytes of ``data`` (compile-time side)."""
    arr = np.frombuffer(bytes(data[:q]), dtype=np.uint8).astype(np.uint64)
    assert arr.shape[0] == q, "gram shorter than q"
    p1, p2 = _powers(BASE1, q), _powers(BASE2, q)
    h1 = int((arr * p1).sum() & np.uint64(0xFFFFFFFF))
    h2 = int((arr * p2).sum() & np.uint64(0xFFFFFFFF))
    return h1, h2


def window_hashes_jnp(stream, q: int):
    """[B, W] uint8 → ([B, W] uint32 h1, [B, W] uint32 h2).

    Position i holds the hash of bytes[i:i+q] (windows running past W
    hash into zero padding; they can only ever produce candidates that
    the exact verify rejects).
    """
    import jax.numpy as jnp

    b = stream.astype(jnp.uint32)
    B, W = b.shape
    padded = jnp.pad(b, ((0, 0), (0, q)))
    p1 = _powers(BASE1, q)
    p2 = _powers(BASE2, q)
    h1 = jnp.zeros((B, W), dtype=jnp.uint32)
    h2 = jnp.zeros((B, W), dtype=jnp.uint32)
    for j in range(q):  # unrolled: q static shifted multiply-adds
        window = padded[:, j : j + W]
        h1 = h1 + window * jnp.uint32(int(p1[j]))
        h2 = h2 + window * jnp.uint32(int(p2[j]))
    return h1, h2


def bloom_indices_np(h1: np.ndarray, h2: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    mask = BLOOM_BITS - 1
    return (h1 & mask).astype(np.int64), (h2 & mask).astype(np.int64)


def build_bloom_np(h1s: np.ndarray, h2s: np.ndarray) -> np.ndarray:
    """Pack table-side bloom bitmap: uint32 [BLOOM_WORDS]."""
    bitmap = np.zeros(BLOOM_WORDS, dtype=np.uint32)
    i1, i2 = bloom_indices_np(np.asarray(h1s, np.uint32), np.asarray(h2s, np.uint32))
    for idx in np.concatenate([i1, i2]):
        bitmap[idx >> 5] |= np.uint32(1) << np.uint32(idx & 31)
    return bitmap


def bloom_probe_jnp(bitmap, h1, h2):
    """Window-side probe: both bits must be set."""
    import jax.numpy as jnp

    mask = jnp.uint32(BLOOM_BITS - 1)
    i1 = (h1 & mask).astype(jnp.int32)
    i2 = (h2 & mask).astype(jnp.int32)
    w1 = bitmap[i1 >> 5]
    w2 = bitmap[i2 >> 5]
    bit1 = (w1 >> (i1 & 31).astype(jnp.uint32)) & 1
    bit2 = (w2 >> (i2 & 31).astype(jnp.uint32)) & 1
    return (bit1 & bit2) == 1
