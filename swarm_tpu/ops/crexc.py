"""crex compiler: Python ``re`` pattern -> native VM program.

Lowers a conservative sre-parse-tree subset to the flat instruction
format ``native/crex.cpp`` executes: byte classes, ordered alternation
(preference-first SPLIT), greedy/lazy repeats (single-class repeats as
counted REP instructions, general bounded repeats unrolled, unbounded
general repeats as SPLIT loops), capturing groups (SAVE slots), and
end/boundary anchors. Anything outside the subset — backreferences,
lookarounds, (?a) semantics, empty-matchable loop bodies, oversized
programs — returns None and the caller stays on Python ``re``.

Exactness: masks are built by the same machinery the device lowering
trusts (``regexlin._class_mask`` — per-byte membership matching re's
latin-1 semantics), and the VM's backtracking order (leftmost start,
preference-ordered alternatives, longest-first greedy) is Python re's
own strategy, so results are byte-identical for the supported subset.
Equivalence is fuzz-pinned over the corpus regex population by
tests/test_crex.py and tests/test_fastre.py.

Replaces compute the reference runs through nuclei's Go regexp
(/root/reference/worker/modules/nuclei.json); the hot shapes are the
corpus extraction regexes, e.g. templates/miscellaneous/
robots-txt-endpoint.yaml's path extractor.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import numpy as np

from swarm_tpu.fingerprints.regexlin import (
    _class_mask,
    _case_fold,
    _category_mask,
    _Unsupported,
    parse_quiet,
)

# instruction opcodes — keep in lockstep with native/crex.cpp
OP_CHAR, OP_CLASS, OP_SPLIT, OP_JMP, OP_SAVE, OP_MATCH = 0, 1, 2, 3, 4, 5
OP_REPG, OP_REPL, OP_AT, OP_LOOP = 6, 7, 8, 9

#: ABI version — bump on ANY change to the opcode set, instruction
#: encoding, or driver return codes, in lockstep with
#: CREX_ABI_VERSION in native/crex.cpp. native/crex.py verifies the
#: loaded .so reports this value and refuses a stale build.
CREX_ABI = 4

_INT32_MAX = 2**31 - 1

#: Codepoints > 0xFF that Python re's IGNORECASE folds INTO latin-1
#: (so a latin-1 byte can match them): chars whose single-char
#: ``str.lower`` lands < 0x100 (K→k, Å→å, ẞ→ß, Ÿ→ÿ) plus the
#: ``re._casefix._EXTRA_CASES`` pairs that cross the byte boundary
#: (ı↔i, ſ↔s, μ↔µ). Patterns touching these under (?i) stay on exact
#: Python re; every OTHER >0xFF char can never match latin-1 text and
#: lowers to an impossible class. tests/test_crex.py re-derives this
#: set from the running interpreter (unicode-data drift guard).
CI_LATIN1_FOLDERS = frozenset(
    {0x131, 0x178, 0x17F, 0x1E9E, 0x212A, 0x212B, 0x3BC}
)
AT_BOS, AT_EOS, AT_EOD, AT_WB, AT_NWB, AT_BOL, AT_EOL = 0, 1, 2, 3, 4, 5, 6

MAX_PROG = 2048     # instructions (the corpus's largest lowerable
                    # pattern, technologies' el-table alternation,
                    # needs 1,233; a program is 16 B/instr of compile-
                    # time memory and size does not slow the VM's
                    # per-attempt execution)
MAX_GROUP = 31      # save slots 2..63 (group 0 handled by the driver)
MAX_SLOTS = 64      # total save slots (group pairs + loop marks)
_MAXREPEAT = 2**32 - 1  # sre MAXREPEAT compares equal to this

_DOT = np.ones(256, dtype=bool)
_DOT[ord("\n")] = False
_DOTALL = np.ones(256, dtype=bool)


@dataclasses.dataclass
class CrexProgram:
    prog: np.ndarray       # int32 [n, 4] flattened C-order
    masks: np.ndarray      # uint8 [n_masks, 32] bitsets
    n_saves: int           # save slots used (2 * (max group + 1))
    group_exists: dict     # gid -> True for groups the pattern defines


def _guard_ci_fold(arg: int, ci: bool, what: str) -> None:
    """Shared rejection for (?i) literals that fold INTO latin-1
    (kelvin K matches k, long-s matches s) — only Python re gets
    those right. One guard for all four literal sites (compile_seq
    and _single_class, LITERAL and NOT_LITERAL)."""
    if ci and arg in CI_LATIN1_FOLDERS:
        raise _Unsupported(f"latin-1-folding {what} under (?i)")


class _Compiler:
    def __init__(self, counted_reps: bool = True):
        self.instrs: list[list[int]] = []
        self.masks: list[bytes] = []
        self._mask_idx: dict[bytes, int] = {}
        self.max_group = 0
        self.n_loops = 0  # loop-mark slots, allocated from MAX_SLOTS down
        # False: lower single-class repeats as unrolled SPLIT chains
        # instead of counted OP_REPG/OP_REPL — the NFA existence scan
        # (native sw_crex_exists) cannot simulate counters
        self.counted_reps = counted_reps

    def loop_slot(self) -> int:
        self.n_loops += 1
        slot = MAX_SLOTS - self.n_loops
        # group-pair slots grow from 0, loop marks from the top —
        # overlap is checked at finalize (compile_crex)
        return slot

    def emit(self, op: int, a: int = 0, b: int = 0, c: int = 0) -> int:
        if len(self.instrs) >= MAX_PROG:
            raise _Unsupported("program too large")
        self.instrs.append([op, a, b, c])
        return len(self.instrs) - 1

    def mask_id(self, mask: np.ndarray) -> int:
        key = np.packbits(mask, bitorder="little").tobytes()
        idx = self._mask_idx.get(key)
        if idx is None:
            idx = self._mask_idx[key] = len(self.masks)
            self.masks.append(key)
        return idx

    # ---- tree walk ----

    def compile_seq(self, seq, ci: bool, dotall: bool, multiline: bool):
        for op, arg in seq:
            name = str(op)
            if name == "LITERAL":
                if arg > 255:
                    _guard_ci_fold(arg, ci, "literal")
                    # cannot occur in latin-1 text; the whole pattern
                    # can never match — emit an impossible class
                    self.emit(OP_CLASS, self.mask_id(np.zeros(256, bool)))
                elif ci:
                    m = np.zeros(256, dtype=bool)
                    m[arg] = True
                    self.emit(OP_CLASS, self.mask_id(_case_fold(m)))
                else:
                    self.emit(OP_CHAR, arg)
            elif name == "NOT_LITERAL":
                _guard_ci_fold(arg, ci, "not-literal")
                m = np.zeros(256, dtype=bool)
                if 0 <= arg <= 255:
                    m[arg] = True
                if ci:
                    m = _case_fold(m)
                self.emit(OP_CLASS, self.mask_id(~m))
            elif name == "IN":
                _guard_ci_nonlatin(arg, ci)
                self.emit(OP_CLASS, self.mask_id(_class_mask(arg, ci)))
            elif name == "ANY":
                self.emit(OP_CLASS, self.mask_id(_DOTALL if dotall else _DOT))
            elif name == "SUBPATTERN":
                gid, add_f, del_f, sub = arg
                if add_f & re.ASCII:
                    raise _Unsupported("(?a:) scope")
                sub_ci = (ci or bool(add_f & re.IGNORECASE)) and not bool(
                    del_f & re.IGNORECASE
                )
                sub_dotall = (dotall or bool(add_f & re.DOTALL)) and not bool(
                    del_f & re.DOTALL
                )
                sub_ml = (multiline or bool(add_f & re.MULTILINE)) and not bool(
                    del_f & re.MULTILINE
                )
                if gid is not None:
                    if gid > MAX_GROUP:
                        raise _Unsupported("too many groups")
                    self.max_group = max(self.max_group, gid)
                    self.emit(OP_SAVE, 2 * gid)
                self.compile_seq(sub, sub_ci, sub_dotall, sub_ml)
                if gid is not None:
                    self.emit(OP_SAVE, 2 * gid + 1)
            elif name == "BRANCH":
                branches = arg[1]
                jmps = []
                for i, br in enumerate(branches):
                    if i < len(branches) - 1:
                        sp = self.emit(OP_SPLIT)
                    else:
                        sp = None
                    start = len(self.instrs)
                    self.compile_seq(br, ci, dotall, multiline)
                    if i < len(branches) - 1:
                        jmps.append(self.emit(OP_JMP))
                        self.instrs[sp][1] = start
                        self.instrs[sp][2] = len(self.instrs)
                after = len(self.instrs)
                for j in jmps:
                    self.instrs[j][1] = after
            elif name in ("MAX_REPEAT", "MIN_REPEAT"):
                lo, hi, sub = arg
                if hi >= _MAXREPEAT:
                    hi = -1  # unbounded
                self.compile_repeat(
                    lo, hi, sub, name == "MIN_REPEAT", ci, dotall, multiline
                )
            elif name == "AT":
                at = str(arg).rsplit(".", 1)[-1]
                wb = self.mask_id(_category_mask("CATEGORY_WORD"))
                if at in ("AT_BEGINNING",):
                    self.emit(OP_AT, AT_BOL if multiline else AT_BOS)
                elif at == "AT_BEGINNING_STRING":
                    self.emit(OP_AT, AT_BOS)
                elif at == "AT_END":
                    self.emit(OP_AT, AT_EOL if multiline else AT_EOD)
                elif at == "AT_END_STRING":
                    self.emit(OP_AT, AT_EOS)
                elif at == "AT_BOUNDARY":
                    self.emit(OP_AT, AT_WB, wb)
                elif at == "AT_NON_BOUNDARY":
                    self.emit(OP_AT, AT_NWB, wb)
                else:
                    raise _Unsupported(f"anchor {at}")
            else:
                # GROUPREF / ASSERT / ASSERT_NOT / GROUPREF_EXISTS /
                # ATOMIC_GROUP / POSSESSIVE repeats / ...
                raise _Unsupported(f"op {name}")

    def _single_class(self, sub, ci: bool, dotall: bool):
        """The class mask when ``sub`` is one single-byte item, else
        None (drives the counted-REP fast instruction). Raises
        _Unsupported for the same (?i) non-latin-1 fold cases
        compile_seq rejects."""
        if len(sub) != 1:
            return None
        op, arg = sub[0]
        name = str(op)
        if name == "LITERAL":
            if arg > 255:
                _guard_ci_fold(arg, ci, "literal")
                return np.zeros(256, dtype=bool)
            m = np.zeros(256, dtype=bool)
            m[arg] = True
            return _case_fold(m) if ci else m
        if name == "NOT_LITERAL":
            _guard_ci_fold(arg, ci, "not-literal")
            m = np.zeros(256, dtype=bool)
            if 0 <= arg <= 255:
                m[arg] = True
            if ci:
                m = _case_fold(m)
            return ~m
        if name == "IN":
            _guard_ci_nonlatin(arg, ci)
            return _class_mask(arg, ci)
        if name == "ANY":
            return _DOTALL if dotall else _DOT
        return None

    def compile_repeat(self, lo, hi, sub, lazy, ci, dotall, multiline):
        if lo > _INT32_MAX or hi > _INT32_MAX:
            # re accepts counts up to 2**32-2; they don't fit the
            # int32 instruction fields (and an a{3000000000} unroll
            # would be absurd anyway) — stay on Python re
            raise _Unsupported("repeat bound exceeds int32")
        mask = self._single_class(sub, ci, dotall)
        if mask is not None and self.counted_reps:
            self.emit(OP_REPL if lazy else OP_REPG,
                      self.mask_id(mask), lo, hi)
            return
        # general body. Bounded repeats with empty-matchable bodies
        # unroll to finite SPLIT chains — Python verifiably runs
        # trailing empty iterations there (((a)|){2} on "a" leaves
        # group 1 at the empty (1,1)), exactly what the preference
        # encoding produces. Unbounded ones additionally need Python's
        # empty-iteration break rule: a mark slot records each
        # iteration's entry position and OP_LOOP exits when the body
        # consumed nothing (else the SPLIT loop would spin forever).
        for _ in range(lo):
            self.compile_seq(sub, ci, dotall, multiline)
        if hi < 0:
            mark = self.loop_slot() if _can_empty(sub) else None
            l0 = len(self.instrs)
            sp = self.emit(OP_SPLIT)
            if mark is not None:
                self.emit(OP_SAVE, mark)
            self.compile_seq(sub, ci, dotall, multiline)
            if mark is not None:
                self.emit(OP_LOOP, l0, mark)
            else:
                self.emit(OP_JMP, l0)
            after = len(self.instrs)
            if lazy:
                self.instrs[sp][1], self.instrs[sp][2] = after, sp + 1
            else:
                self.instrs[sp][1], self.instrs[sp][2] = sp + 1, after
        else:
            # optional copies carry the same zero-width protection as
            # CPython's >=min repeat phase: an optional copy that
            # consumed nothing skips the REMAINING copies (but itself
            # counts — ((a)|){2} on "a" keeps the trailing empty
            # iteration; (?:(?:a|)(?:|b)){0,2} on "ba" must not let an
            # empty copy 1 spawn a copy 2). Mandatory (count < min)
            # copies are unprotected, as in CPython.
            mark = self.loop_slot() if _can_empty(sub) else None
            splits = []
            skip_jmps = []
            for _ in range(hi - lo):
                splits.append(self.emit(OP_SPLIT))
                if mark is not None:
                    self.emit(OP_SAVE, mark)
                self.compile_seq(sub, ci, dotall, multiline)
                if mark is not None:
                    lp = self.emit(OP_LOOP, 0, mark)
                    skip_jmps.append(self.emit(OP_JMP))  # empty: done
                    # progress: continue at the next copy (== `after`
                    # for the final copy, by construction)
                    self.instrs[lp][1] = len(self.instrs)
            after = len(self.instrs)
            for sp in splits:
                if lazy:
                    self.instrs[sp][1], self.instrs[sp][2] = after, sp + 1
                else:
                    self.instrs[sp][1], self.instrs[sp][2] = sp + 1, after
            for j in skip_jmps:
                self.instrs[j][1] = after


def _guard_ci_nonlatin(items, ci: bool) -> None:
    """Reject class items that (?i)-fold non-latin-1 chars into the
    byte domain: ``(?i)[\\u212a]`` matches ``k`` and a range spanning
    past 0xFF can contain such members ((?i)[\\u2100-\\u2200] matches
    ``k`` under re, large or small) — ``_class_mask`` clamps them
    away, so these patterns must stay on exact Python ``re``. Members
    outside ``CI_LATIN1_FOLDERS`` can never match latin-1 text and
    the clamp is exact for them."""
    if not ci:
        return
    for op, arg in items:
        name = str(op)
        if name == "LITERAL" and arg in CI_LATIN1_FOLDERS:
            raise _Unsupported("latin-1-folding class literal under (?i)")
        if name == "RANGE" and arg[1] > 255 and any(
            arg[0] <= d <= arg[1] for d in CI_LATIN1_FOLDERS
        ):
            raise _Unsupported("latin-1-folding class range under (?i)")


def _can_empty(seq) -> bool:
    """Whether ``seq`` can match the empty string (conservative: any
    unknown construct counts as maybe-empty)."""
    for op, arg in seq:
        name = str(op)
        if name in ("LITERAL", "NOT_LITERAL", "IN", "ANY"):
            return False  # consumes a byte: the sequence can't be empty
        if name == "AT":
            continue
        if name in ("MAX_REPEAT", "MIN_REPEAT"):
            lo, _hi, sub = arg
            if lo > 0 and not _can_empty(sub):
                return False
            continue
        if name == "SUBPATTERN":
            _g, _af, _df, sub = arg
            if not _can_empty(sub):
                return False
            continue
        if name == "BRANCH":
            if not any(_can_empty(b) for b in arg[1]):
                return False
            continue
        return True  # unknown: assume it may be empty
    return True


_COMPILE_CACHE: dict = {}
_CACHE_MAX = 16384


def compile_crex(pattern: str) -> Optional[CrexProgram]:
    """Pattern -> native VM program, or None when out of subset."""
    hit = _COMPILE_CACHE.get(pattern)
    if hit is not None or pattern in _COMPILE_CACHE:
        return hit
    out = _compile(pattern)
    if len(_COMPILE_CACHE) < _CACHE_MAX:
        _COMPILE_CACHE[pattern] = out
    return out


def compile_crex_nfa(pattern: str) -> Optional[CrexProgram]:
    """Pattern -> counter-free program for the linear-time NFA
    existence scan (native sw_crex_exists): single-class repeats
    unroll like general bodies instead of emitting counted OP_REP
    instructions. Oversized unrolls (huge {m,n}) fall out via
    MAX_PROG -> None, and the caller stays on the backtracking /
    Python-re paths."""
    hit = _NFA_CACHE.get(pattern)
    if hit is not None or pattern in _NFA_CACHE:
        return hit
    out = _compile(pattern, counted_reps=False)
    if len(_NFA_CACHE) < _CACHE_MAX:
        _NFA_CACHE[pattern] = out
    return out


_NFA_CACHE: dict = {}


def _compile(
    pattern: str, counted_reps: bool = True
) -> Optional[CrexProgram]:
    try:
        tree = parse_quiet(pattern)
    except re.error:
        return None
    flags = tree.state.flags
    if flags & (re.ASCII | re.LOCALE):
        return None  # mask semantics are Unicode-for-latin-1 only
    ci = bool(flags & re.IGNORECASE)
    dotall = bool(flags & re.DOTALL)
    multiline = bool(flags & re.MULTILINE)
    c = _Compiler(counted_reps=counted_reps)
    try:
        c.compile_seq(list(tree), ci, dotall, multiline)
        c.emit(OP_MATCH)
    except _Unsupported:
        return None
    except re.error:
        return None
    group_slots = 2 * (c.max_group + 1)
    if group_slots > MAX_SLOTS - c.n_loops:
        return None  # group pairs and loop marks would collide
    try:
        prog = np.array(c.instrs, dtype=np.int32).reshape(-1, 4)
    except OverflowError:
        # belt for any count that escaped into an int32 field (the
        # compile_repeat bound guard is the primary defense)
        return None
    masks = (
        np.frombuffer(b"".join(c.masks), dtype=np.uint8).reshape(-1, 32)
        if c.masks
        else np.zeros((1, 32), dtype=np.uint8)
    )
    groups = {g: True for g in range(1, c.max_group + 1)}
    return CrexProgram(
        prog=np.ascontiguousarray(prog),
        masks=np.ascontiguousarray(masks),
        n_saves=MAX_SLOTS if c.n_loops else group_slots,
        group_exists=groups,
    )


__all__ = ["compile_crex", "compile_crex_nfa", "CrexProgram", "MAX_PROG"]
