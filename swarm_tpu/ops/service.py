"""Service/version classification — the ``nmap -sV`` capability.

Reference parity target: the nmap module (``-sV --top-ports 1000``,
`/root/reference/worker/modules/nmap.json`) whose matching brain is the
nmap-service-probes DB. Here every match directive lowers into the same
device match infrastructure the template corpus uses (regex → required
literal → word table, ``fingerprints/compile.py``): the TPU prefilters
(row, match) candidate pairs over the whole banner batch, then the host
confirms only the candidates with the real regex to bind capture groups
for version extraction. First hard match in DB order wins; softmatches
name the service when nothing hard fires (nmap semantics).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from swarm_tpu.fingerprints.model import Matcher, Operation, Response, Template
from swarm_tpu.fingerprints.nmap_probes import (
    ServiceMatch,
    ServiceProbe,
    load_probes,
    substitute_version,
)


@dataclasses.dataclass
class ServiceInfo:
    host: str
    port: int
    open: bool = False
    service: Optional[str] = None
    product: Optional[str] = None
    version: Optional[str] = None
    info: Optional[str] = None
    cpe: list[str] = dataclasses.field(default_factory=list)
    soft: bool = False  # only a softmatch fired

    def line(self) -> str:
        """One output line: host:port state service product version."""
        state = "open" if self.open else "closed"
        fields = [f"{self.host}:{self.port}", state, self.service or "unknown"]
        desc = " ".join(x for x in (self.product, self.version) if x)
        if desc:
            fields.append(desc)
        if self.info:
            fields.append(f"({self.info})")
        return "\t".join(fields)


def _inline_flags(m: ServiceMatch) -> str:
    """Fold the directive's s/i flags into the pattern so every regex
    engine downstream (device required-literal lowering, CPU oracle,
    host confirm) sees identical semantics."""
    prefix = ""
    if "s" in m.flags:
        prefix += "(?s)"
    if "i" in m.flags:
        prefix += "(?i)"
    return prefix + m.pattern


class ServiceClassifier:
    """Compiled probes DB + the batched classify path."""

    def __init__(
        self,
        probes: Optional[list[ServiceProbe]] = None,
        db_path: Optional[str] = None,
        **engine_kwargs,
    ):
        file_backed = probes is None  # cacheable: identity = the DB file
        if probes is None:
            probes, self.skipped_matches = load_probes(db_path)
        else:
            self.skipped_matches = 0
        self.probes = probes
        self.probe_by_name = {p.name: p for p in probes}

        # Flatten matches in DB order; each becomes one network template
        # whose single regex matcher runs over the banner stream.
        self._matches: list[tuple[str, ServiceMatch]] = []  # (probe_name, match)
        templates = []
        for probe in probes:
            for match in probe.matches:
                tid = f"svc/{probe.name}/{len(self._matches)}"
                self._matches.append((probe.name, match))
                templates.append(
                    Template(
                        id=tid,
                        protocol="network",
                        operations=[
                            Operation(
                                matchers=[
                                    Matcher(
                                        type="regex",
                                        part="body",
                                        regex=[_inline_flags(match)],
                                    )
                                ]
                            )
                        ],
                    )
                )
        from swarm_tpu.ops.engine import MatchEngine  # deferred: heavy import

        # bound the compile: 12k signatures cost ~18 s of lowering cold
        # (the production-scale DB) — key the CompiledDB on the match
        # population (post-inlining, so a flag-folding change can never
        # serve stale lowerings) and serve it from the disk cache warm.
        # Only file-backed DBs cache: the tag is the DB file's identity
        # so distinct DBs (bundled vs production) keep separate entries
        # instead of evicting each other.
        if "db" not in engine_kwargs and file_backed:
            from swarm_tpu.fingerprints.compile import compile_corpus
            from swarm_tpu.fingerprints.dbcache import (
                load_or_compile_keyed,
                path_tag,
            )

            key = "\x00".join(
                f"{p}|{m.service}|{int(m.soft)}|{_inline_flags(m)}"
                for p, m in self._matches
            ).encode("utf-8", "surrogateescape")
            tag = "svcdb-" + (path_tag(db_path) if db_path else "builtin")
            engine_kwargs["db"] = load_or_compile_keyed(
                tag, key, lambda: compile_corpus(templates)
            )
        self.engine = MatchEngine(templates, **engine_kwargs)
        self._compiled = [m.compile() for _probe, m in self._matches]
        self._by_probe: dict[str, list[int]] = {}
        for idx, (probe_name, _m) in enumerate(self._matches):
            self._by_probe.setdefault(probe_name, []).append(idx)
        self._port_probe_cache: dict[int, ServiceProbe] = {}
        # (banner, sent_probe) -> classified service fields; bounded
        self._classify_memo: dict = {}

    # ------------------------------------------------------------------
    def _probe_order(self, sent_probe: Optional[str]) -> Optional[list[str]]:
        """Probes whose matches apply to a response elicited by
        ``sent_probe``, in nmap evaluation order: the sent probe's own
        matches first, then its declared fallbacks, then NULL."""
        if sent_probe is None:
            return None  # no probe bookkeeping: every match applies
        order = [sent_probe]
        probe = self.probe_by_name.get(sent_probe)
        if probe:
            order.extend(f for f in probe.fallback if f not in order)
        if "NULL" not in order:
            order.append("NULL")
        return order

    def classify(
        self,
        rows: Sequence[Response],
        sent_probes: Optional[Sequence[Optional[str]]] = None,
    ) -> list[ServiceInfo]:
        results = self.engine.match(rows)
        out: list[ServiceInfo] = []
        for i, (row, hits) in enumerate(zip(rows, results)):
            info = ServiceInfo(host=row.host, port=row.port, open=row.alive)
            banner = row.part("body")
            if not row.alive or not banner:
                out.append(info)
                continue
            # fleet banners repeat heavily (every OpenSSH 8.9 host says
            # the same bytes): the whole verify/veto walk below is a
            # pure function of (banner, sent probe), so memo its
            # service fields across rows and batches
            sent = sent_probes[i] if sent_probes else None
            mkey = (banner, sent)
            memo = self._classify_memo.get(mkey)
            if memo is not None:
                (
                    info.service, info.product, info.version,
                    info.info, cpe, info.soft,
                ) = memo
                info.cpe = list(cpe)  # callers may mutate their copy
                out.append(info)
                continue
            cand = {
                int(tid.rsplit("/", 1)[1])
                for tid in hits.template_ids
                if tid.startswith("svc/")
            }
            probe_order = self._probe_order(sent)
            if probe_order is None:
                ordered = sorted(cand)
            else:
                ordered = [
                    idx
                    for pname in probe_order
                    for idx in self._by_probe.get(pname, [])
                    if idx in cand
                ]
            soft_hit: Optional[ServiceMatch] = None
            hard_done = False
            for idx in ordered:
                _probe_name, match = self._matches[idx]
                pattern = self._compiled[idx]
                mo = pattern.search(banner) if pattern else None
                if not mo:
                    continue  # device prefilter is a superset; host veto
                if match.soft:
                    soft_hit = soft_hit or match
                    continue
                # after a softmatch names a service, only hard matches for
                # that same service may win (nmap softmatch semantics)
                if soft_hit is not None and match.service != soft_hit.service:
                    continue
                info.service = match.service
                info.product = substitute_version(match.product, mo)
                info.version = substitute_version(match.version, mo)
                info.info = substitute_version(match.info, mo)
                info.cpe = [substitute_version(c, mo) for c in match.cpe]
                hard_done = True
                break
            if not hard_done and soft_hit:
                info.service = soft_hit.service
                info.soft = True
            # tuple-copy cpe: the caller owns (and may mutate) its list.
            # Bounding shares the engine's memo policy (_cache_put).
            self.engine._cache_put(
                self._classify_memo,
                mkey,
                (
                    info.service, info.product, info.version,
                    info.info, tuple(info.cpe), info.soft,
                ),
            )
            out.append(info)
        return out

    # ------------------------------------------------------------------
    def probe_for_port(self, port: int) -> ServiceProbe:
        """Payload selection: lowest-rarity TCP probe with a payload
        covering the port; NULL (listen-only) otherwise. Memoized —
        service scans call this per (host, port) on the probing hot
        path."""
        cached = self._port_probe_cache.get(port)
        if cached is not None:
            return cached
        best: Optional[ServiceProbe] = None
        for probe in self.probes:
            if probe.proto != "TCP" or not probe.payload:
                continue
            if probe.covers_port(port) and (best is None or probe.rarity < best.rarity):
                best = probe
        if best is None:
            best = self.probe_by_name.get("NULL") or ServiceProbe(
                proto="TCP", name="NULL"
            )
        self._port_probe_cache[port] = best
        return best

    def default_payload_probe(self) -> Optional[ServiceProbe]:
        """Second-round probe for silent-but-open ports: the lowest-
        rarity TCP payload probe regardless of port coverage (nmap keeps
        escalating probes by rarity when the NULL listen stays quiet)."""
        best: Optional[ServiceProbe] = None
        for probe in self.probes:
            if probe.proto != "TCP" or not probe.payload:
                continue
            if best is None or probe.rarity < best.rarity:
                best = probe
        return best
